// Native dataset loaders — CSV tabular and MNIST-idx binary.
//
// Capability parity: the reference's C++ on-device DataLoaders
// (android/fedmlsdk/MobileNN/src/MNN/{mnist,cifar10,tabular}.cpp and
// src/torch/{mnist,cifar10}.cpp) that feed the native trainer without any
// Python in the loop.  Formats:
//  * CSV: one sample per line, features then integer label last; '#' lines
//    and blanks skipped.  Non-numeric cells are a hard error (code 4).
//  * idx: the MNIST big-endian idx3 (images, normalized to [0,1]) and idx1
//    (labels) pair.  Short reads are a hard error (code 5).
// Query-then-fill C API: call with null outputs to get n/d; the fill call
// takes the CALLER's capacity and never writes past it (the file may have
// grown between the two calls).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

extern "C" {

// Returns 0 on success. If out_x == null, only *out_n / *out_d are set
// (capacity ignored).  Fill pass writes at most `capacity` rows.
// Errors: 1 open, 2 ragged row, 4 unparseable cell.
int ft_load_csv(const char* path, int64_t* out_n, int64_t* out_d,
                float* out_x, int32_t* out_y, int64_t capacity) {
  std::ifstream f(path);
  if (!f.is_open()) return 1;
  std::string line;
  int64_t n = 0, d = -1;
  while (std::getline(f, line)) {
    if (line.empty() || line[0] == '#') continue;
    std::stringstream ss(line);
    std::string cell;
    std::vector<float> row;
    while (std::getline(ss, cell, ',')) {
      const char* s = cell.c_str();
      char* end = nullptr;
      float v = std::strtof(s, &end);
      while (end != nullptr && (*end == ' ' || *end == '\r')) ++end;
      if (end == s || (end != nullptr && *end != '\0')) return 4;
      row.push_back(v);
    }
    if (row.size() < 2) continue;
    if (d < 0) d = static_cast<int64_t>(row.size()) - 1;
    if (static_cast<int64_t>(row.size()) != d + 1) return 2;  // ragged
    if (out_x != nullptr) {
      if (n >= capacity) break;  // file grew since the size pass
      std::memcpy(out_x + n * d, row.data(), d * sizeof(float));
      out_y[n] = static_cast<int32_t>(row.back());
    }
    ++n;
  }
  *out_n = n;
  *out_d = d < 0 ? 0 : d;
  return 0;
}

static uint32_t read_be32(std::ifstream& f) {
  unsigned char b[4] = {0, 0, 0, 0};
  f.read(reinterpret_cast<char*>(b), 4);
  return (uint32_t(b[0]) << 24) | (uint32_t(b[1]) << 16) |
         (uint32_t(b[2]) << 8) | uint32_t(b[3]);
}

// MNIST idx3 (images) + idx1 (labels). Pixels normalized to [0,1].
// Errors: 1 open, 2 bad magic, 3 count mismatch, 5 truncated data.
int ft_load_idx(const char* images_path, const char* labels_path,
                int64_t* out_n, int64_t* out_d, float* out_x,
                int32_t* out_y, int64_t capacity) {
  std::ifstream fi(images_path, std::ios::binary);
  std::ifstream fl(labels_path, std::ios::binary);
  if (!fi.is_open() || !fl.is_open()) return 1;
  if (read_be32(fi) != 0x00000803u) return 2;  // idx3 magic
  if (read_be32(fl) != 0x00000801u) return 2;  // idx1 magic
  const int64_t n = read_be32(fi);
  const int64_t rows = read_be32(fi), cols = read_be32(fi);
  if (static_cast<int64_t>(read_be32(fl)) != n) return 3;
  if (fi.fail() || fl.fail()) return 5;
  *out_n = n;
  *out_d = rows * cols;
  if (out_x == nullptr) return 0;
  const int64_t n_fill = n < capacity ? n : capacity;
  std::vector<unsigned char> buf(static_cast<size_t>(rows * cols));
  for (int64_t i = 0; i < n_fill; ++i) {
    fi.read(reinterpret_cast<char*>(buf.data()), rows * cols);
    unsigned char y;
    fl.read(reinterpret_cast<char*>(&y), 1);
    if (fi.fail() || fl.fail()) return 5;  // truncated mid-data
    for (int64_t j = 0; j < rows * cols; ++j)
      out_x[i * rows * cols + j] = buf[j] / 255.0f;
    out_y[i] = y;
  }
  return 0;
}

}  // extern "C"
