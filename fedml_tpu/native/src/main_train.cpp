// CLI test main for the native trainer + secagg codec.
#include <cstring>
//
// Capability parity: the reference's on-host test mains
// (android/fedmlsdk/MobileNN/src/main_MNN_train.cpp, main_torch_train.cpp,
// main_FedMLClientManager.cpp).  Trains the native classifier on a
// procedurally generated dataset and round-trips a LightSecAgg mask.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <vector>

extern "C" {
typedef void (*ft_progress_cb)(int epoch, float loss, float acc);
float ft_train_classifier(const float*, const int32_t*, int64_t, int64_t,
                          int64_t, int64_t, float*, float*, float*, float*,
                          int64_t, int64_t, float, float, uint64_t,
                          ft_progress_cb);
float ft_eval_classifier(const float*, const int32_t*, int64_t, int64_t,
                         int64_t, int64_t, const float*, const float*,
                         const float*, const float*, float*);
void ft_mask_encode(const int64_t*, int64_t, int64_t, int64_t, int64_t,
                    uint64_t, int64_t*, int64_t*);
void ft_aggregate_shares(const int64_t*, int64_t, int64_t, int64_t*);
void ft_decode_aggregate_mask(const int64_t*, const int64_t*, int64_t,
                              int64_t, int64_t, int64_t, int64_t, int64_t*);
}

static void progress(int epoch, float loss, float acc) {
  std::printf("epoch %d: loss=%.4f acc=%.4f\n", epoch, loss, acc);
}

int main() {
  // synthetic linearly separable data, 3 classes, 20 features
  const int64_t n = 600, d = 20, classes = 3;
  std::mt19937_64 rng(0);
  std::normal_distribution<float> g(0.f, 1.f);
  std::vector<float> W(d * classes);
  for (auto& w : W) w = g(rng);
  std::vector<float> x(n * d);
  std::vector<int32_t> y(n);
  for (int64_t i = 0; i < n; ++i) {
    for (int64_t k = 0; k < d; ++k) x[i * d + k] = g(rng);
    float best = -1e30f;
    for (int64_t c = 0; c < classes; ++c) {
      float acc = 0.f;
      for (int64_t k = 0; k < d; ++k) acc += x[i * d + k] * W[k * classes + c];
      if (acc > best) { best = acc; y[i] = static_cast<int32_t>(c); }
    }
  }
  std::vector<float> w2(d * classes, 0.f), b2(classes, 0.f);
  ft_train_classifier(x.data(), y.data(), n, d, classes, /*hidden=*/0,
                      nullptr, nullptr, w2.data(), b2.data(),
                      /*epochs=*/5, /*batch=*/32, /*lr=*/0.1f,
                      /*momentum=*/0.9f, /*seed=*/1, progress);
  float loss = 0.f;
  float acc = ft_eval_classifier(x.data(), y.data(), n, d, classes, 0,
                                 nullptr, nullptr, w2.data(), b2.data(),
                                 &loss);
  std::printf("final: acc=%.4f loss=%.4f\n", acc, loss);
  if (acc < 0.8f) { std::printf("FAIL trainer\n"); return 1; }

  // LightSecAgg round trip: 3 clients, u=2, t=1, one dropout
  const int64_t dd = 17, nn = 3, u = 2, t = 1;
  std::vector<int64_t> masks(nn * dd);
  std::mt19937_64 r2(7);
  for (auto& m : masks) m = static_cast<int64_t>(r2() % 65536);
  int64_t blk = 0;
  std::vector<int64_t> shares(nn * nn * ((dd + (u - t) - 1) / (u - t)));
  for (int64_t i = 0; i < nn; ++i)
    ft_mask_encode(masks.data() + i * dd, dd, nn, u, t, 100 + i,
                   shares.data() + i * nn * ((dd + (u - t) - 1) / (u - t)),
                   &blk);
  // survivors {0, 2}; each sums the shares it holds from survivors
  int64_t surv[2] = {0, 2};
  std::vector<int64_t> agg(2 * blk);
  for (int64_t s = 0; s < 2; ++s) {
    std::vector<int64_t> held(2 * blk);
    for (int64_t i = 0; i < 2; ++i)
      std::memcpy(held.data() + i * blk,
                  shares.data() + surv[i] * nn * blk + surv[s] * blk,
                  blk * sizeof(int64_t));
    ft_aggregate_shares(held.data(), 2, blk, agg.data() + s * blk);
  }
  std::vector<int64_t> decoded(dd);
  ft_decode_aggregate_mask(agg.data(), surv, 2, dd, u, t, blk,
                           decoded.data());
  for (int64_t i = 0; i < dd; ++i) {
    int64_t expect = (masks[0 * dd + i] + masks[2 * dd + i]) % ((1LL << 31) - 1);
    if (decoded[i] != expect) { std::printf("FAIL secagg @%lld\n",
                                            static_cast<long long>(i));
      return 1; }
  }
  std::printf("secagg round-trip OK\n");
  return 0;
}
