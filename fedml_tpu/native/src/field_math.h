// Finite-field math over p = 2^31 - 1 for the native secure-aggregation
// codec.  C++ counterpart of fedml_tpu/core/mpc/secagg.py (host reference:
// the Android MobileNN C++ LightSecAgg, android/fedmlsdk/MobileNN/src/
// security/LightSecAgg.cpp — reimplemented from the protocol, not ported).
#pragma once

#include <cstdint>
#include <vector>

namespace fedml_native {

constexpr int64_t kFieldPrime = (1LL << 31) - 1;

inline int64_t mod_p(int64_t a) {
  int64_t r = a % kFieldPrime;
  return r < 0 ? r + kFieldPrime : r;
}

inline int64_t mul_mod(int64_t a, int64_t b) {
  // |a|,|b| < 2^31 so the product fits in int64 exactly.
  return mod_p(mod_p(a) * mod_p(b));
}

int64_t pow_mod(int64_t a, int64_t e);
int64_t modular_inv(int64_t a);

// U[i*n_interp + j] = l_j(eval[i]) with nodes interp[].
std::vector<int64_t> lagrange_basis(const std::vector<int64_t>& eval_pts,
                                    const std::vector<int64_t>& interp_pts);

}  // namespace fedml_native
