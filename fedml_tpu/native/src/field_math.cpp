#include "field_math.h"
#include <cstddef>

namespace fedml_native {

int64_t pow_mod(int64_t a, int64_t e) {
  int64_t result = 1;
  a = mod_p(a);
  while (e > 0) {
    if (e & 1) result = mul_mod(result, a);
    a = mul_mod(a, a);
    e >>= 1;
  }
  return result;
}

int64_t modular_inv(int64_t a) { return pow_mod(a, kFieldPrime - 2); }

std::vector<int64_t> lagrange_basis(const std::vector<int64_t>& eval_pts,
                                    const std::vector<int64_t>& interp_pts) {
  const size_t ne = eval_pts.size(), ni = interp_pts.size();
  std::vector<int64_t> U(ne * ni);
  for (size_t j = 0; j < ni; ++j) {
    int64_t den = 1;
    for (size_t k = 0; k < ni; ++k) {
      if (k == j) continue;
      den = mul_mod(den, mod_p(interp_pts[j] - interp_pts[k]));
    }
    const int64_t inv_den = modular_inv(den);
    for (size_t i = 0; i < ne; ++i) {
      int64_t num = 1;
      for (size_t k = 0; k < ni; ++k) {
        if (k == j) continue;
        num = mul_mod(num, mod_p(eval_pts[i] - interp_pts[k]));
      }
      U[i * ni + j] = mul_mod(num, inv_den);
    }
  }
  return U;
}

}  // namespace fedml_native
