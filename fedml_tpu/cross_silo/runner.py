"""Cross-silo runners.

Capability parity: reference `cross_silo/fedml_client.py` / `fedml_server.py`
+ `server_initializer.py`: build the (Server|Client)Manager pair for the
configured role; optimizer dispatch FedAvg (default) / "SA" / "LSA".

Adds the capability the reference lacks (SURVEY §4): a LOCAL FEDERATION mode
— when backend=INPROC and role="simulated", the runner spins server + N
clients on threads over the in-process hub, so the full message protocol runs
in one process (used by tests and single-host runs).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Tuple

from ..constants import FED_OPT_LIGHTSECAGG, FED_OPT_SECAGG
from ..ml.trainer.default_trainer import DefaultServerAggregator
from .client.fedml_client_master_manager import ClientMasterManager
from .client.trainer_dist_adapter import TrainerDistAdapter
from .server.fedml_aggregator import FedMLAggregator
from .server.fedml_server_manager import FedMLServerManager, fleet_size


def init_server(args: Any, dataset: Tuple, bundle: Any,
                server_aggregator: Optional[Any] = None,
                backend: str = "INPROC") -> FedMLServerManager:
    import jax

    if server_aggregator is None and bool(getattr(args, "fed_llm", False)):
        # fed-LLM plane: the global model IS the LoRA adapter tree.  The
        # aggregator pre-sets adapter-shaped params, so the None-param
        # full-model auto-init below never fires for it.
        from ..train.fed_llm import FedLLMAggregator
        server_aggregator = FedLLMAggregator(bundle, args)
    aggregator_impl = server_aggregator or DefaultServerAggregator(bundle, args)
    if aggregator_impl.get_model_params() is None:
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0) or 0))
        aggregator_impl.set_model_params(bundle.init_variables(rng))
    test_global = dataset[3]
    agg = FedMLAggregator(args, aggregator_impl, test_global)
    client_num = fleet_size(args)
    opt = str(getattr(args, "federated_optimizer", "FedAvg"))
    async_agg = bool(getattr(args, "async_agg", False))
    if opt in (FED_OPT_LIGHTSECAGG, FED_OPT_SECAGG) and async_agg:
        # secure aggregation's masking/reconstruction stages are sync
        # barriers by construction — folding updates one at a time would
        # sum partial mask sets (garbage after unmasking)
        raise ValueError(
            f"async_agg is incompatible with federated_optimizer={opt}: "
            "secure aggregation requires synchronous rounds")
    if opt == FED_OPT_LIGHTSECAGG:
        from .lightsecagg.lsa_server_manager import LSAServerManager
        return LSAServerManager(args, agg, rank=0, client_num=client_num,
                                backend=backend)
    if opt == FED_OPT_SECAGG:
        from .secagg.sa_server_manager import SAServerManager
        return SAServerManager(args, agg, rank=0, client_num=client_num,
                               backend=backend)
    if async_agg:
        from .server.async_server_manager import AsyncFedMLServerManager
        return AsyncFedMLServerManager(args, agg, rank=0,
                                       client_num=client_num,
                                       backend=backend)
    return FedMLServerManager(args, agg, rank=0, client_num=client_num,
                              backend=backend)


def init_client(args: Any, dataset: Tuple, bundle: Any, rank: int,
                client_trainer: Optional[Any] = None,
                backend: str = "INPROC") -> ClientMasterManager:
    adapter = TrainerDistAdapter(args, bundle, dataset, client_trainer)
    size = fleet_size(args) + 1
    opt = str(getattr(args, "federated_optimizer", "FedAvg"))
    if opt == FED_OPT_LIGHTSECAGG:
        from .lightsecagg.lsa_client_manager import LSAClientManager
        return LSAClientManager(args, adapter, rank=rank, size=size,
                                backend=backend)
    if opt == FED_OPT_SECAGG:
        from .secagg.sa_client_manager import SAClientManager
        return SAClientManager(args, adapter, rank=rank, size=size,
                               backend=backend)
    return ClientMasterManager(args, adapter, rank=rank, size=size,
                               backend=backend)


class LocalFederationRunner:
    """Server + N clients over INPROC threads; returns final server metrics.

    ``client_trainer`` may be a single trainer instance (shared, the
    default-trainer case) or a CALLABLE ``rank -> trainer`` for planes that
    need one trainer per client (cross-cloud mesh slices)."""

    JOIN_TIMEOUT_S = 30.0

    def __init__(self, args: Any, device: Any, dataset: Tuple, bundle: Any,
                 client_trainer: Optional[Any] = None,
                 server_aggregator: Optional[Any] = None) -> None:
        self.args = args
        self.dataset = dataset
        self.bundle = bundle
        self.client_trainer = client_trainer
        self.server_aggregator = server_aggregator

    def _trainer_for(self, rank: int):
        if callable(self.client_trainer) and not hasattr(
                self.client_trainer, "train"):
            return self.client_trainer(rank)
        return self.client_trainer

    def train(self):
        n = fleet_size(self.args)
        server = init_server(self.args, self.dataset, self.bundle,
                             self.server_aggregator, backend="INPROC")
        clients: List[ClientMasterManager] = [
            init_client(self.args, self.dataset, self.bundle, rank,
                        self._trainer_for(rank), backend="INPROC")
            for rank in range(1, n + 1)
        ]
        threads = [threading.Thread(target=c.run, daemon=True,
                                    name=f"client-{c.rank}") for c in clients]
        for t in threads:
            t.start()
        server.run()  # blocks until FINISH
        for t in threads:
            t.join(timeout=self.JOIN_TIMEOUT_S)
        hist = server.aggregator.metrics_history
        return hist[-1] if hist else {}


class SingleRoleRunner:
    """Run this process's role only (real deployments: one host per role)."""

    def __init__(self, args: Any, device: Any, dataset: Tuple, bundle: Any,
                 client_trainer=None, server_aggregator=None) -> None:
        self.args = args
        backend = str(getattr(args, "backend", "INPROC"))
        role = str(getattr(args, "role", "server"))
        rank = int(getattr(args, "rank", 0))
        if role == "server" or rank == 0:
            self.manager = init_server(args, dataset, bundle,
                                       server_aggregator, backend)
        else:
            self.manager = init_client(args, dataset, bundle, rank,
                                       client_trainer, backend)

    def train(self):
        self.manager.run()
        agg = getattr(self.manager, "aggregator", None)
        if agg is not None and agg.metrics_history:
            return agg.metrics_history[-1]
        return {}


def build_cross_silo_runner(args: Any, device: Any, dataset: Tuple,
                            bundle: Any, client_trainer=None,
                            server_aggregator=None):
    backend = str(getattr(args, "backend", "INPROC")).upper()
    if int(getattr(args, "hier_regions", 0) or 0) >= 2:
        # geo-distributed hierarchy: regional aggregators fold their silos
        # locally and ship one pre-reduced delta per round segment over
        # the WAN plane to the global server (per-tier fault domains)
        if backend != "INPROC":
            raise NotImplementedError(
                "hier_regions over a non-INPROC backend: launch the "
                "global/region/silo roles per host instead (see "
                "docs/ROBUSTNESS.md, Hierarchical aggregation)")
        from .hierarchical.runner import HierarchicalFederationRunner
        return HierarchicalFederationRunner(args, device, dataset, bundle,
                                            client_trainer,
                                            server_aggregator)
    if backend == "INPROC":
        # the in-process bus cannot cross OS processes, so a single-role
        # run over INPROC can never federate — it would block forever
        # waiting for peers.  INPROC therefore ALWAYS means the local
        # (simulated) federation; real deployments pick GRPC/MQTT_S3 and
        # set role/rank per host.
        return LocalFederationRunner(args, device, dataset, bundle,
                                     client_trainer, server_aggregator)
    return SingleRoleRunner(args, device, dataset, bundle, client_trainer,
                            server_aggregator)
