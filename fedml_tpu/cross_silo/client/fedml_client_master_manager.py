"""Cross-silo ClientMasterManager.

Capability parity: reference `cross_silo/client/fedml_client_master_manager.py
:22-261` — registers online status, handles INIT/SYNC/FINISH, runs local
training via TrainerDistAdapter, uploads (weights, n_samples).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

from ...core import mlops
from ...core.mlops import tracing
from ...core.distributed.communication.message import Message
from ...core.distributed.communication.reliable import ARG_VOLATILE
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...utils.compression import WIRE_BYTES as _wire_bytes
from ..message_define import MyMessage
from .trainer_dist_adapter import TrainerDistAdapter


class ClientMasterManager(FedMLCommManager):
    def __init__(self, args: Any, trainer_dist_adapter: TrainerDistAdapter,
                 comm=None, rank: int = 0, size: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, comm, rank, size, backend)
        self.trainer_dist_adapter = trainer_dist_adapter
        self.num_rounds = int(args.comm_round)
        self._compressor = None  # built lazily when enable_compression
        #: negotiated uplink wire codec: assigned by the server per link
        #: on the round broadcast (None until then / for legacy servers);
        #: one instance per assignment so the error-feedback residual
        #: persists across rounds
        self._wire_codec = None
        self._wire_codec_spec: str = ""
        self.round_idx = 0
        self._hb_stop = threading.Event()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_message_init)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
            self.handle_message_receive_model_from_server)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self.handle_message_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.send_client_status(0)
        self._start_heartbeat()
        self.com_manager.handle_receive_message()

    def finish(self) -> None:
        self._hb_stop.set()
        super().finish()

    # -- liveness ------------------------------------------------------------
    def _start_heartbeat(self) -> None:
        """Periodic heartbeat to the server's failure detector.  Volatile
        on the reliable plane: the next beat supersedes a lost one, so
        retransmitting a stale heartbeat would only add noise."""
        interval = float(getattr(self.args, "heartbeat_interval_s", 0) or 0)
        if interval <= 0:
            return

        def _loop() -> None:
            while not self._hb_stop.wait(interval):
                try:
                    msg = Message(MyMessage.MSG_TYPE_HEARTBEAT,
                                  self.get_sender_id(), 0)
                    msg.add_params(MyMessage.MSG_ARG_KEY_HEARTBEAT_TS,
                                   time.time())
                    msg.add_params(ARG_VOLATILE, True)
                    self.send_message(msg)
                except Exception:  # noqa: BLE001 — a failed beat is a
                    # missed beat, nothing to escalate from here
                    logging.debug("client %d: heartbeat send failed",
                                  self.rank, exc_info=True)

        threading.Thread(target=_loop, daemon=True,
                         name=f"heartbeat-{self.rank}").start()

    # -- protocol ------------------------------------------------------------
    def send_client_status(self, receiver_id: int,
                           status: str = MyMessage.CLIENT_STATUS_ONLINE) -> None:
        from ...utils.compression import WIRE_CAPS

        msg = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
                      self.get_sender_id(), receiver_id)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS, status)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_OS, "python")
        # capability advertisement: the server only assigns a wire codec
        # this build can actually decode/encode
        msg.add_params(MyMessage.MSG_ARG_KEY_WIRE_CAPS, list(WIRE_CAPS))
        self.send_message(msg)

    def _unpack_broadcast(self, msg: Message) -> Any:
        """Model payload → tree, honoring the server's codec assignment.
        The DECODED tree doubles as the delta reference for compressed
        uploads — identical bits to the server's copy by construction."""
        global_model = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        if msg.get(MyMessage.MSG_ARG_KEY_MODEL_ENCODED):
            from ...utils.compression import WireCodec

            global_model = WireCodec.decode_model(global_model)
        codec_spec = msg.get(MyMessage.MSG_ARG_KEY_WIRE_CODEC)
        if codec_spec and str(codec_spec) != self._wire_codec_spec:
            from ...utils.compression import WireCodec

            self._wire_codec = WireCodec(str(codec_spec))
            self._wire_codec_spec = str(codec_spec)
        elif not codec_spec:
            self._wire_codec = None
            self._wire_codec_spec = ""
        return global_model

    def handle_message_init(self, msg: Message) -> None:
        global_model = self._unpack_broadcast(msg)
        client_index = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX)
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND, 0))
        mlops.log_training_status("RUNNING")
        self._train_and_upload(
            global_model, client_index,
            tracing.extract(msg.get(MyMessage.MSG_ARG_KEY_TRACE_CTX)))

    def handle_message_receive_model_from_server(self, msg: Message) -> None:
        global_model = self._unpack_broadcast(msg)
        client_index = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX)
        self.round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND,
                                     self.round_idx + 1))
        self._train_and_upload(
            global_model, client_index,
            tracing.extract(msg.get(MyMessage.MSG_ARG_KEY_TRACE_CTX)))

    def handle_message_finish(self, msg: Message) -> None:
        logging.info("client %d: finish", self.rank)
        mlops.log_training_status("FINISHED")
        self.finish()

    def _train_and_upload(self, global_model: Any, client_index: int,
                          trace_ctx: Any = None) -> None:
        self.trainer_dist_adapter.update_dataset(int(client_index))
        self.trainer_dist_adapter.update_model(global_model)
        # attach the server's round-span context so this client's train span
        # (and everything the trainer opens inside it) joins the round trace
        with tracing.use_ctx(trace_ctx):
            with tracing.span("client.train", round=self.round_idx,
                              rank=self.rank,
                              client_index=int(client_index)):
                mlops.event("train", True, self.round_idx)
                weights, n_samples = self.trainer_dist_adapter.train(
                    self.round_idx)
                mlops.event("train", False, self.round_idx)
        if logging.getLogger().isEnabledFor(logging.DEBUG):
            # structure-only summary (shapes/dtypes/bytes, never values):
            # the sanctioned way to log a payload
            from ...utils.redact import summarize_payload

            logging.debug("client %d: round %d upload: %s", self.rank,
                          self.round_idx, summarize_payload(weights))
        msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                      self.get_sender_id(), 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.round_idx)
        if trace_ctx is not None:
            # echo the round context on the upload: the server (and any
            # relay hop) can stitch receive-side spans without local state
            msg.add_params(MyMessage.MSG_ARG_KEY_TRACE_CTX,
                           tracing.inject(trace_ctx))
        if self._wire_codec is not None:
            # negotiated wire codec: ship delta(weights, received global)
            # through quantize/sparsify with client-side error feedback;
            # the server reconstructs against its identical reference
            from ...utils.serialization import estimate_nbytes

            payload = self._wire_codec.encode_delta(weights, global_model)
            msg.add_params(MyMessage.MSG_ARG_KEY_WIRE_UPDATE, payload)
            _wire_bytes.labels(
                run_id=str(getattr(self.args, "run_id", "0")),
                direction="up", codec=self._wire_codec.spec.kind).inc(
                estimate_nbytes(payload))
        elif getattr(self.args, "enable_compression", False):
            # sparse delta upload (reference utils/compression.py TopK/EF):
            # only top-k(|Δ|) entries travel; the server reconstructs
            # weights = global + Δ against its own copy of the global model
            import jax

            if self._compressor is None:
                from ...utils.compression import (
                    EFTopKCompressor,
                    TopKCompressor,
                )

                kind = str(getattr(self.args, "compression_type",
                                   "eftopk")).lower()
                ratio = float(getattr(self.args, "compress_ratio", 0.01)
                              or 0.01)
                self._compressor = (EFTopKCompressor(ratio)
                                    if kind.startswith("ef")
                                    else TopKCompressor(ratio))
            delta = jax.tree_util.tree_map(lambda w, g: w - g, weights,
                                           global_model)
            payload, _ = self._compressor.compress(delta)
            msg.add_params(MyMessage.MSG_ARG_KEY_COMPRESSED_UPDATE, payload)
        else:
            from ...utils.serialization import estimate_nbytes

            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, weights)
            _wire_bytes.labels(
                run_id=str(getattr(self.args, "run_id", "0")),
                direction="up", codec="raw").inc(estimate_nbytes(weights))
        msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, n_samples)
        msg.add_params(MyMessage.MSG_ARG_KEY_TRAIN_METRICS,
                       getattr(self.trainer_dist_adapter.trainer,
                               "last_metrics", {}))
        self.send_message(msg)
