"""TrainerDistAdapter — silo-internal training adapter.

Capability parity: reference `cross_silo/client/fedml_trainer_dist_adapter.py`
+ `fedml_trainer.py`: device placement, hierarchical DDP wrap, delegate to the
user ClientTrainer hooks, return (weights, n_samples).

TPU redesign: "DDP across silo processes" becomes sharding the silo's batch
over the `data` mesh axis inside one jit — gradient sync is XLA's psum, not
NCCL.  In the horizontal scenario it's the plain local-update engine.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional, Tuple

from ...constants import AXIS_DATA, CROSS_SILO_SCENARIO_HIERARCHICAL
from ...ml.engine.mesh import build_mesh
from ...ml.trainer.default_trainer import DefaultClientTrainer


class TrainerDistAdapter:
    def __init__(self, args: Any, bundle: Any, dataset: Tuple,
                 client_trainer: Optional[Any] = None) -> None:
        self.args = args
        (self.train_num, self.test_num, self.train_global, self.test_global,
         self.local_num_dict, self.train_data_local_dict,
         self.test_data_local_dict, self.class_num) = dataset
        if client_trainer is None and bool(getattr(args, "fed_llm", False)):
            # fed-LLM plane: local SFT through the functional-LoRA epoch;
            # the exchanged params are the adapter tree
            from ...train.fed_llm import FedLLMTrainer
            client_trainer = FedLLMTrainer(bundle, args)
        self.trainer = client_trainer or DefaultClientTrainer(bundle, args)
        bs = int(getattr(args, "batch_size", 32))
        max_n = max(self.local_num_dict.values()) if self.local_num_dict else bs
        self.trainer.set_num_batches(max(1, -(-int(max_n) // bs)))

        self.mesh = None
        if str(getattr(args, "scenario", "horizontal")) == \
                CROSS_SILO_SCENARIO_HIERARCHICAL:
            import jax

            n_proc = min(int(getattr(args, "n_proc_per_node", 1) or 1),
                         len(jax.devices()))
            if n_proc > 1:
                self.mesh = build_mesh({AXIS_DATA: n_proc})
                logging.info("hierarchical silo: data-parallel mesh %s",
                             self.mesh)

    def update_dataset(self, client_index: int) -> None:
        self.client_index = int(client_index)
        self.trainer.set_id(self.client_index)
        self.trainer.update_dataset(
            self.train_data_local_dict[self.client_index],
            self.test_data_local_dict[self.client_index],
            self.local_num_dict[self.client_index])

    def update_model(self, model_params: Any) -> None:
        self.trainer.set_model_params(model_params)

    def train(self, round_idx: int) -> Tuple[Any, float]:
        self.trainer.on_before_local_training(
            self.trainer.local_train_dataset, None, self.args)
        ctx = self.mesh if self.mesh is not None else _Null()
        with ctx:
            self.trainer.train(self.trainer.local_train_dataset, None,
                               self.args)
        self.trainer.on_after_local_training(
            self.trainer.local_train_dataset, None, self.args)
        return (self.trainer.get_model_params(),
                float(self.trainer.local_sample_number))


class _Null:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False
