"""LightSecAgg client manager.

Capability parity: reference `cross_silo/lightsecagg/
lsa_fedml_client_manager.py`: train → generate local mask → LCC-encode and
share to peers → upload masked model → on server request, send the sum of
held shares for the surviving set.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import numpy as np

from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.mpc.lightsecagg import aggregate_encoded_masks, mask_encoding
from ...core.mpc.secagg import FIELD_PRIME
from ..client.trainer_dist_adapter import TrainerDistAdapter
from .lsa_message_define import LSAMessage
from .lsa_utils import mask_field_vector, tree_to_weighted_field_vector


class LSAClientManager(FedMLCommManager):
    def __init__(self, args: Any, trainer_dist_adapter: TrainerDistAdapter,
                 comm=None, rank: int = 0, size: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, comm, rank, size, backend)
        self.adapter = trainer_dist_adapter
        self.round_idx = 0
        self.proto: Dict[str, int] = {}
        self.received_shares: Dict[int, np.ndarray] = {}  # sender rank → share
        self._rng = np.random.RandomState(
            int(getattr(args, "random_seed", 0) or 0) * 1000 + rank)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_init)
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.handle_sync)
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_C2C_ENCODED_MASK_SHARE, self.handle_share)
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_S2C_AGG_MASK_REQUEST, self.handle_agg_request)
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        msg = Message(LSAMessage.MSG_TYPE_C2S_CLIENT_STATUS,
                      self.get_sender_id(), 0)
        msg.add_params(LSAMessage.ARG_CLIENT_STATUS,
                       LSAMessage.CLIENT_STATUS_ONLINE)
        self.send_message(msg)
        self.com_manager.handle_receive_message()

    # -- round work ----------------------------------------------------------
    def handle_init(self, msg: Message) -> None:
        self.proto = dict(msg.get(LSAMessage.ARG_PROTO))
        self._train_mask_upload(msg)

    def handle_sync(self, msg: Message) -> None:
        self.received_shares = {}
        self._train_mask_upload(msg)

    def _train_mask_upload(self, msg: Message) -> None:
        client_index = msg.get(LSAMessage.ARG_CLIENT_INDEX)
        self.round_idx = int(msg.get(LSAMessage.ARG_ROUND, 0))
        self.adapter.update_dataset(int(client_index))
        self.adapter.update_model(msg.get(LSAMessage.ARG_MODEL_PARAMS))
        weights, n_samples = self.adapter.train(self.round_idx)

        d, n, u, t = (self.proto["d"], self.proto["n"], self.proto["u"],
                      self.proto["t"])
        scale = self.proto.get("scale", 1 << 10)
        # quantize then field-multiply by integer n_samples → server opens
        # the weighted-FedAvg numerator exactly (see
        # lsa_utils.tree_to_weighted_field_vector for overflow headroom)
        qvec, _ = tree_to_weighted_field_vector(weights, n_samples, scale)
        assert len(qvec) == d, (len(qvec), d)
        local_mask = self._rng.randint(0, int(FIELD_PRIME), size=d).astype(
            np.int64)
        shares = mask_encoding(d, n, u, t, local_mask, self._rng)
        # share j goes to client rank j+1 (self-share kept locally)
        for j in range(n):
            peer_rank = j + 1
            if peer_rank == self.rank:
                self.received_shares[self.rank] = shares[j]
                continue
            share_msg = Message(LSAMessage.MSG_TYPE_C2C_ENCODED_MASK_SHARE,
                                self.get_sender_id(), peer_rank)
            share_msg.add_params(LSAMessage.ARG_SHARE, shares[j])
            self.send_message(share_msg)

        masked = mask_field_vector(qvec, local_mask)
        up = Message(LSAMessage.MSG_TYPE_C2S_MASKED_MODEL,
                     self.get_sender_id(), 0)
        up.add_params(LSAMessage.ARG_MASKED_VECTOR, masked)
        up.add_params(LSAMessage.ARG_NUM_SAMPLES, n_samples)
        self.send_message(up)

    def handle_share(self, msg: Message) -> None:
        self.received_shares[msg.get_sender_id()] = np.asarray(
            msg.get(LSAMessage.ARG_SHARE), np.int64)

    def handle_agg_request(self, msg: Message) -> None:
        survivors = [int(s) for s in msg.get(LSAMessage.ARG_SURVIVORS)]
        have = [self.received_shares[r] for r in survivors
                if r in self.received_shares]
        agg_share = aggregate_encoded_masks(have)
        reply = Message(LSAMessage.MSG_TYPE_C2S_AGG_MASK_SHARE,
                        self.get_sender_id(), 0)
        reply.add_params(LSAMessage.ARG_SHARE, agg_share)
        reply.add_params(LSAMessage.ARG_ROUND, self.round_idx)
        self.send_message(reply)

    def handle_finish(self, msg: Message) -> None:
        logging.info("LSA client %d: finish", self.rank)
        self.finish()
