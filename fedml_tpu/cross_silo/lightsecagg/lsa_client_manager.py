"""LightSecAgg client manager.

Capability parity: reference `cross_silo/lightsecagg/
lsa_fedml_client_manager.py`: train → generate local mask → LCC-encode and
share to peers → upload masked model → on server request, send the sum of
held shares for the surviving set.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional

import numpy as np

from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.mpc.lightsecagg import aggregate_encoded_masks, mask_encoding
from ...core.mpc.secagg import FIELD_PRIME
from ..client.trainer_dist_adapter import TrainerDistAdapter
from .lsa_message_define import LSAMessage
from .lsa_utils import mask_field_vector, tree_to_weighted_field_vector


class LSAClientManager(FedMLCommManager):
    def __init__(self, args: Any, trainer_dist_adapter: TrainerDistAdapter,
                 comm=None, rank: int = 0, size: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, comm, rank, size, backend)
        self.adapter = trainer_dist_adapter
        self.round_idx = 0
        self.proto: Dict[str, int] = {}
        #: round → {sender rank → encoded share}.  Keyed by ROUND: on a
        #: reordering transport a fast peer's next-round share can overtake
        #: this client's own sync, and a flat table would wipe it
        self.received_shares: Dict[int, Dict[int, np.ndarray]] = {}
        # deferred server request (round, survivors): the agg-mask request
        # can overtake the last peer's C2C share — answer only once every
        # survivor's share is held.  Bounded: if a share never arrives
        # (lost past the reliable plane's retransmit deadline), a timer
        # sends the server an explicit "unavailable" reply so it can ask
        # the next share-holder instead of deadlocking the cohort.  The
        # lock covers the timer thread racing the receive-loop thread.
        self._pending_agg_request = None
        # RLock: _clear_pending_request re-acquires under callers that
        # already hold it.  Guards _pending_agg_request/_req_timer AND
        # received_shares — the give-up Timer thread reads shares
        # concurrently with the receive thread's writes.
        self._req_lock = threading.RLock()
        self._req_timer: Optional[threading.Timer] = None
        self._share_wait_s = float(
            getattr(args, "lsa_share_wait_s", 30.0) or 30.0)
        self._rng = np.random.RandomState(
            int(getattr(args, "random_seed", 0) or 0) * 1000 + rank)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_init)
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.handle_sync)
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_C2C_ENCODED_MASK_SHARE, self.handle_share)
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_S2C_AGG_MASK_REQUEST, self.handle_agg_request)
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        msg = Message(LSAMessage.MSG_TYPE_C2S_CLIENT_STATUS,
                      self.get_sender_id(), 0)
        msg.add_params(LSAMessage.ARG_CLIENT_STATUS,
                       LSAMessage.CLIENT_STATUS_ONLINE)
        self.send_message(msg)
        self.com_manager.handle_receive_message()

    # -- round work ----------------------------------------------------------
    def handle_init(self, msg: Message) -> None:
        self.proto = dict(msg.get(LSAMessage.ARG_PROTO))
        self._train_mask_upload(msg)

    def handle_sync(self, msg: Message) -> None:
        self._train_mask_upload(msg)

    def _train_mask_upload(self, msg: Message) -> None:
        client_index = msg.get(LSAMessage.ARG_CLIENT_INDEX)
        self.round_idx = int(msg.get(LSAMessage.ARG_ROUND, 0))
        # retire state from completed rounds (early-arrived shares for the
        # current/future rounds are kept)
        with self._req_lock:
            self.received_shares = {
                r: v for r, v in self.received_shares.items()
                if r >= self.round_idx}
            if (self._pending_agg_request is not None
                    and self._pending_agg_request[0] < self.round_idx):
                self._clear_pending_request()
        self.adapter.update_dataset(int(client_index))
        self.adapter.update_model(msg.get(LSAMessage.ARG_MODEL_PARAMS))
        weights, n_samples = self.adapter.train(self.round_idx)

        d, n, u, t = (self.proto["d"], self.proto["n"], self.proto["u"],
                      self.proto["t"])
        scale = self.proto.get("scale", 1 << 10)
        # quantize then field-multiply by integer n_samples → server opens
        # the weighted-FedAvg numerator exactly (see
        # lsa_utils.tree_to_weighted_field_vector for overflow headroom)
        qvec, _ = tree_to_weighted_field_vector(weights, n_samples, scale)
        assert len(qvec) == d, (len(qvec), d)
        local_mask = self._rng.randint(0, int(FIELD_PRIME), size=d).astype(
            np.int64)
        shares = mask_encoding(d, n, u, t, local_mask, self._rng)
        # share j goes to client rank j+1 (self-share kept locally)
        for j in range(n):
            peer_rank = j + 1
            if peer_rank == self.rank:
                with self._req_lock:
                    self.received_shares.setdefault(
                        self.round_idx, {})[self.rank] = shares[j]
                self._maybe_answer_agg_request()
                continue
            share_msg = Message(LSAMessage.MSG_TYPE_C2C_ENCODED_MASK_SHARE,
                                self.get_sender_id(), peer_rank)
            share_msg.add_params(LSAMessage.ARG_SHARE, shares[j])
            share_msg.add_params(LSAMessage.ARG_ROUND, self.round_idx)
            self.send_message(share_msg)

        masked = mask_field_vector(qvec, local_mask)
        up = Message(LSAMessage.MSG_TYPE_C2S_MASKED_MODEL,
                     self.get_sender_id(), 0)
        up.add_params(LSAMessage.ARG_MASKED_VECTOR, masked)
        up.add_params(LSAMessage.ARG_NUM_SAMPLES, int(n_samples))
        self.send_message(up)

    def handle_share(self, msg: Message) -> None:
        rnd = int(msg.get(LSAMessage.ARG_ROUND, self.round_idx))
        with self._req_lock:
            self.received_shares.setdefault(rnd, {})[msg.get_sender_id()] = \
                np.asarray(msg.get(LSAMessage.ARG_SHARE), np.int64)
        self._maybe_answer_agg_request()

    def handle_agg_request(self, msg: Message) -> None:
        rnd = int(msg.get(LSAMessage.ARG_ROUND, self.round_idx))
        with self._req_lock:
            self._clear_pending_request()
            self._pending_agg_request = (
                rnd, [int(s) for s in msg.get(LSAMessage.ARG_SURVIVORS)])
            self._req_timer = threading.Timer(
                self._share_wait_s, self._give_up_agg_request, args=(rnd,))
            self._req_timer.daemon = True
            self._req_timer.start()
        self._maybe_answer_agg_request()

    def _clear_pending_request(self) -> None:
        # _req_lock is reentrant — callers hold it already
        with self._req_lock:
            self._pending_agg_request = None
            if self._req_timer is not None:
                self._req_timer.cancel()
                self._req_timer = None

    def _maybe_answer_agg_request(self) -> None:
        """Answer the server's aggregate-mask request once every
        survivor's encoded share for that round is held.  Summing a
        PARTIAL set would silently LCC-decode the wrong aggregate mask
        and poison the global model — a share that is merely delayed must
        be waited out (the reliable plane retransmits it); one lost for
        good is handled by the give-up timer below."""
        with self._req_lock:
            if self._pending_agg_request is None:
                return
            rnd, survivors = self._pending_agg_request
            held = self.received_shares.get(rnd, {})
            missing = [r for r in survivors if r not in held]
            if missing:
                logging.debug(
                    "LSA client %d: round-%d agg-mask request waiting on "
                    "shares from %s", self.rank, rnd, missing)
                return
            self._clear_pending_request()
            agg_share = aggregate_encoded_masks(
                [held[r] for r in survivors])
        reply = Message(LSAMessage.MSG_TYPE_C2S_AGG_MASK_SHARE,
                        self.get_sender_id(), 0)
        reply.add_params(LSAMessage.ARG_SHARE, agg_share)
        reply.add_params(LSAMessage.ARG_ROUND, rnd)
        self.send_message(reply)

    def _give_up_agg_request(self, rnd: int) -> None:
        """A survivor's share never arrived (lost past the reliable
        plane's deadline): tell the server this holder can't serve the
        round so it can ask the next one — an explicit refusal keeps the
        protocol live where silence would deadlock the whole cohort."""
        with self._req_lock:
            if (self._pending_agg_request is None
                    or self._pending_agg_request[0] != rnd):
                return
            _, survivors = self._pending_agg_request
            held = self.received_shares.get(rnd, {})
            missing = [r for r in survivors if r not in held]
            if not missing:
                pass      # last share raced the timer — answer normally
            else:
                self._clear_pending_request()
        if not missing:
            self._maybe_answer_agg_request()
            return
        logging.warning(
            "LSA client %d: giving up on round-%d agg-mask request — "
            "shares from %s never arrived", self.rank, rnd, missing)
        reply = Message(LSAMessage.MSG_TYPE_C2S_AGG_MASK_SHARE,
                        self.get_sender_id(), 0)
        reply.add_params(LSAMessage.ARG_SHARE_UNAVAILABLE, True)
        reply.add_params(LSAMessage.ARG_ROUND, rnd)
        self.send_message(reply)

    def handle_finish(self, msg: Message) -> None:
        logging.info("LSA client %d: finish", self.rank)
        self.finish()
