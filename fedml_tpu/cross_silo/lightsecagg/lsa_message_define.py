"""LightSecAgg message schema (reference `cross_silo/lightsecagg/
lsa_message_define.py`)."""


class LSAMessage:
    MSG_TYPE_C2S_CLIENT_STATUS = "C2S_CLIENT_STATUS"
    MSG_TYPE_S2C_INIT_CONFIG = "S2C_INIT_CONFIG_LSA"
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = "S2C_SYNC_MODEL_LSA"
    MSG_TYPE_C2C_ENCODED_MASK_SHARE = "C2C_ENCODED_MASK_SHARE"
    MSG_TYPE_C2S_MASKED_MODEL = "C2S_MASKED_MODEL"
    MSG_TYPE_S2C_AGG_MASK_REQUEST = "S2C_AGG_MASK_REQUEST"
    MSG_TYPE_C2S_AGG_MASK_SHARE = "C2S_AGG_MASK_SHARE"
    MSG_TYPE_S2C_FINISH = "S2C_FINISH_LSA"

    ARG_MODEL_PARAMS = "model_params"
    ARG_MASKED_VECTOR = "masked_vector"
    ARG_CLIENT_INDEX = "client_idx"
    ARG_NUM_SAMPLES = "num_samples"
    ARG_ROUND = "round_idx"
    ARG_SHARE = "mask_share"
    # set on a C2S_AGG_MASK_SHARE reply when the client gave up waiting
    # for a survivor's C2C share (lost past the reliable plane's
    # retransmit deadline) — the server then asks the next share-holder
    ARG_SHARE_UNAVAILABLE = "mask_share_unavailable"
    ARG_SURVIVORS = "survivors"
    ARG_CLIENT_STATUS = "client_status"
    ARG_PROTO = "lsa_proto"  # dict(d, n, u, t, scale)

    CLIENT_STATUS_ONLINE = "ONLINE"
