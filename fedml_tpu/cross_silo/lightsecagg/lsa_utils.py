"""LightSecAgg field-domain model transforms.

Quantize a param pytree into the prime field (p = 2^31 − 1), mask/unmask
mod p, and de-quantize back (reference `cross_silo/lightsecagg/
lsa_fedml_aggregator.py` transform_tensor_to_finite / finite_to_tensor).
Host-side numpy int64: exact, and this path is control-plane-sized.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import numpy as np

from ...core.mpc.secagg import FIELD_PRIME

DEFAULT_SCALE = 1 << 10


def tree_to_field_vector(tree: Any, scale: int = DEFAULT_SCALE
                         ) -> Tuple[np.ndarray, Any]:
    """float pytree → field vector [d] (negatives map to p + v)."""
    leaves = jax.tree_util.tree_leaves(tree)
    flat = np.concatenate([np.asarray(l, np.float64).ravel() for l in leaves])
    q = np.round(flat * scale).astype(np.int64)
    return np.mod(q, FIELD_PRIME), tree


def field_vector_to_tree(vec: np.ndarray, like: Any, n_summed: int = 1,
                         scale: int = DEFAULT_SCALE) -> Any:
    """field vector (a mod-p SUM of n_summed quantized models) → mean pytree."""
    v = np.asarray(vec, np.int64) % FIELD_PRIME
    signed = np.where(v > FIELD_PRIME // 2, v - FIELD_PRIME, v).astype(
        np.float64)
    flat = signed / (scale * max(n_summed, 1))
    leaves, treedef = jax.tree_util.tree_flatten(like)
    out, off = [], 0
    import jax.numpy as jnp

    for leaf in leaves:
        size = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
        out.append(jnp.asarray(
            flat[off:off + size].reshape(np.shape(leaf)),
            dtype=np.result_type(np.asarray(leaf))))
        off += size
    return jax.tree_util.tree_unflatten(treedef, out)


def mask_field_vector(qvec: np.ndarray, mask: np.ndarray) -> np.ndarray:
    return (np.asarray(qvec, np.int64) + np.asarray(mask, np.int64)) \
        % FIELD_PRIME


def unmask_field_sum(qsum: np.ndarray, agg_mask: np.ndarray) -> np.ndarray:
    return (np.asarray(qsum, np.int64) - np.asarray(agg_mask, np.int64)) \
        % FIELD_PRIME


# -- sample-weighted aggregation under masking -------------------------------
# Clients quantize FIRST (full scale precision), then multiply by the integer
# n_samples in the field — exact mod-p arithmetic, no precision loss — so the
# opened field sum is the weighted-FedAvg numerator; the server divides by
# sum(n_samples).  Headroom: signed recovery needs
# sum_i |x_i|_max * scale * n_i < p/2 ≈ 1.07e9, i.e. with scale 2^10 and
# |x| ≤ 10 the cohort supports ~100k total samples per round.


def tree_to_weighted_field_vector(tree: Any, n_samples: float,
                                  scale: int = DEFAULT_SCALE
                                  ) -> Tuple[np.ndarray, Any]:
    qvec, template = tree_to_field_vector(tree, scale)
    w = np.int64(max(1, int(round(float(n_samples))))) % FIELD_PRIME
    return (qvec * w) % FIELD_PRIME, template


def weighted_sum_to_mean_tree(qsum: np.ndarray, like: Any,
                              total_samples: float,
                              scale: int = DEFAULT_SCALE) -> Any:
    sum_tree = field_vector_to_tree(qsum, like, n_summed=1, scale=scale)
    denom = max(1.0, round(float(total_samples)))
    return jax.tree_util.tree_map(lambda x: (x / denom).astype(x.dtype),
                                  sum_tree)
