"""LightSecAgg server manager.

Capability parity: reference `cross_silo/lightsecagg/
lsa_fedml_server_manager.py` + `lsa_fedml_aggregator.py`: collect masked
models, request aggregate-mask shares from survivors, LCC-decode the
aggregate mask, unmask the sum, average, advance rounds.  Tolerates client
dropout between upload and reconstruction (the masked sum only includes
survivors, and any u surviving shares reconstruct their aggregate mask).
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import numpy as np

from ...core import mlops
from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.mpc.lightsecagg import decode_aggregate_mask
from ..server.fedml_aggregator import FedMLAggregator
from .lsa_message_define import LSAMessage
from .lsa_utils import (
    tree_to_field_vector,
    unmask_field_sum,
    weighted_sum_to_mean_tree,
)

FIELD = None


class LSAServerManager(FedMLCommManager):
    def __init__(self, args: Any, aggregator: FedMLAggregator, comm=None,
                 rank: int = 0, client_num: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, comm, rank, client_num + 1, backend)
        self.aggregator = aggregator
        self.round_num = int(args.comm_round)
        self.args.round_idx = 0
        self.client_num = client_num
        self.scale = 1 << 10
        # privacy/dropout parameters: tolerate t colluding, need u survivors
        self.t = max(1, client_num // 3)
        self.u = max(self.t + 1, 2 * client_num // 3)
        self.online: Dict[int, bool] = {}
        self.masked: Dict[int, np.ndarray] = {}
        self.sample_nums: Dict[int, float] = {}
        self.agg_shares: Dict[int, np.ndarray] = {}
        # idempotent stage transition: a duplicated masked upload arriving
        # after the cohort is complete must not re-broadcast the share
        # request (see the SecAgg manager's matching guard)
        self._shares_requested = False
        # reconstruction fallback bookkeeping: ANY u survivors' aggregate
        # shares open the mask, so when a requested holder replies
        # "unavailable" (its C2C shares were lost for good) the server
        # asks the next survivor instead of stalling
        self._share_survivors: list = []
        self._share_req_sent: set = set()
        self.d = None
        self._template = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_C2S_CLIENT_STATUS, self.handle_status)
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_C2S_MASKED_MODEL, self.handle_masked_model)
        self.register_message_receive_handler(
            LSAMessage.MSG_TYPE_C2S_AGG_MASK_SHARE, self.handle_agg_share)

    # -- handshake -----------------------------------------------------------
    def handle_status(self, msg: Message) -> None:
        status = msg.get(LSAMessage.ARG_CLIENT_STATUS,
                         LSAMessage.CLIENT_STATUS_ONLINE)
        if status != LSAMessage.CLIENT_STATUS_ONLINE:
            return
        self.online[msg.get_sender_id()] = True
        if len(self.online) == self.client_num:
            self._send_round_start(LSAMessage.MSG_TYPE_S2C_INIT_CONFIG)

    def _send_round_start(self, msg_type: str) -> None:
        global_model = self.aggregator.get_global_model_params()
        self._template = global_model
        qvec, _ = tree_to_field_vector(global_model, self.scale)
        self.d = int(len(qvec))
        proto = {"d": self.d, "n": self.client_num, "u": self.u, "t": self.t,
                 "scale": self.scale}
        ids = self.aggregator.client_sampling(
            self.args.round_idx, int(self.args.client_num_in_total),
            self.client_num)
        for i in range(self.client_num):
            msg = Message(msg_type, self.get_sender_id(), i + 1)
            msg.add_params(LSAMessage.ARG_MODEL_PARAMS, global_model)
            msg.add_params(LSAMessage.ARG_CLIENT_INDEX, ids[i % len(ids)])
            msg.add_params(LSAMessage.ARG_ROUND, self.args.round_idx)
            msg.add_params(LSAMessage.ARG_PROTO, proto)
            self.send_message(msg)

    # -- masked model collection ---------------------------------------------
    def handle_masked_model(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        self.masked[sender] = np.asarray(
            msg.get(LSAMessage.ARG_MASKED_VECTOR), np.int64)
        self.sample_nums[sender] = float(
            msg.get(LSAMessage.ARG_NUM_SAMPLES, 1.0))
        # dropout emulation hook for tests (mirrors the SecAgg manager's
        # ``sa_simulate_dropout_ranks``): ranks listed here "die after the
        # masking commitment" — their upload never arrives
        drop = set(getattr(self.args, "lsa_simulate_dropout_ranks", [])
                   or [])
        if sender in drop:
            del self.masked[sender]
            self.sample_nums.pop(sender, None)
            return
        expected = self.client_num - len(drop)
        if len(self.masked) >= expected and not self._shares_requested:
            self._shares_requested = True
            self._share_survivors = sorted(self.masked.keys())
            self._share_req_sent = set()
            targets = self._share_survivors[: self.u] \
                if len(self._share_survivors) >= self.u \
                else list(self._share_survivors)
            for r in targets:
                self._request_share_from(r)

    def _request_share_from(self, rank: int) -> None:
        self._share_req_sent.add(rank)
        req = Message(LSAMessage.MSG_TYPE_S2C_AGG_MASK_REQUEST,
                      self.get_sender_id(), rank)
        req.add_params(LSAMessage.ARG_SURVIVORS, self._share_survivors)
        req.add_params(LSAMessage.ARG_ROUND, self.args.round_idx)
        self.send_message(req)

    # -- reconstruction ------------------------------------------------------
    def handle_agg_share(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        rnd = int(msg.get(LSAMessage.ARG_ROUND, self.args.round_idx))
        if rnd != int(self.args.round_idx):
            # a reply delayed past the round boundary must not pollute the
            # next round's share set (LCC would decode the wrong mask)
            logging.warning("LSA server: dropping stale round-%d agg share "
                            "from client %d (now round %d)", rnd, sender,
                            self.args.round_idx)
            return
        if msg.get(LSAMessage.ARG_SHARE_UNAVAILABLE):
            remaining = [r for r in self._share_survivors
                         if r not in self._share_req_sent]
            if remaining:
                logging.warning(
                    "LSA server: client %d cannot serve round-%d agg "
                    "shares — asking client %d instead", sender, rnd,
                    remaining[0])
                self._request_share_from(remaining[0])
                return
            logging.error(
                "LSA server: no share-holder left for round %d (%d/%d "
                "replies) — aborting the run", rnd, len(self.agg_shares),
                self.u)
            self._abort_run()
            return
        self.agg_shares[sender - 1] = np.asarray(
            msg.get(LSAMessage.ARG_SHARE), np.int64)
        if len(self.agg_shares) < self.u:
            return
        try:
            self._reconstruct_and_advance()
        except Exception:
            # LCC decode failure is unrecoverable for the round — release
            # the clients (they'd otherwise block on the next sync) before
            # surfacing the error
            logging.exception("LSA server: aggregate-mask reconstruction "
                              "failed in round %s — aborting the run",
                              self.args.round_idx)
            self._abort_run()
            raise

    def _abort_run(self) -> None:
        try:
            self._broadcast_finish()
        finally:
            mlops.log_aggregation_status("FAILED")
            self.finish()

    def _broadcast_finish(self) -> None:
        for r in range(1, self.client_num + 1):
            try:
                self.send_message(Message(LSAMessage.MSG_TYPE_S2C_FINISH,
                                          self.get_sender_id(), r))
            except Exception:
                # best-effort: one dead transport must not strand the
                # remaining clients without their FINISH
                logging.exception("LSA server: FINISH to rank %d failed",
                                  r)

    def _reconstruct_and_advance(self) -> None:
        from ...core.mpc.secagg import FIELD_PRIME

        survivors = sorted(self.masked.keys())
        qsum = np.zeros(self.d, np.int64)
        for r in survivors:
            qsum = (qsum + self.masked[r]) % FIELD_PRIME
        agg_mask = decode_aggregate_mask(
            dict(self.agg_shares), self.d, self.client_num, self.u, self.t)
        clear = unmask_field_sum(qsum, agg_mask)
        total_w = sum(self.sample_nums.get(r, 1.0) for r in survivors) or 1.0
        avg_tree = weighted_sum_to_mean_tree(clear, self._template, total_w,
                                             self.scale)
        self.aggregator.set_global_model_params(avg_tree)

        freq = int(getattr(self.args, "frequency_of_the_test", 1) or 1)
        if (self.args.round_idx % freq == 0
                or self.args.round_idx == self.round_num - 1):
            self.aggregator.test_on_server_for_all_clients(self.args.round_idx)

        self.masked.clear()
        self.agg_shares.clear()
        self._shares_requested = False
        self._share_survivors = []
        self._share_req_sent = set()
        self.args.round_idx += 1
        if self.args.round_idx >= self.round_num:
            self._broadcast_finish()
            mlops.log_aggregation_status("FINISHED")
            self.finish()
            return
        self._send_round_start(LSAMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
