"""Cross-silo message schema.

Capability parity: reference `cross_silo/server/message_define.py` /
`client/message_define.py` (MyMessage constants): connection handshake,
init-config broadcast, model upload, sync, finish.
"""


class MyMessage:
    # handshake / liveness (reference MSG_TYPE_CONNECTION_IS_READY + status)
    # reference-parity constant: emitted by the hosted MLOps broker on MQTT
    # bring-up; reserved here so configs/payloads stay wire-compatible
    MSG_TYPE_CONNECTION_IS_READY = "CONNECTION_IS_READY"  # fedml: noqa[PROTO001]
    MSG_TYPE_C2S_CLIENT_STATUS = "C2S_CLIENT_STATUS"
    # heartbeat failure detection (PR 4): clients emit this every
    # ``heartbeat_interval_s``; the server's phi-accrual-lite detector
    # declares a peer dead after ``heartbeat_miss_threshold`` silent
    # intervals and drops it from the round immediately (instead of
    # waiting out the full elastic round timer).  Heartbeats ride the
    # reliable plane as VOLATILE messages — never retransmitted, the next
    # beat supersedes a lost one.
    MSG_TYPE_HEARTBEAT = "C2S_HEARTBEAT"

    # training round-trip
    MSG_TYPE_S2C_INIT_CONFIG = "S2C_INIT_CONFIG"
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = "S2C_SYNC_MODEL_TO_CLIENT"
    MSG_TYPE_C2S_SEND_MODEL_TO_SERVER = "C2S_SEND_MODEL_TO_SERVER"
    MSG_TYPE_S2C_FINISH = "S2C_FINISH"

    # payload keys
    MSG_ARG_KEY_MODEL_PARAMS = "model_params"
    MSG_ARG_KEY_CLIENT_INDEX = "client_idx"
    MSG_ARG_KEY_NUM_SAMPLES = "num_samples"
    MSG_ARG_KEY_ROUND = "round_idx"
    MSG_ARG_KEY_CLIENT_STATUS = "client_status"
    MSG_ARG_KEY_CLIENT_OS = "client_os"
    MSG_ARG_KEY_TRAIN_METRICS = "train_metrics"
    MSG_ARG_KEY_COMPRESSED_UPDATE = "compressed_update"
    # wire-compression negotiation (docs/ROBUSTNESS.md "Asynchronous
    # rounds"): clients advertise codec capability tokens on their status
    # message; the server assigns a codec per link on every round
    # broadcast (only when the link's caps cover it — a legacy client
    # simply keeps exchanging raw pytrees).  Compressed uploads travel as
    # a self-describing delta payload; compressed broadcasts replace the
    # model tree with per-leaf quantized marker dicts and set the
    # MODEL_ENCODED flag
    MSG_ARG_KEY_WIRE_CAPS = "wire_caps"
    MSG_ARG_KEY_WIRE_CODEC = "wire_codec"
    MSG_ARG_KEY_WIRE_UPDATE = "wire_update"
    MSG_ARG_KEY_MODEL_ENCODED = "model_wq"
    # distributed-tracing context ({trace_id, span_id}, `mlops.tracing`):
    # injected by the server into every round broadcast and echoed back on
    # uploads, so one round's spans across server/clients/aggregator stitch
    # into a single trace
    MSG_ARG_KEY_TRACE_CTX = "trace_ctx"
    MSG_ARG_KEY_HEARTBEAT_TS = "hb_ts"

    CLIENT_STATUS_ONLINE = "ONLINE"
    CLIENT_STATUS_IDLE = "IDLE"
