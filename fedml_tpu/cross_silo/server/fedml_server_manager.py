"""Cross-silo FedMLServerManager.

Capability parity: reference `cross_silo/server/fedml_server_manager.py:15-332`
— waits for client online statuses, sends init config (global model +
client_index), collects C2S models, aggregates, advances rounds, sends FINISH.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

from ...core import mlops
from ...core.mlops import metrics, tracing
from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ..message_define import MyMessage
from .fedml_aggregator import FedMLAggregator

_rounds_total = metrics.counter(
    "fedml_rounds_completed_total", "Federated rounds completed",
    labels=("run_id",))
_round_seconds = metrics.histogram(
    "fedml_round_seconds", "Wall-clock duration of a federated round",
    labels=("run_id",),
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0, 1800.0))
_clients_reported = metrics.gauge(
    "fedml_round_clients_reported",
    "Client results aggregated in the last completed round",
    labels=("run_id",))
_current_round = metrics.gauge(
    "fedml_current_round", "Round index the server is currently on",
    labels=("run_id",))


class FedMLServerManager(FedMLCommManager):
    def __init__(self, args: Any, aggregator: FedMLAggregator, comm=None,
                 rank: int = 0, client_num: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, comm, rank, client_num + 1, backend)
        self.aggregator = aggregator
        self.round_num = int(args.comm_round)
        self.args.round_idx = 0
        self.client_num = client_num
        self.client_online_status: Dict[int, bool] = {}
        self.client_id_list_in_this_round: List[int] = []
        self.data_silo_index_of_client: List[int] = []
        self.is_initialized = False
        # elastic membership (new capability, SURVEY §7 item 10):
        # round_timeout_s > 0 → aggregate with whoever reported once the
        # timer fires (≥ min_clients_per_round); a timed-out round below the
        # minimum RE-SOLICITS the missing clients before extending; init
        # force-starts after the timeout once ≥ min clients are online
        self.round_timeout_s = float(
            getattr(args, "round_timeout_s", 0) or 0)
        self.min_clients = int(
            getattr(args, "min_clients_per_round", 1) or 1)
        self._round_lock = threading.RLock()
        self._round_timer: Optional[threading.Timer] = None
        self._init_timer: Optional[threading.Timer] = None
        self._caught_up_this_round: set = set()
        # client-reported training metrics for the round in flight, keyed
        # by sender rank; summarized onto the round span at completion
        self._round_train_metrics: Dict[int, Dict] = {}
        # distributed tracing: one root span per run, one parent span per
        # round; the round span's context travels on every broadcast so
        # client + aggregator spans stitch under it
        self._run_span: Optional[tracing.Span] = None
        self._round_span: Optional[tracing.Span] = None
        self._run_label = str(getattr(args, "run_id", "0"))

    def run(self) -> None:
        super().run()

    # -- protocol ------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_client_status_update)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client)

    def handle_message_client_status_update(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        status = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        client_os = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_OS, "unknown")
        with self._round_lock:
            # status dict is read by the init-timer thread under the lock;
            # writing it under the lock too avoids mutating during iteration
            if status == MyMessage.CLIENT_STATUS_ONLINE:
                self.client_online_status[sender] = True
            n_online = sum(self.client_online_status.values())
        logging.info("server: client %d (%s) status %s (%d/%d online)",
                     sender, client_os, status, n_online, self.client_num)
        with self._round_lock:
            if not self.is_initialized:
                if len(self.client_online_status) == self.client_num:
                    self._start_training()
                elif (self.round_timeout_s > 0
                      and self._init_timer is None):
                    # elastic init: don't block forever on a client that
                    # never comes online — force-start after the timeout
                    # once ≥ min clients are here
                    self._init_timer = threading.Timer(
                        self.round_timeout_s, self._maybe_force_init)
                    self._init_timer.daemon = True
                    self._init_timer.start()
            elif status == MyMessage.CLIENT_STATUS_ONLINE:
                # elastic late join: a (re)connecting client that hasn't
                # uploaded this round is caught up with the round's model —
                # at most ONCE per round (a duplicated ONLINE re-announce
                # must not trigger a redundant full training pass; lost
                # syncs are covered by the timeout's re-solicitation)
                if (sender in self._ranks_for(
                        self.client_id_list_in_this_round)
                        and sender not in self._caught_up_this_round
                        and not self.aggregator.has_received(sender - 1)):
                    logging.info("server: late-joining client %d caught up "
                                 "into round %d", sender, self.args.round_idx)
                    self._caught_up_this_round.add(sender)
                    self._broadcast_round(only_rank=sender)

    def _maybe_force_init(self) -> None:
        with self._round_lock:
            self._init_timer = None
            if self.is_initialized:
                return
            online = sum(self.client_online_status.values())
            if online >= self.min_clients:
                logging.warning(
                    "server: init timeout — starting with %d/%d clients "
                    "online", online, self.client_num)
                self._start_training()
            else:  # keep waiting, check again after another timeout
                self._init_timer = threading.Timer(
                    self.round_timeout_s, self._maybe_force_init)
                self._init_timer.daemon = True
                self._init_timer.start()

    def _start_training(self) -> None:
        mlops.log_aggregation_status("RUNNING")
        self._run_span = tracing.start_span(
            "fed_run", run_id=self._run_label, rounds=self.round_num)
        self.is_initialized = True
        self.send_init_msg()

    def _open_round_span(self) -> None:
        parent = self._run_span.ctx if self._run_span else None
        self._round_span = tracing.start_span(
            "train_round", parent=parent, round=int(self.args.round_idx))
        _current_round.labels(run_id=self._run_label).set(
            int(self.args.round_idx))

    def send_init_msg(self) -> None:
        self.client_id_list_in_this_round = self.aggregator.client_sampling(
            self.args.round_idx, int(self.args.client_num_in_total),
            int(self.args.client_num_per_round))
        self.data_silo_index_of_client = self.aggregator.data_silo_selection(
            self.args.round_idx, int(self.args.client_num_in_total),
            len(self.client_id_list_in_this_round))
        self._open_round_span()
        self._broadcast_round()
        self._arm_round_timer()

    def _broadcast_round(self, only_rank: Optional[int] = None) -> None:
        """Send the current round's model to every participating rank (or
        just ``only_rank`` for re-solicitation/late-join catch-up) — one
        message per slot a rank serves.  Caller holds ``_round_lock``."""
        mtype = (MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT
                 if self.args.round_idx else
                 MyMessage.MSG_TYPE_S2C_INIT_CONFIG)
        global_model = self.aggregator.get_global_model_params()
        for i, rank in enumerate(
                self._ranks_for(self.client_id_list_in_this_round)):
            if only_rank is not None and rank != only_rank:
                continue
            msg = Message(mtype, self.get_sender_id(), rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                           self.client_id_list_in_this_round[i])
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.args.round_idx)
            if self._round_span is not None:
                msg.add_params(MyMessage.MSG_ARG_KEY_TRACE_CTX,
                               tracing.inject(self._round_span.ctx))
            self.send_message(msg)

    # -- elastic round timeout ----------------------------------------------
    def _arm_round_timer(self) -> None:
        if self.round_timeout_s <= 0:
            return
        if self._round_timer is not None:
            self._round_timer.cancel()
        self._round_timer = threading.Timer(
            self.round_timeout_s, self._on_round_timeout,
            args=(self.args.round_idx,))
        self._round_timer.daemon = True
        self._round_timer.start()

    def _on_round_timeout(self, round_idx: int) -> None:
        with self._round_lock:
            if self.args.round_idx != round_idx:
                return  # round already completed normally
            got = self.aggregator.receive_count()
            if got < self.min_clients:
                # RE-SOLICIT the ranks that haven't reported (their sync or
                # upload may have been lost), then extend the deadline —
                # without this a lossy link could extend forever with idle
                # clients that never got the round
                missing = [r for r in set(self._ranks_for(
                    self.client_id_list_in_this_round))
                    if not self.aggregator.has_received(r - 1)]
                logging.warning(
                    "server: round %d timeout with only %d results "
                    "(< min %d) — re-soliciting %s and extending",
                    round_idx, got, self.min_clients, missing)
                for rank in missing:
                    self._broadcast_round(only_rank=rank)
                self._arm_round_timer()
                return
            logging.warning(
                "server: round %d timeout — aggregating %d/%d results, "
                "dropping stragglers", round_idx, got,
                len(self.client_id_list_in_this_round))
            self._complete_round()

    def _ranks_for(self, client_ids: List[int]) -> List[int]:
        """client slots → comm ranks 1..client_num (round-robin when
        client_num_per_round < physical clients is 1:1 in this build)."""
        return [1 + (i % self.client_num)
                for i in range(len(client_ids))]

    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        local_sample_number = msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
        with self._round_lock:
            # stale check FIRST (and under the lock): a round the timeout
            # already closed must not cost a decompression, and an on-time
            # upload must not lose the race against the timer thread
            upload_round = msg.get(MyMessage.MSG_ARG_KEY_ROUND)
            if (upload_round is not None
                    and int(upload_round) != int(self.args.round_idx)):
                logging.warning("server: dropping stale round-%s upload "
                                "from client %d (now round %d)",
                                upload_round, sender, self.args.round_idx)
                return
            model_params = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
            compressed = msg.get(MyMessage.MSG_ARG_KEY_COMPRESSED_UPDATE)
            if model_params is None and compressed is not None:
                # sparse delta: rebuild weights = global + Δ using OUR copy
                # of the global model for the tree structure
                import jax

                from ...utils.compression import TopKCompressor, tree_spec

                global_model = self.aggregator.get_global_model_params()
                delta = TopKCompressor().decompress(
                    compressed, tree_spec(global_model))
                model_params = jax.tree_util.tree_map(
                    lambda g, d: g + d, global_model, delta)
            train_metrics = msg.get(MyMessage.MSG_ARG_KEY_TRAIN_METRICS)
            if isinstance(train_metrics, dict) and train_metrics:
                self._round_train_metrics[sender] = train_metrics
            self.aggregator.add_local_trained_result(
                sender - 1, model_params, local_sample_number)
            if self.aggregator.check_whether_all_receive():
                self._complete_round()
                return
            # elastic early completion: when every ONLINE participant has
            # reported, don't idle out the full timeout waiting for ranks
            # the server already knows are absent
            if self.round_timeout_s > 0:
                ranks = set(self._ranks_for(self.client_id_list_in_this_round))
                online = {r for r in ranks
                          if self.client_online_status.get(r)}
                if (online
                        and all(self.aggregator.has_received(r - 1)
                                for r in online)
                        and self.aggregator.receive_count()
                        >= self.min_clients):
                    logging.info(
                        "server: round %d — all %d online participants "
                        "reported; completing without waiting for %d "
                        "offline", self.args.round_idx, len(online),
                        len(ranks - online))
                    self._complete_round()

    def _complete_round(self) -> None:
        """Aggregate (possibly a partial set), test, advance or finish.
        Caller must hold ``_round_lock``."""
        if self._round_timer is not None:
            self._round_timer.cancel()
        mlops.event("server.wait", False, self.args.round_idx)
        n_reported = self.aggregator.receive_count()
        # aggregation + eval run UNDER the round span's context so the
        # aggregator's own spans nest into this round's trace subtree
        with tracing.use_ctx(
                self._round_span.ctx if self._round_span else None):
            self.aggregator.aggregate()
            freq = int(getattr(self.args, "frequency_of_the_test", 1) or 1)
            if (self.args.round_idx % freq == 0
                    or self.args.round_idx == self.round_num - 1):
                self.aggregator.test_on_server_for_all_clients(
                    self.args.round_idx)
        _clients_reported.labels(run_id=self._run_label).set(n_reported)
        _rounds_total.labels(run_id=self._run_label).inc()
        losses = [m.get("train_loss")
                  for m in self._round_train_metrics.values()
                  if isinstance(m.get("train_loss"), (int, float))]
        self._round_train_metrics = {}
        if self._round_span is not None:
            if losses:
                self._round_span.set_attr(
                    "mean_client_train_loss", sum(losses) / len(losses))
            self._round_span.set_attr("clients_reported", n_reported)
            _round_seconds.labels(run_id=self._run_label).observe(
                self._round_span.end())
            self._round_span = None

        self.args.round_idx += 1
        if self.args.round_idx >= self.round_num:
            self.send_finish_to_all()
            mlops.log_aggregation_status("FINISHED")
            if self._run_span is not None:
                self._run_span.end()
                self._run_span = None
            self.finish()
            return
        # next round
        self._caught_up_this_round = set()
        self.client_id_list_in_this_round = self.aggregator.client_sampling(
            self.args.round_idx, int(self.args.client_num_in_total),
            int(self.args.client_num_per_round))
        mlops.event("server.wait", True, self.args.round_idx)
        self._open_round_span()
        self._broadcast_round()
        self._arm_round_timer()

    def send_finish_to_all(self) -> None:
        for rank in range(1, self.client_num + 1):
            msg = Message(MyMessage.MSG_TYPE_S2C_FINISH,
                          self.get_sender_id(), rank)
            self.send_message(msg)
