"""Cross-silo FedMLServerManager.

Capability parity: reference `cross_silo/server/fedml_server_manager.py:15-332`
— waits for client online statuses, sends init config (global model +
client_index), collects C2S models, aggregates, advances rounds, sends FINISH.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

from ...core import mlops
from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ..message_define import MyMessage
from .fedml_aggregator import FedMLAggregator


class FedMLServerManager(FedMLCommManager):
    def __init__(self, args: Any, aggregator: FedMLAggregator, comm=None,
                 rank: int = 0, client_num: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, comm, rank, client_num + 1, backend)
        self.aggregator = aggregator
        self.round_num = int(args.comm_round)
        self.args.round_idx = 0
        self.client_num = client_num
        self.client_online_status: Dict[int, bool] = {}
        self.client_id_list_in_this_round: List[int] = []
        self.data_silo_index_of_client: List[int] = []
        self.is_initialized = False
        # elastic membership (new capability, SURVEY §7 item 10):
        # round_timeout_s > 0 → aggregate with whoever reported once the
        # timer fires (≥ min_clients_per_round); late-online clients are
        # caught up into the current round instead of blocking init forever
        self.round_timeout_s = float(
            getattr(args, "round_timeout_s", 0) or 0)
        self.min_clients = int(
            getattr(args, "min_clients_per_round", 1) or 1)
        self._round_lock = threading.RLock()
        self._round_timer: Optional[threading.Timer] = None
        self._served_this_round: set = set()

    def run(self) -> None:
        super().run()

    # -- protocol ------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_client_status_update)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client)

    def handle_message_client_status_update(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        status = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        if status == MyMessage.CLIENT_STATUS_ONLINE:
            self.client_online_status[sender] = True
        logging.info("server: client %d status %s (%d/%d online)", sender,
                     status, sum(self.client_online_status.values()),
                     self.client_num)
        if (len(self.client_online_status) == self.client_num
                and not self.is_initialized):
            mlops.log_aggregation_status("RUNNING")
            self.is_initialized = True
            self.send_init_msg()
        elif self.is_initialized and status == \
                MyMessage.CLIENT_STATUS_ONLINE:
            # elastic late join: a client that came online after training
            # started is caught up with the current round's model — but only
            # if it wasn't already served this round (an ONLINE re-announce
            # from a participating client must not trigger double training)
            with self._round_lock:
                if (sender in self._ranks_for(
                        self.client_id_list_in_this_round)
                        and sender not in self._served_this_round
                        and (sender - 1) not in
                        self.aggregator._received_this_round):
                    logging.info("server: late-joining client %d caught up "
                                 "into round %d", sender, self.args.round_idx)
                    self._send_round_to(sender)

    def send_init_msg(self) -> None:
        self.client_id_list_in_this_round = self.aggregator.client_sampling(
            self.args.round_idx, int(self.args.client_num_in_total),
            int(self.args.client_num_per_round))
        self.data_silo_index_of_client = self.aggregator.data_silo_selection(
            self.args.round_idx, int(self.args.client_num_in_total),
            len(self.client_id_list_in_this_round))
        global_model = self.aggregator.get_global_model_params()
        for i, receiver_rank in enumerate(
                self._ranks_for(self.client_id_list_in_this_round)):
            msg = Message(MyMessage.MSG_TYPE_S2C_INIT_CONFIG,
                          self.get_sender_id(), receiver_rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                           self.client_id_list_in_this_round[i])
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.args.round_idx)
            self.send_message(msg)
            self._served_this_round.add(receiver_rank)
        self._arm_round_timer()

    def _send_round_to(self, receiver_rank: int) -> None:
        """(Re)send the current round's sync message(s) to one client — one
        per slot it serves (a rank can hold several slots when the mapping
        round-robins)."""
        ranks = self._ranks_for(self.client_id_list_in_this_round)
        mtype = (MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT
                 if self.args.round_idx else
                 MyMessage.MSG_TYPE_S2C_INIT_CONFIG)
        for i, rank in enumerate(ranks):
            if rank != receiver_rank:
                continue
            msg = Message(mtype, self.get_sender_id(), receiver_rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                           self.aggregator.get_global_model_params())
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                           self.client_id_list_in_this_round[i])
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.args.round_idx)
            self.send_message(msg)
        self._served_this_round.add(receiver_rank)

    # -- elastic round timeout ----------------------------------------------
    def _arm_round_timer(self) -> None:
        if self.round_timeout_s <= 0:
            return
        if self._round_timer is not None:
            self._round_timer.cancel()
        self._round_timer = threading.Timer(
            self.round_timeout_s, self._on_round_timeout,
            args=(self.args.round_idx,))
        self._round_timer.daemon = True
        self._round_timer.start()

    def _on_round_timeout(self, round_idx: int) -> None:
        with self._round_lock:
            if self.args.round_idx != round_idx:
                return  # round already completed normally
            got = self.aggregator.receive_count()
            if got < self.min_clients:
                logging.warning(
                    "server: round %d timeout with only %d/%d results "
                    "(< min %d) — extending", round_idx, got,
                    len(self.client_id_list_in_this_round), self.min_clients)
                self._arm_round_timer()
                return
            logging.warning(
                "server: round %d timeout — aggregating %d/%d results, "
                "dropping stragglers", round_idx, got,
                len(self.client_id_list_in_this_round))
            self._complete_round()

    def _ranks_for(self, client_ids: List[int]) -> List[int]:
        """client slots → comm ranks 1..client_num (round-robin when
        client_num_per_round < physical clients is 1:1 in this build)."""
        return [1 + (i % self.client_num)
                for i in range(len(client_ids))]

    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        local_sample_number = msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
        with self._round_lock:
            # stale check FIRST (and under the lock): a round the timeout
            # already closed must not cost a decompression, and an on-time
            # upload must not lose the race against the timer thread
            upload_round = msg.get(MyMessage.MSG_ARG_KEY_ROUND)
            if (upload_round is not None
                    and int(upload_round) != int(self.args.round_idx)):
                logging.warning("server: dropping stale round-%s upload "
                                "from client %d (now round %d)",
                                upload_round, sender, self.args.round_idx)
                return
            model_params = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
            compressed = msg.get(MyMessage.MSG_ARG_KEY_COMPRESSED_UPDATE)
            if model_params is None and compressed is not None:
                # sparse delta: rebuild weights = global + Δ using OUR copy
                # of the global model for the tree structure
                import jax

                from ...utils.compression import TopKCompressor, tree_spec

                global_model = self.aggregator.get_global_model_params()
                delta = TopKCompressor().decompress(
                    compressed, tree_spec(global_model))
                model_params = jax.tree_util.tree_map(
                    lambda g, d: g + d, global_model, delta)
            self.aggregator.add_local_trained_result(
                sender - 1, model_params, local_sample_number)
            if not self.aggregator.check_whether_all_receive():
                return
            self._complete_round()

    def _complete_round(self) -> None:
        """Aggregate (possibly a partial set), test, advance or finish.
        Caller must hold ``_round_lock``."""
        if self._round_timer is not None:
            self._round_timer.cancel()
        mlops.event("server.wait", False, self.args.round_idx)
        self.aggregator.aggregate()
        freq = int(getattr(self.args, "frequency_of_the_test", 1) or 1)
        if (self.args.round_idx % freq == 0
                or self.args.round_idx == self.round_num - 1):
            self.aggregator.test_on_server_for_all_clients(self.args.round_idx)

        self.args.round_idx += 1
        if self.args.round_idx >= self.round_num:
            self.send_finish_to_all()
            mlops.log_aggregation_status("FINISHED")
            self.finish()
            return
        # next round
        self._served_this_round = set()
        self.client_id_list_in_this_round = self.aggregator.client_sampling(
            self.args.round_idx, int(self.args.client_num_in_total),
            int(self.args.client_num_per_round))
        global_model = self.aggregator.get_global_model_params()
        mlops.event("server.wait", True, self.args.round_idx)
        for i, receiver_rank in enumerate(
                self._ranks_for(self.client_id_list_in_this_round)):
            msg = Message(MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT,
                          self.get_sender_id(), receiver_rank)
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, global_model)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                           self.client_id_list_in_this_round[i])
            msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, self.args.round_idx)
            self.send_message(msg)
            self._served_this_round.add(receiver_rank)
        self._arm_round_timer()

    def send_finish_to_all(self) -> None:
        for rank in range(1, self.client_num + 1):
            msg = Message(MyMessage.MSG_TYPE_S2C_FINISH,
                          self.get_sender_id(), rank)
            self.send_message(msg)
