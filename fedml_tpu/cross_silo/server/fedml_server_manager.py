"""Cross-silo FedMLServerManager.

Capability parity: reference `cross_silo/server/fedml_server_manager.py:15-332`
— waits for client online statuses, sends init config (global model +
client_index), collects C2S models, aggregates, advances rounds, sends FINISH.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ...core import mlops
from ...core.mlops import flight_recorder, ledger, metrics, slo, tracing
from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...utils.compression import WIRE_BYTES as _wire_bytes
from ..message_define import MyMessage
from .fedml_aggregator import FedMLAggregator

_rounds_total = metrics.counter(
    "fedml_rounds_completed_total", "Federated rounds completed",
    labels=("run_id",))
_round_seconds = metrics.histogram(
    "fedml_round_seconds", "Wall-clock duration of a federated round",
    labels=("run_id",),
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0, 1800.0))
_clients_reported = metrics.gauge(
    "fedml_round_clients_reported",
    "Client results aggregated in the last completed round",
    labels=("run_id",))
_current_round = metrics.gauge(
    "fedml_current_round", "Round index the server is currently on",
    labels=("run_id",))
_hb_misses = metrics.counter(
    "fedml_heartbeat_misses_total",
    "Clients declared dead by the heartbeat failure detector",
    labels=("run_id",))
_resumed_round = metrics.gauge(
    "fedml_resumed_from_round",
    "Round index this server restored from a crash-resume checkpoint "
    "(absent when the run started fresh)", labels=("run_id",))
_stragglers_dropped = metrics.counter(
    "fedml_round_stragglers_dropped_total",
    "Clients dropped from a round by the deadline pacer (solicited but "
    "unreported when the round deadline fired)", labels=("run_id",))
_preempted_round = metrics.gauge(
    "fedml_preempted_at_round",
    "Round index at which this server drained for a pod preemption "
    "(absent when the run was never preempted)", labels=("run_id",))


def fleet_size(args: Any) -> int:
    """Physical client ranks per round: K plus the straggler-tolerance
    over-provision margin, capped by the population.  The SINGLE source of
    truth shared by the runner (how many client processes to spawn) and
    the server's cohort sampling — if these drifted apart the server would
    solicit ranks with no running client behind them."""
    return min(int(args.client_num_per_round)
               + int(getattr(args, "over_provision", 0) or 0),
               int(args.client_num_in_total))


class FedMLServerManager(FedMLCommManager):
    def __init__(self, args: Any, aggregator: FedMLAggregator, comm=None,
                 rank: int = 0, client_num: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, comm, rank, client_num + 1, backend)
        self.aggregator = aggregator
        self.round_num = int(args.comm_round)
        self.args.round_idx = 0
        self.client_num = client_num
        self.client_online_status: Dict[int, bool] = {}
        self.client_id_list_in_this_round: List[int] = []
        self.data_silo_index_of_client: List[int] = []
        self.is_initialized = False
        # elastic membership (new capability, SURVEY §7 item 10):
        # round_timeout_s > 0 → aggregate with whoever reported once the
        # timer fires (≥ min_clients_per_round); a timed-out round below the
        # minimum RE-SOLICITS the missing clients before extending; init
        # force-starts after the timeout once ≥ min clients are online
        self.round_timeout_s = float(
            getattr(args, "round_timeout_s", 0) or 0)
        self.min_clients = int(
            getattr(args, "min_clients_per_round", 1) or 1)
        # straggler-tolerant pacing (docs/ROBUSTNESS.md "Data-plane
        # robustness"): over-provision selects K+m clients while the
        # aggregator's completion target stays K (the first K arrivals
        # close the round); the round deadline aggregates whatever arrived
        # once it fires (never below min_aggregation_clients, extending by
        # grace periods below that), dropping unreported solicited ranks
        # exactly like heartbeat-dead clients
        self.over_provision = int(getattr(args, "over_provision", 0) or 0)
        self.round_deadline_s = float(
            getattr(args, "round_deadline_s", 0) or 0)
        self.deadline_grace_s = float(
            getattr(args, "round_deadline_grace_s", 2.0) or 2.0)
        self.min_agg_clients = max(1, int(
            getattr(args, "min_aggregation_clients", 1) or 1))
        self._deadline_timer: Optional[threading.Timer] = None
        #: ranks the deadline pacer dropped while they were (presumably)
        #: still training: they already hold the next round's broadcast in
        #: their queue, so their next heartbeat must NOT trigger a
        #: late-join catch-up re-send (a duplicate full training pass);
        #: an explicit ONLINE re-announce (a restarted client) still does
        self._deadline_dropped: set = set()
        #: ranks already re-solicited after a quarantined upload this
        #: round — bounded so a persistently-byzantine client costs at
        #: most ``admission_resolicit_max`` extra training passes per
        #: round before the deadline pacer completes without it
        self._quarantine_resolicits: Dict[int, int] = {}
        self._resolicit_max = int(
            getattr(args, "admission_resolicit_max", 1) or 0)
        # wire compression (docs/ROBUSTNESS.md "Asynchronous rounds"):
        # negotiated per link — a client advertises capability tokens on
        # its status message; broadcasts to capable links carry the
        # quantized model + the uplink codec assignment, everyone else
        # keeps exchanging raw pytrees.  ``_round_ref`` is the DECODED
        # broadcast (identical on both ends by construction), the exact
        # reference compressed uplink deltas are reconstructed against.
        from ...utils.compression import parse_wire_compression

        self._wire_spec = parse_wire_compression(
            getattr(args, "wire_compression", None))
        self._peer_caps: Dict[int, tuple] = {}
        self._round_ref: Optional[Any] = None
        #: (round_idx, enc_payload, decoded) — the global only changes
        #: when round_idx advances, so re-solicits/catch-ups/async
        #: re-dispatches within a round reuse one full-model encode
        self._enc_cache: Optional[tuple] = None
        self._round_lock = threading.RLock()
        self._round_timer: Optional[threading.Timer] = None
        self._init_timer: Optional[threading.Timer] = None
        self._caught_up_this_round: set = set()
        # client-reported training metrics for the round in flight, keyed
        # by sender rank; summarized onto the round span at completion
        self._round_train_metrics: Dict[int, Dict] = {}
        # distributed tracing: one root span per run, one parent span per
        # round; the round span's context travels on every broadcast so
        # client + aggregator spans stitch under it
        self._run_span: Optional[tracing.Span] = None
        self._round_span: Optional[tracing.Span] = None
        self._run_label = str(getattr(args, "run_id", "0"))
        # heartbeat failure detector (phi-accrual-lite): a client silent
        # for miss_threshold × interval is declared dead and dropped from
        # the round immediately — no waiting out the full round timer; a
        # rejoining client is re-admitted with the current global model
        # through the late-join catch-up path
        self._hb_interval = float(
            getattr(args, "heartbeat_interval_s", 0) or 0)
        self._hb_miss_threshold = int(
            getattr(args, "heartbeat_miss_threshold", 3) or 3)
        self._last_seen: Dict[int, float] = {}
        # only ranks that have actually emitted a heartbeat are judged by
        # the detector: a client launched WITHOUT --heartbeat-interval-s is
        # silent between uploads by design, and declaring it dead off a
        # stale status/upload sighting would shrink every round to the
        # fastest clients
        self._hb_peers: set = set()
        self._hb_stop = threading.Event()
        # crash-resume (RoundCheckpointer wiring): round index, global
        # params and the received-results set persist per round; a
        # restarted server picks up at round k and re-solicits only the
        # missing clients
        self._ckpt = None
        self._ckpt_writer = None
        self._resumed = False
        self._finishing = False
        ckpt_dir = getattr(args, "checkpoint_dir", None)
        if ckpt_dir:
            from concurrent.futures import ThreadPoolExecutor

            from ...utils.checkpoint import RoundCheckpointer

            self._ckpt = RoundCheckpointer(str(ckpt_dir))
            # writes happen OFF the receive-loop thread: a multi-second
            # orbax save under _round_lock would block heartbeat dispatch
            # long enough for the failure detector to falsely declare live
            # clients dead.  One worker keeps writes ordered.
            self._ckpt_writer = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="round-ckpt-writer")
            resume = getattr(args, "resume_from", None)
            if resume is not None and resume is not False and resume != "":
                self._try_resume(resume)
        # round-boundary preemption (pod scheduler contract): a drain
        # file (FEDML_TPU_DRAIN_FILE) or SIGUSR1 asks this server to stop
        # at the NEXT round boundary — the boundary checkpoint is already
        # persisted by then, so the requeued job resumes with zero lost
        # rounds and zero duplicate-counted uploads.  The launcher turns
        # ``args.preempted_at_round`` into exit code 75 (EX_TEMPFAIL).
        self._drain_file = (os.environ.get("FEDML_TPU_DRAIN_FILE")
                            or getattr(args, "drain_file", None))
        self._drain_event = threading.Event()
        self.args.preempted_at_round = None
        try:
            signal.signal(signal.SIGUSR1,
                          lambda *_: self._drain_event.set())
        except ValueError:
            pass  # not the main thread (in-process jobs poll the file)
        # elastic resize (pod scheduler contract, beside the drain file):
        # FEDML_TPU_RESIZE_FILE announces a new gang size; the next round
        # boundary checkpoints, re-meshes IN PLACE and acks — no requeue
        # round-trip.  A failed re-mesh degrades to the drain path above,
        # so a resize can never end worse than a preemption.
        self._resize_file = (os.environ.get("FEDML_TPU_RESIZE_FILE")
                             or getattr(args, "resize_file", None))
        self._resize_acked: Optional[Dict] = None
        #: monotonic deadline before which heartbeat/deadline verdicts
        #: are suppressed — the announced-resize pause must not read as
        #: client death (the ``_finishing``-guard idiom)
        self._resize_guard_until = 0.0
        slots_env = os.environ.get("FEDML_TPU_SLOTS", "")
        self._mesh_slots: Optional[int] = (
            len([s for s in slots_env.split(",") if s != ""])
            or None)

    def run(self) -> None:
        self._start_hb_monitor()
        if self._resumed:
            with self._round_lock:
                self._resume_training()
        super().run()

    def _cohort_size(self) -> int:
        """Clients to solicit per round — the aggregator's completion
        target stays K, so the slowest m solicited clients never gate the
        round."""
        return fleet_size(self.args)

    def finish(self) -> None:
        self._hb_stop.set()
        with self._round_lock:
            self._finishing = True
            for timer in (self._round_timer, self._init_timer,
                          self._deadline_timer):
                if timer is not None:
                    timer.cancel()
        super().finish()
        if self._ckpt_writer is not None:
            # drain queued round-state writes (each is small and bounded);
            # the worker never takes _round_lock so this cannot deadlock
            self._ckpt_writer.shutdown(wait=True)
            self._ckpt_writer = None

    # -- crash-resume --------------------------------------------------------
    def _try_resume(self, resume: Any) -> None:
        # "latest" (or a bare true flag) → newest step; anything numeric
        # is an explicit round index
        if resume is True or str(resume).strip().lower() in (
                "latest", "true", "yes"):
            step = None
        else:
            step = int(resume)
        state = self._ckpt.restore(step)
        if state is None:
            logging.warning(
                "server: resume_from=%r but no usable checkpoint in %s — "
                "starting fresh", resume, self._ckpt.dir)
            return
        self.args.round_idx = int(np.asarray(state["round_idx"]))
        self.aggregator.set_global_model_params(state["global_model"])
        self.aggregator.restore_round_state(state)
        self._resumed = True
        _resumed_round.labels(run_id=self._run_label).set(
            int(self.args.round_idx))
        logging.warning(
            "server: resumed at round %d with %d/%d results already "
            "received", self.args.round_idx, self.aggregator.receive_count(),
            self.client_num)

    def _resume_training(self) -> None:
        """Re-enter round k from checkpointed state.  Caller holds
        ``_round_lock``.  No blanket broadcast here: already-received
        clients must not retrain, and the missing ones are re-solicited
        individually as they re-announce (status/heartbeat → late-join
        catch-up) or by the elastic round timer for silent survivors."""
        with self._round_lock:
            if self.args.round_idx >= self.round_num:
                logging.warning(
                    "server: checkpoint says the run already completed "
                    "(round %d/%d) — broadcasting FINISH and exiting",
                    self.args.round_idx, self.round_num)
                self.send_finish_to_all()
                mlops.log_aggregation_status("FINISHED")
                self.finish()
                return
            mlops.log_aggregation_status("RUNNING")
            self._run_span = tracing.start_span(
                "fed_run", run_id=self._run_label, rounds=self.round_num,
                resumed_at=int(self.args.round_idx))
            self.is_initialized = True
            self.client_id_list_in_this_round = self.aggregator.client_sampling(
                self.args.round_idx, int(self.args.client_num_in_total),
                self._cohort_size())
            self.data_silo_index_of_client = self.aggregator.data_silo_selection(
                self.args.round_idx, int(self.args.client_num_in_total),
                len(self.client_id_list_in_this_round))
            self._open_round_span()
            self._arm_round_timer()
            self._arm_deadline_timer()
            if self.aggregator.check_whether_all_receive():
                # the crash hit AFTER the last upload was persisted but BEFORE
                # aggregation: no client is missing, so no upload will ever
                # re-trigger completion — aggregate now
                logging.warning("server: resumed round %d already has every "
                                "result — aggregating immediately",
                                self.args.round_idx)
                self._complete_round()

    def _persist_round_state(self) -> None:
        """Checkpoint the in-flight round (called on every accepted upload
        and at each round boundary; caller holds ``_round_lock``).  The
        snapshot is taken under the lock — cheap reference captures, the
        pytrees are never mutated in place — and the write runs on the
        single-worker checkpoint thread so the lock is released while the
        bytes land."""
        if self._ckpt is None or self._ckpt_writer is None:
            return
        state = {"round_idx": int(self.args.round_idx),
                 "global_model": self.aggregator.get_global_model_params()}
        state.update(self.aggregator.export_round_state())
        self._ckpt_writer.submit(
            self._write_round_state, int(self.args.round_idx), state)

    def _write_round_state(self, round_idx: int, state: Dict) -> None:
        try:
            self._ckpt.save(round_idx, state, force=True)
        except Exception:  # noqa: BLE001 — a failed checkpoint write must
            # not kill the round it is trying to protect
            logging.exception("server: round checkpoint save failed "
                              "(continuing without it)")

    # -- heartbeat failure detection -----------------------------------------
    def _start_hb_monitor(self) -> None:
        if self._hb_interval <= 0:
            return
        t = threading.Thread(target=self._hb_monitor_loop, daemon=True,
                             name="hb-monitor")
        t.start()

    def _hb_monitor_loop(self) -> None:
        deadline = self._hb_miss_threshold * self._hb_interval
        while not self._hb_stop.wait(self._hb_interval):
            now = time.monotonic()
            if now < self._resize_guard_until:
                # announced re-mesh in progress: the pause is the
                # server's, so no liveness verdicts until it lifts
                continue
            with self._round_lock:
                dead = [rank for rank, last in self._last_seen.items()
                        if rank in self._hb_peers
                        and self.client_online_status.get(rank)
                        and now - last > deadline]
                for rank in dead:
                    self.client_online_status[rank] = False
                    _hb_misses.labels(run_id=self._run_label).inc()
                    ledger.event("server", "heartbeat_dead",
                                 round_idx=int(self.args.round_idx),
                                 client=rank)
                if dead:
                    logging.warning(
                        "server: clients %s silent for > %d heartbeat "
                        "intervals — declared dead, dropped from round %d",
                        dead, self._hb_miss_threshold, self.args.round_idx)
                    self._note_peers_dead(dead, "heartbeat")
                    if self.is_initialized:
                        self._maybe_complete_early()

    def _note_peers_dead(self, ranks, cause: str) -> None:
        """Hook: a fault-domain verdict (heartbeat detector or deadline
        pacer) dropped ``ranks`` from the round.  The base emits nothing
        extra; tier subclasses (the hierarchical global server) add
        per-tier telemetry here.  Caller holds ``_round_lock``."""

    def handle_message_heartbeat(self, msg: Message) -> None:
        sent_at = msg.get(MyMessage.MSG_ARG_KEY_HEARTBEAT_TS)
        if sent_at is not None:
            # wall-clock transit age: coarse (cross-host clock skew) but a
            # consistently large value flags a congested/backlogged link
            # before the detector ever fires
            logging.debug("server: heartbeat from %d aged %.3fs in transit",
                          msg.get_sender_id(), time.time() - float(sent_at))
        with self._round_lock:
            self._hb_peers.add(msg.get_sender_id())
            self._mark_alive(msg.get_sender_id())

    # -- protocol ------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
            self.handle_message_client_status_update)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
            self.handle_message_receive_model_from_client)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_HEARTBEAT, self.handle_message_heartbeat)

    def handle_message_client_status_update(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        status = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_STATUS)
        client_os = msg.get(MyMessage.MSG_ARG_KEY_CLIENT_OS, "unknown")
        caps = msg.get(MyMessage.MSG_ARG_KEY_WIRE_CAPS)
        with self._round_lock:
            if caps:
                self._peer_caps[sender] = tuple(str(c) for c in caps)
            if status == MyMessage.CLIENT_STATUS_ONLINE:
                self._mark_alive(sender, announce=True)
            n_online = sum(self.client_online_status.values())
        logging.info("server: client %d (%s) status %s (%d/%d online)",
                     sender, client_os, status, n_online, self.client_num)

    def _mark_alive(self, sender: int, announce: bool = False) -> None:
        """Liveness sighting: refresh the failure detector, (re-)admit the
        client, and drive init/late-join membership.  Caller holds
        ``_round_lock`` — the status dict is read by the init-timer and
        heartbeat-monitor threads under it.

        ``announce`` marks an explicit (re)connect (an ONLINE status).
        Catch-up below must fire only on a liveness TRANSITION — an
        announce, a heartbeat from a client previously declared dead, or
        the FIRST sighting of a rank this server has no record of (a
        restarted server inherits live clients that will never re-announce
        — their first heartbeat is what re-solicits them).  A plain
        heartbeat from a client already known online and merely still
        training must NOT re-send it the round model (that would cost a
        redundant full training pass per client per round)."""
        with self._round_lock:
            if self._finishing:
                # the run is over: a late (re)announce — e.g. after a resumed
                # server found the checkpointed run already complete — must not
                # restart training or solicit dead peers
                return
            self._last_seen[sender] = time.monotonic()
            was_online = self.client_online_status.get(sender)
            self.client_online_status[sender] = True
            if was_online is False:
                logging.warning("server: client %d rejoined after being "
                                "declared dead", sender)
            if not (announce or was_online is not True):
                return
            if not announce and sender in self._deadline_dropped:
                # dropped by the deadline pacer for SLOWNESS, not death: the
                # client is alive and already holds the current broadcast in
                # its queue — a catch-up re-send would cost it a duplicate
                # training pass.  An explicit ONLINE announce (restarted
                # process, empty queue) still takes the catch-up path below.
                self._deadline_dropped.discard(sender)
                return
            self._deadline_dropped.discard(sender)
            if not self.is_initialized:
                if len(self.client_online_status) == self.client_num:
                    self._start_training()
                elif self.round_timeout_s > 0 and self._init_timer is None:
                    # elastic init: don't block forever on a client that
                    # never comes online — force-start after the timeout
                    # once ≥ min clients are here
                    self._init_timer = threading.Timer(
                        self.round_timeout_s, self._maybe_force_init)
                    self._init_timer.daemon = True
                    self._init_timer.start()
            else:
                # elastic late join / rejoin: a (re)connecting client that
                # hasn't uploaded this round is re-admitted with the round's
                # current global model — at most ONCE per round (a duplicated
                # re-announce must not trigger a redundant full training pass;
                # lost syncs are covered by the timeout's re-solicitation)
                if (sender in self._ranks_for(
                        self.client_id_list_in_this_round)
                        and sender not in self._caught_up_this_round
                        and not self.aggregator.has_received(sender - 1)):
                    logging.info("server: late-joining client %d caught up "
                                 "into round %d", sender, self.args.round_idx)
                    self._caught_up_this_round.add(sender)
                    ledger.event("server", "late_join",
                                 round_idx=int(self.args.round_idx),
                                 client=sender)
                    self._broadcast_round(only_rank=sender)

    def _maybe_force_init(self) -> None:
        with self._round_lock:
            self._init_timer = None
            if self.is_initialized:
                return
            online = sum(self.client_online_status.values())
            if online >= self.min_clients:
                logging.warning(
                    "server: init timeout — starting with %d/%d clients "
                    "online", online, self.client_num)
                self._start_training()
            else:  # keep waiting, check again after another timeout
                self._init_timer = threading.Timer(
                    self.round_timeout_s, self._maybe_force_init)
                self._init_timer.daemon = True
                self._init_timer.start()

    def _start_training(self) -> None:
        with self._round_lock:
            mlops.log_aggregation_status("RUNNING")
            self._run_span = tracing.start_span(
                "fed_run", run_id=self._run_label, rounds=self.round_num)
            self.is_initialized = True
            self._persist_round_state()   # round-0 anchor for crash-resume
            self.send_init_msg()

    def _open_round_span(self) -> None:
        with self._round_lock:
            parent = self._run_span.ctx if self._run_span else None
            self._round_span = tracing.start_span(
                "train_round", parent=parent, round=int(self.args.round_idx))
            _current_round.labels(run_id=self._run_label).set(
                int(self.args.round_idx))
            ledger.event("server", "round_start",
                         round_idx=int(self.args.round_idx),
                         expected=len(self.client_id_list_in_this_round))

    def send_init_msg(self) -> None:
        with self._round_lock:
            self.client_id_list_in_this_round = self.aggregator.client_sampling(
                self.args.round_idx, int(self.args.client_num_in_total),
                self._cohort_size())
            self.data_silo_index_of_client = self.aggregator.data_silo_selection(
                self.args.round_idx, int(self.args.client_num_in_total),
                len(self.client_id_list_in_this_round))
            self._open_round_span()
            self._broadcast_round()
            self._arm_round_timer()
            self._arm_deadline_timer()

    def _link_codec(self, rank: int) -> bool:
        """True when this link negotiated the configured wire codec (the
        peer's advertised capability tokens cover it)."""
        if self._wire_spec is None:
            return False
        from ...utils.compression import required_caps

        caps = set(self._peer_caps.get(rank, ()))
        need = set(required_caps(self._wire_spec))
        # the downlink leg quantizes the model (int8, or bf16 for a bf16
        # spec) — the peer must be able to decode it
        need.add("bf16" if self._wire_spec.kind == "bf16" else "int8")
        return need.issubset(caps)

    def _note_round_ref(self, ref: Any, raw: Optional[Any] = None) -> None:
        """Record the round's shared delta reference (hook point — the
        async manager versions these).  ``ref`` is what a CODEC link
        computes deltas against (the decoded broadcast); ``raw`` is the
        unencoded global a legacy/raw link received (defaults to ref)."""
        with self._round_lock:
            self._round_ref = ref

    def _broadcast_round(self, only_rank=None) -> None:
        """Send the current round's model to every participating rank (or
        just ``only_rank`` — an int, or a collection of ranks — for
        re-solicitation/late-join catch-up/async flush release) — one
        message per slot a rank serves.  Caller holds ``_round_lock``.

        With wire compression negotiated, capable links receive the
        quantized model plus their uplink codec assignment; the DECODED
        broadcast becomes the round's delta reference on both ends."""
        with self._round_lock:
            from ...utils.serialization import estimate_nbytes

            only = (None if only_rank is None
                    else {only_rank} if isinstance(only_rank, int)
                    else set(only_rank))
            mtype = (MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT
                     if self.args.round_idx else
                     MyMessage.MSG_TYPE_S2C_INIT_CONFIG)
            global_model = self.aggregator.get_global_model_params()
            enc_payload = None
            if self._wire_spec is not None:
                from ...utils.compression import WireCodec

                version = int(self.args.round_idx)
                if self._enc_cache is not None and self._enc_cache[0] == version:
                    _, enc_payload, decoded = self._enc_cache
                else:
                    enc_payload = WireCodec.encode_model(
                        global_model,
                        "bf16" if self._wire_spec.kind == "bf16" else "int8")
                    decoded = WireCodec.decode_model(enc_payload)
                    self._enc_cache = (version, enc_payload, decoded)
                self._note_round_ref(decoded, raw=global_model)
            else:
                self._note_round_ref(global_model)
            with flight_recorder.phase("comm", program="server/broadcast"):
                for i, rank in enumerate(
                        self._ranks_for(self.client_id_list_in_this_round)):
                    if only is not None and rank not in only:
                        continue
                    use_codec = enc_payload is not None and self._link_codec(rank)
                    msg = Message(mtype, self.get_sender_id(), rank)
                    msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                                   enc_payload if use_codec else global_model)
                    if use_codec:
                        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_ENCODED, True)
                        msg.add_params(MyMessage.MSG_ARG_KEY_WIRE_CODEC,
                                       str(getattr(self.args, "wire_compression")))
                    nbytes = estimate_nbytes(
                        enc_payload if use_codec else global_model)
                    _wire_bytes.labels(
                        run_id=self._run_label, direction="down",
                        codec=(self._wire_spec.kind if use_codec
                               else "raw")).inc(nbytes)
                    flight_recorder.note_transfer("comm", nbytes)
                    ledger.event("server", "solicit",
                                 round_idx=int(self.args.round_idx),
                                 client=rank, nbytes=int(nbytes),
                                 codec=(self._wire_spec.kind if use_codec
                                        else "raw"))
                    msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_INDEX,
                                   self.client_id_list_in_this_round[i])
                    msg.add_params(MyMessage.MSG_ARG_KEY_ROUND,
                                   self.args.round_idx)
                    if self._round_span is not None:
                        msg.add_params(MyMessage.MSG_ARG_KEY_TRACE_CTX,
                                       tracing.inject(self._round_span.ctx))
                    self.send_message(msg)

    # -- elastic round timeout ----------------------------------------------
    def _arm_round_timer(self) -> None:
        if self.round_timeout_s <= 0:
            return
        if self._round_timer is not None:
            self._round_timer.cancel()
        self._round_timer = threading.Timer(
            self.round_timeout_s, self._on_round_timeout,
            args=(self.args.round_idx,))
        self._round_timer.daemon = True
        self._round_timer.start()

    def _on_round_timeout(self, round_idx: int) -> None:
        with self._round_lock:
            if self.args.round_idx != round_idx:
                return  # round already completed normally
            got = self.aggregator.receive_count()
            if got < self.min_clients:
                # RE-SOLICIT the ranks that haven't reported (their sync or
                # upload may have been lost), then extend the deadline —
                # without this a lossy link could extend forever with idle
                # clients that never got the round
                missing = [r for r in set(self._ranks_for(
                    self.client_id_list_in_this_round))
                    if not self.aggregator.has_received(r - 1)]
                logging.warning(
                    "server: round %d timeout with only %d results "
                    "(< min %d) — re-soliciting %s and extending",
                    round_idx, got, self.min_clients, missing)
                for rank in missing:
                    self._broadcast_round(only_rank=rank)
                self._arm_round_timer()
                return
            logging.warning(
                "server: round %d timeout — aggregating %d/%d results, "
                "dropping stragglers", round_idx, got,
                len(self.client_id_list_in_this_round))
            self._round_close_reason = "timeout"
            self._complete_round()

    def _quarantine_exhausted(self, rank: int) -> bool:
        """True when this rank's uploads were quarantined this round AND
        its re-solicit budget is spent — nothing further is expected from
        it until the next round.  Caller holds ``_round_lock``."""
        with self._round_lock:
            return ((rank - 1) in self.aggregator.quarantined_this_round
                    and self._quarantine_resolicits.get(rank, 0)
                    >= self._resolicit_max)

    # -- deadline-paced rounds (straggler tolerance) -------------------------
    def _arm_deadline_timer(self, delay_s: Optional[float] = None) -> None:
        """Arm (or re-arm, for a grace extension) the round deadline.
        Caller holds ``_round_lock``."""
        if self.round_deadline_s <= 0:
            return
        if self._deadline_timer is not None:
            self._deadline_timer.cancel()
        self._deadline_timer = threading.Timer(
            self.round_deadline_s if delay_s is None else delay_s,
            self._on_round_deadline, args=(self.args.round_idx,))
        self._deadline_timer.daemon = True
        self._deadline_timer.start()

    def _on_round_deadline(self, round_idx: int) -> None:
        """Deadline fired: aggregate with whoever reported, dropping the
        stragglers from the round exactly like heartbeat-dead clients (a
        straggler that shows up later rejoins via the late-join catch-up
        path).  Below ``min_aggregation_clients`` the round is NEVER
        closed: re-solicit the missing ranks and extend by the grace
        period until the floor is met."""
        with self._round_lock:
            if self.args.round_idx != round_idx or self._finishing:
                return  # round already completed normally
            if time.monotonic() < self._resize_guard_until:
                # announced re-mesh in progress: the stall is the
                # server's, not the clients' — re-arm instead of
                # dropping anyone as a straggler
                self._arm_deadline_timer(self.deadline_grace_s)
                return
            got = self.aggregator.receive_count()
            ranks = set(self._ranks_for(self.client_id_list_in_this_round))
            # quarantined ranks DID report on time — their uploads were
            # rejected by admission control and already counted in the
            # quarantine metric; conflating them with stragglers would
            # make a data-poisoning problem read as a pacing problem
            quarantined = {r for r in ranks
                           if (r - 1) in self.aggregator.quarantined_this_round}
            missing = [r for r in ranks
                       if not self.aggregator.has_received(r - 1)]
            stragglers = [r for r in missing if r not in quarantined]
            if got < self.min_agg_clients:
                # re-solicit only ranks a retry could actually recover
                # (a quarantine-exhausted client would just be rejected
                # again) and extend by the grace period
                resend = [r for r in missing
                          if not self._quarantine_exhausted(r)]
                logging.warning(
                    "server: round %d deadline with %d results (< min "
                    "aggregation floor %d) — re-soliciting %s, extending "
                    "by %.1fs grace", round_idx, got, self.min_agg_clients,
                    resend, self.deadline_grace_s)
                for rank in resend:
                    self._broadcast_round(only_rank=rank)
                self._arm_deadline_timer(self.deadline_grace_s)
                return
            for rank in stragglers:
                self.client_online_status[rank] = False
                self._deadline_dropped.add(rank)
                _stragglers_dropped.labels(run_id=self._run_label).inc()
                ledger.event("server", "deadline_drop",
                             round_idx=int(round_idx), client=rank)
            if stragglers:
                self._note_peers_dead(stragglers, "deadline")
            logging.warning(
                "server: round %d deadline — aggregating %d/%d results, "
                "dropping stragglers %s (quarantined, not stragglers: %s)",
                round_idx, got, len(ranks), stragglers,
                sorted(quarantined))
            self._round_close_reason = "deadline"
            self._complete_round()

    def _ranks_for(self, client_ids: List[int]) -> List[int]:
        """client slots → comm ranks 1..client_num (round-robin when
        client_num_per_round < physical clients is 1:1 in this build)."""
        return [1 + (i % self.client_num)
                for i in range(len(client_ids))]

    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        local_sample_number = msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
        with self._round_lock:
            # stale check FIRST (and under the lock): a round the timeout
            # already closed must not cost a decompression, and an on-time
            # upload must not lose the race against the timer thread
            upload_round = msg.get(MyMessage.MSG_ARG_KEY_ROUND)
            if (upload_round is not None
                    and int(upload_round) != int(self.args.round_idx)):
                logging.warning("server: dropping stale round-%s upload "
                                "from client %d (now round %d)",
                                upload_round, sender, self.args.round_idx)
                return
            model_params = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
            compressed = msg.get(MyMessage.MSG_ARG_KEY_COMPRESSED_UPDATE)
            wire_update = msg.get(MyMessage.MSG_ARG_KEY_WIRE_UPDATE)
            if model_params is None and wire_update is not None:
                # negotiated codec: weights = round reference + decoded
                # delta, reconstructed inside the jitted decode path
                from ...utils.compression import decode_delta

                ref = (self._round_ref if self._round_ref is not None
                       else self.aggregator.get_global_model_params())
                model_params = decode_delta(wire_update, ref)
            if model_params is None and compressed is not None:
                # sparse delta: rebuild weights = global + Δ using OUR copy
                # of the global model for the tree structure
                import jax

                from ...utils.compression import TopKCompressor, tree_spec

                global_model = self.aggregator.get_global_model_params()
                delta = TopKCompressor().decompress(
                    compressed, tree_spec(global_model))
                model_params = jax.tree_util.tree_map(
                    lambda g, d: g + d, global_model, delta)
            if logging.getLogger().isEnabledFor(logging.DEBUG):
                # structure-only summary (shapes/dtypes/bytes, never
                # values): the sanctioned way to log a payload
                from ...utils.redact import summarize_payload

                logging.debug("server: round %d upload from client %d: %s",
                              int(self.args.round_idx), sender,
                              summarize_payload(model_params))
            train_metrics = msg.get(MyMessage.MSG_ARG_KEY_TRAIN_METRICS)
            if isinstance(train_metrics, dict) and train_metrics:
                self._round_train_metrics[sender] = train_metrics
            self._last_seen[sender] = time.monotonic()
            self.client_online_status[sender] = True
            ledger.event("server", "receive",
                         round_idx=int(self.args.round_idx),
                         client=sender, samples=local_sample_number)
            reason = self.aggregator.add_local_trained_result(
                sender - 1, model_params, local_sample_number)
            if reason is not None:
                # quarantined: the upload never entered the received set —
                # re-solicit the client like a missing upload (PR 4's
                # re-solicitation path), bounded per round so a
                # persistently-byzantine sender can't loop training
                # forever; past the bound the deadline pacer completes the
                # round without it
                n_prev = self._quarantine_resolicits.get(sender, 0)
                if n_prev < self._resolicit_max:
                    self._quarantine_resolicits[sender] = n_prev + 1
                    logging.warning(
                        "server: re-soliciting client %d after "
                        "quarantined upload (%s, attempt %d/%d)",
                        sender, reason, n_prev + 1, self._resolicit_max)
                    ledger.event("server", "resolicit",
                                 round_idx=int(self.args.round_idx),
                                 client=sender, reason=reason,
                                 attempt=n_prev + 1)
                    self._broadcast_round(only_rank=sender)
                else:
                    # budget exhausted: this rank is given up on for the
                    # round — it no longer blocks early completion, so a
                    # persistent byzantine client can't stall a run that
                    # has no deadline/timeout pacer configured
                    self._maybe_complete_early()
                return
            self._persist_round_state()
            if self.aggregator.check_whether_all_receive():
                self._complete_round()
                return
            self._maybe_complete_early()

    def _maybe_complete_early(self) -> None:
        """Elastic early completion: when every ONLINE participant has
        reported, don't idle out the full timeout waiting for ranks the
        server already knows are absent (round timer, heartbeat detector
        OR deadline pacer supplies the liveness signal).  A
        deadline-dropped straggler without heartbeats stays offline, so
        later rounds close on the survivors' uploads instead of paying
        the deadline again; a heartbeating straggler re-marks itself
        online and rounds run at deadline pace — bounded, since it may
        yet report in time.  Admission control is a fourth signal: a rank
        quarantined past its re-solicit budget is given up on for the
        round (its uploads will keep being rejected), so it must not hold
        the round open.  Caller holds ``_round_lock``."""
        with self._round_lock:
            if (self.round_timeout_s <= 0 and self._hb_interval <= 0
                    and self.round_deadline_s <= 0
                    and not self.aggregator.admission_control):
                return
            ranks = set(self._ranks_for(self.client_id_list_in_this_round))
            online = {r for r in ranks if self.client_online_status.get(r)
                      and not self._quarantine_exhausted(r)}
            if (online
                    and all(self.aggregator.has_received(r - 1) for r in online)
                    and self.aggregator.receive_count()
                    >= max(self.min_clients, self.min_agg_clients)):
                logging.info(
                    "server: round %d — all %d online participants reported; "
                    "completing without waiting for %d offline",
                    self.args.round_idx, len(online), len(ranks - online))
                self._round_close_reason = "early"
                self._complete_round()

    def _drain_requested(self) -> bool:
        """True once a pod drain signal (file or SIGUSR1) has been seen —
        latches, so a racing file removal cannot un-drain mid-boundary."""
        if self._drain_event.is_set():
            return True
        if self._drain_file and os.path.exists(self._drain_file):
            self._drain_event.set()
            return True
        return False

    # -- elastic resize (round-boundary re-mesh) -----------------------------
    def _resize_requested(self) -> Optional[int]:
        """The announced new gang size, or None.  Latches per announce:
        a request this server already acked is ignored until the
        scheduler clears the file (fast rounds can complete before the
        next scheduler tick collects the ack)."""
        if not self._resize_file:
            return None
        from ...scheduler.pod.runners import read_resize

        req = read_resize(self._resize_file)
        if req is None or req == self._resize_acked:
            return None
        return int(req["slots"])

    def _perform_resize(self, target: int) -> bool:
        """Re-mesh in place at the round boundary: the boundary
        checkpoint is already queued (`_persist_round_state` ran first),
        so re-building device state at the new slot count and restoring
        onto it loses nothing.  The aggregator owns its device layout —
        it re-meshes through its ``remesh(n_slots)`` hook when it has
        one; a host-funnel aggregator (the CPU-proxy data-parallel case)
        has no device mesh to rebuild and resizes for free.  Returns
        False when the re-mesh failed — the caller degrades to the
        preempt ladder.  Caller holds ``_round_lock``."""
        from ...scheduler.pod.runners import ack_resize, read_resize

        t0 = time.monotonic()
        prev = self._mesh_slots
        hb_deadline = self._hb_miss_threshold * self._hb_interval
        self._resize_guard_until = t0 + max(30.0, 2 * hb_deadline)
        try:
            remesh = getattr(self.aggregator, "remesh", None)
            if callable(remesh):
                remesh(int(target))
            self._mesh_slots = int(target)
            now = time.monotonic()
            downtime = now - t0
            # the pause is ours, not the clients': refresh every liveness
            # stamp so the detector never bills it to them
            for rank in list(self._last_seen):
                self._last_seen[rank] = now
            self._resize_guard_until = now
            self._resize_acked = read_resize(self._resize_file)
            ack_resize(self._resize_file, "ok", int(target),
                       downtime_s=round(downtime, 6),
                       round=int(self.args.round_idx))
            ledger.event("server", "resize",
                         round_idx=int(self.args.round_idx),
                         outcome="ok", downtime_s=round(downtime, 6),
                         **{"from": prev, "to": int(target)})
            logging.info(
                "server: re-meshed %s -> %d slots in place at round "
                "boundary %d (%.3fs pause)", prev, target,
                self.args.round_idx, downtime)
            return True
        except Exception:  # noqa: BLE001 — a failed re-mesh must degrade
            # to the preempt ladder, never take the run down mid-round
            logging.exception(
                "server: in-place resize to %d slots failed — falling "
                "back to preempt", target)
            self._resize_guard_until = 0.0
            try:
                ack_resize(self._resize_file, "failed", int(target),
                           round=int(self.args.round_idx))
            except OSError:
                pass
            ledger.event("server", "resize",
                         round_idx=int(self.args.round_idx),
                         outcome="failed", downtime_s=None,
                         **{"from": prev, "to": int(target)})
            return False

    def _preempt_at_boundary(self) -> None:
        """Preempted at this boundary: the round_idx checkpoint is
        queued on the writer and finish() drains it before exit, so the
        requeued dispatch resumes exactly here — no lost round, and the
        aggregator's received set is empty (no upload can be
        double-counted).  Clients get FINISH so the process tree winds
        down cleanly; resume re-launches the full cohort.  Callers hold
        ``_round_lock``; re-taking the RLock keeps the span handoff
        guarded even so."""
        logging.info("################ DRAIN at round boundary %d — "
                     "preempting (checkpoint saved)",
                     self.args.round_idx)
        self.args.preempted_at_round = int(self.args.round_idx)
        _preempted_round.labels(run_id=self._run_label).set(
            int(self.args.round_idx))
        ledger.event("server", "preempt",
                     round_idx=int(self.args.round_idx))
        self.send_finish_to_all()
        mlops.log_aggregation_status("PREEMPTED")
        with self._round_lock:
            if self._run_span is not None:
                self._run_span.set_attr(
                    "preempted_at_round", int(self.args.round_idx))
                self._run_span.end()
                self._run_span = None
        self.finish()

    def _complete_round(self) -> None:
        """Aggregate (possibly a partial set), test, advance or finish.
        Caller must hold ``_round_lock``."""
        with self._round_lock:
            if self._round_timer is not None:
                self._round_timer.cancel()
            if self._deadline_timer is not None:
                self._deadline_timer.cancel()
            closed = getattr(self, "_round_close_reason", None) or "full"
            self._round_close_reason = None
            mlops.event("server.wait", False, self.args.round_idx)
            n_reported = self.aggregator.receive_count()
            # aggregation + eval run UNDER the round span's context so the
            # aggregator's own spans nest into this round's trace subtree
            with tracing.use_ctx(
                    self._round_span.ctx if self._round_span else None):
                self.aggregator.aggregate()
                freq = int(getattr(self.args, "frequency_of_the_test", 1) or 1)
                if (self.args.round_idx % freq == 0
                        or self.args.round_idx == self.round_num - 1):
                    self.aggregator.test_on_server_for_all_clients(
                        self.args.round_idx)
            _clients_reported.labels(run_id=self._run_label).set(n_reported)
            _rounds_total.labels(run_id=self._run_label).inc()
            losses = [m.get("train_loss")
                      for m in self._round_train_metrics.values()
                      if isinstance(m.get("train_loss"), (int, float))]
            self._round_train_metrics = {}
            if self._round_span is not None:
                if losses:
                    self._round_span.set_attr(
                        "mean_client_train_loss", sum(losses) / len(losses))
                self._round_span.set_attr("clients_reported", n_reported)
                _round_seconds.labels(run_id=self._run_label).observe(
                    self._round_span.end())
                self._round_span = None
            ledger.event("server", "round_close",
                         round_idx=int(self.args.round_idx), closed=closed,
                         reported=int(n_reported),
                         expected=len(self.client_id_list_in_this_round))
            slo.check_round_boundary(int(self.args.round_idx))

            self.args.round_idx += 1
            # boundary checkpoint: next round index + freshly aggregated global
            # params, received set cleared by aggregate()
            self._persist_round_state()
            if self.args.round_idx >= self.round_num:
                ledger.event("server", "run_finish",
                             round_idx=int(self.args.round_idx),
                             rounds=int(self.round_num))
                self.send_finish_to_all()
                mlops.log_aggregation_status("FINISHED")
                if self._run_span is not None:
                    self._run_span.end()
                    self._run_span = None
                self.finish()
                return
            if self._drain_requested():
                self._preempt_at_boundary()
                return
            target = self._resize_requested()
            if target is not None and not self._perform_resize(target):
                # fallback ladder rung two: the in-place re-mesh failed,
                # so degrade to the drain path — the boundary checkpoint
                # is already saved and the scheduler requeues with resume
                self._preempt_at_boundary()
                return
            # next round
            self._caught_up_this_round = set()
            self._quarantine_resolicits = {}
            self.client_id_list_in_this_round = self.aggregator.client_sampling(
                self.args.round_idx, int(self.args.client_num_in_total),
                self._cohort_size())
            mlops.event("server.wait", True, self.args.round_idx)
            self._open_round_span()
            self._broadcast_round()
            self._arm_round_timer()
            self._arm_deadline_timer()

    def send_finish_to_all(self) -> None:
        for rank in range(1, self.client_num + 1):
            msg = Message(MyMessage.MSG_TYPE_S2C_FINISH,
                          self.get_sender_id(), rank)
            self.send_message(msg)
