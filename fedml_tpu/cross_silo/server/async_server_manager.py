"""Buffered-async cross-silo server (FedBuff-style, docs/ROBUSTNESS.md
"Asynchronous rounds").

The sync server is a barrier: round T closes only when K uploads for
round T arrive, so one slow WAN link gates every silo.  This manager
removes the barrier:

* every ADMITTED upload is folded into a buffer as it arrives, weighted
  by ``n_samples · f(T - t)`` where ``t`` is the server version the
  client trained against and ``f`` the staleness decay
  (``ml/aggregator/staleness.py``);
* the buffer FLUSHES into the global model every ``async_buffer_k``
  updates or ``async_flush_s`` seconds (whichever first), advancing the
  server version — a flush is this mode's "round";
* a client is re-dispatched the current global the moment its upload is
  handled, so silos train continuously; a client already at the frontier
  parks until the next flush (guaranteeing at most ONE upload per client
  per version, which is what makes the ``(sender, client_round)`` dedup
  key sound).

Composition with the robustness stack is strict and order-matters:

1. **dedup** (keep-first on ``(sender, client_round)``) — transport-level
   duplicates never fold twice;
2. **staleness cutoff** — an update older than ``async_staleness_cutoff``
   versions (e.g. a retransmit that survived past the reliable plane's
   deadline) is counted ``expired_stale``, ACKed (the reliable wrapper
   ACKed on delivery, below this layer) and DROPPED — it is *lateness*,
   not hostility, so it must NOT be quarantined, and it can never re-open
   a flushed buffer;
3. **admission control** — the same quarantine screen as the sync path,
   BEFORE the buffer: poison is rejected outright, never merely
   down-weighted;
4. **robust aggregation** — the flush funnels through
   ``FedMLAggregator.aggregate_buffer`` → the ServerAggregator hooks →
   ``FedMLAggOperator`` with ``--robust-agg``, so whatever slipped past
   admission still meets the robust operator with its staleness-decayed
   weight.

The sync pacers (``round_timeout_s`` / ``round_deadline_s`` /
over-provision) are barrier machinery and are inert here — the flush
trigger pair is the async pacer.  The heartbeat failure detector and
late-join catch-up still apply unchanged.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ...core import mlops
from ...core.mlops import ledger, metrics, slo, tracing
from ...core.distributed.communication.message import Message
from ...ml.aggregator.staleness import parse_staleness, staleness_weight
from ..message_define import MyMessage
from .fedml_aggregator import FedMLAggregator
from .fedml_server_manager import FedMLServerManager

_async_updates = metrics.counter(
    "fedml_async_updates_total",
    "Uploads handled by the buffered-async server, by outcome (folded | "
    "expired_stale | duplicate | quarantined)",
    labels=("run_id", "outcome"))
_async_flushes = metrics.counter(
    "fedml_async_flushes_total",
    "Buffer flushes (async round completions), by trigger (count | timer "
    "| drain)", labels=("run_id", "trigger"))
_async_buffer = metrics.gauge(
    "fedml_async_buffer_size", "Updates currently buffered, not yet flushed",
    labels=("run_id",))
_async_staleness_hist = metrics.histogram(
    "fedml_async_update_staleness",
    "Staleness (server version - client round) of folded updates",
    labels=("run_id",),
    buckets=(0.0, 1.0, 2.0, 3.0, 5.0, 8.0, 13.0, 21.0))

#: bound on the (sender, client_round) keep-first window
_DEDUP_WINDOW = 4096

#: sentinel: a compressed upload whose trained-against delta reference is
#: no longer held (e.g. the version predates a crash-resume) — the update
#: cannot be reconstructed and is dropped as expired_stale
_MISSING_REF = object()


class AsyncFedMLServerManager(FedMLServerManager):
    def __init__(self, args: Any, aggregator: FedMLAggregator, comm=None,
                 rank: int = 0, client_num: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, aggregator, comm, rank, client_num, backend)
        k_default = max(1, int(args.client_num_per_round))
        buffer_k = int(getattr(args, "async_buffer_k", 0) or 0)
        flush_s = float(getattr(args, "async_flush_s", 0) or 0)
        if buffer_k < 0 or flush_s < 0:
            # fail at startup like a malformed staleness/codec spec —
            # a negative k is truthy and would silently degenerate to
            # flush-on-every-upload
            raise ValueError(
                f"async_buffer_k ({buffer_k}) and async_flush_s "
                f"({flush_s}) must be >= 0 (0 = use the default trigger)")
        self.buffer_k = buffer_k or k_default
        self.flush_s = flush_s
        self.staleness_cutoff = int(
            getattr(args, "async_staleness_cutoff", 10) or 10)
        self.server_lr = float(getattr(args, "async_server_lr", 1.0) or 1.0)
        # parse at construction so a typo'd spec fails at startup
        self._staleness_spec = parse_staleness(
            getattr(args, "async_staleness", None))
        #: (weight, model, sender, client_round) awaiting the next flush
        self._buffer: List[Tuple[float, Any, int, int]] = []
        #: keep-first dedup over (sender, client_round) — NOT (sender,
        #: round-index-of-the-received-set) like the sync path: a client
        #: legitimately uploads once per version it trained, and only a
        #: transport duplicate repeats a (sender, version) pair
        self._seen_uploads: "OrderedDict" = OrderedDict()
        #: ranks parked at the frontier (uploaded for the current version;
        #: released by the next flush)
        self._waiting: set = set()
        #: rank → last server version dispatched to it
        self._dispatched_version: Dict[int, int] = {}
        #: version → delta reference (the decoded broadcast) for decoding
        #: compressed uploads trained against an OLDER version; bounded by
        #: the staleness cutoff — anything older is expired_stale anyway
        self._version_refs: "OrderedDict" = OrderedDict()
        self._last_flush = time.monotonic()
        self._flush_stop = threading.Event()

    # -- sync-barrier machinery, inert in async mode -------------------------
    def _arm_round_timer(self) -> None:   # the flush pair is the pacer
        return

    def _arm_deadline_timer(self, delay_s: Optional[float] = None) -> None:
        return

    def _maybe_complete_early(self) -> None:
        # no early round-close in async (there is no barrier to close),
        # but a heartbeat-dead declaration shrinks the online set — the
        # drain trigger must re-fire or survivors parked at the frontier
        # stay gated on a dead silo's never-coming upload forever
        with self._round_lock:
            if self.is_initialized and not self._finishing:
                self._maybe_flush_drained()

    # -- lifecycle -----------------------------------------------------------
    def run(self) -> None:
        if self.flush_s > 0:
            t = threading.Thread(target=self._flush_loop, daemon=True,
                                 name="async-flush-timer")
            t.start()
        super().run()

    def finish(self) -> None:
        self._flush_stop.set()
        super().finish()

    def _resume_training(self) -> None:
        """Crash-resume, async flavor: restore version + global and
        re-dispatch the frontier to everyone as they re-announce.  The
        in-flight buffer is NOT checkpointed (its updates re-arrive from
        re-dispatched clients within a staleness window) — only flushed
        state survives, which is exactly the effectively-once guarantee
        the sync path gives per round."""
        if self.args.round_idx >= self.round_num:
            logging.warning(
                "async server: checkpoint says the run already completed "
                "(version %d/%d) — broadcasting FINISH and exiting",
                self.args.round_idx, self.round_num)
            self.send_finish_to_all()
            mlops.log_aggregation_status("FINISHED")
            self.finish()
            return
        mlops.log_aggregation_status("RUNNING")
        self._run_span = tracing.start_span(
            "fed_run", run_id=self._run_label, rounds=self.round_num,
            resumed_at=int(self.args.round_idx))
        self.is_initialized = True
        self.client_id_list_in_this_round = self.aggregator.client_sampling(
            self.args.round_idx, int(self.args.client_num_in_total),
            self._cohort_size())
        self.data_silo_index_of_client = self.aggregator.data_silo_selection(
            self.args.round_idx, int(self.args.client_num_in_total),
            len(self.client_id_list_in_this_round))
        self._open_round_span()
        self._broadcast_round()

    # -- dispatch bookkeeping ------------------------------------------------
    def _note_round_ref(self, ref: Any, raw: Optional[Any] = None) -> None:
        """Version the delta references: a compressed upload trained
        against version t decodes against ref[t], not the frontier.  Both
        flavors are kept — a CODEC link's delta is against the DECODED
        broadcast, a legacy TopK link's against the RAW global it was
        sent (reconstructing the latter against the dequantized ref would
        bake the whole-model quantization error into every upload)."""
        super()._note_round_ref(ref, raw)
        version = int(self.args.round_idx)
        self._version_refs[version] = (ref, ref if raw is None else raw)
        while len(self._version_refs) > self.staleness_cutoff + 2:
            self._version_refs.popitem(last=False)

    def _ref_for(self, client_round: int, raw: bool = False) -> Any:
        """Delta reference the client trained against, or ``None`` when
        that version's reference is gone (e.g. crash-resume only restores
        the frontier) — reconstructing against any OTHER version's
        reference would silently corrupt the update by the inter-version
        model delta, and the corruption passes admission (finite, right
        shapes), so the caller must drop the upload instead."""
        pair = self._version_refs.get(int(client_round))
        if pair is not None:
            return pair[1] if raw else pair[0]
        return None

    def _broadcast_round(self, only_rank=None) -> None:
        super()._broadcast_round(only_rank)
        version = int(self.args.round_idx)
        ranks = (set(self._ranks_for(self.client_id_list_in_this_round))
                 if only_rank is None
                 else {only_rank} if isinstance(only_rank, int)
                 else set(only_rank))
        for rank in ranks:
            self._dispatched_version[rank] = version
            self._waiting.discard(rank)

    def _redispatch(self, rank: int) -> None:
        """Hand ``rank`` its next unit of work: the current global if it
        hasn't trained this version yet, else park it until the next
        flush.  Caller holds ``_round_lock``."""
        if self._finishing:
            return
        version = int(self.args.round_idx)
        if self._dispatched_version.get(rank, -1) >= version:
            self._waiting.add(rank)
            ledger.event("async", "park", round_idx=version, client=rank)
            self._maybe_flush_drained()
            return
        self._broadcast_round(only_rank=rank)

    def _maybe_flush_drained(self) -> None:
        """Every online participant is parked at the frontier → nothing
        more can arrive, so waiting for the count/timer trigger would
        idle the fleet (or deadlock it when ``async_buffer_k`` exceeds
        the cohort and no timer is armed).  Caller holds
        ``_round_lock``."""
        ranks = set(self._ranks_for(self.client_id_list_in_this_round))
        active = [r for r in ranks
                  if self.client_online_status.get(r)
                  and r not in self._waiting]
        if active:
            return
        if self._buffer:
            self._flush("drain")
            return
        # Empty buffer with every online silo parked.  A rank parked by a
        # transport duplicate while still training its outstanding
        # dispatch will unpark things when that upload lands; a rank
        # whose quarantine re-solicit budget is spent never will.  When
        # NO parked rank owes an upload, no admissible update can ever
        # arrive and no flush will ever release the fleet — abort
        # cleanly instead of hanging forever.
        online = [r for r in ranks if self.client_online_status.get(r)]
        if not online:
            return      # everyone offline: the failure detector's rejoin
            # path (late-join catch-up) is the wake-up mechanism
        for r in online:
            if (self._quarantine_resolicits.get(r, 0) < self._resolicit_max
                    and (r, self._dispatched_version.get(r, -1))
                    not in self._seen_uploads):
                return  # r still owes its dispatched upload
        logging.error(
            "async server: every online silo is parked with an EMPTY "
            "buffer and no upload outstanding (quarantine re-solicit "
            "budgets spent at version %d) — the run cannot make progress, "
            "aborting", int(self.args.round_idx))
        self.send_finish_to_all()
        mlops.log_aggregation_status("FAILED")
        if self._run_span is not None:
            self._run_span.end()
            self._run_span = None
        self.finish()

    # -- the async upload path -----------------------------------------------
    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        with self._round_lock:
            if self._finishing:
                return
            version = int(self.args.round_idx)
            client_round = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND, version))
            self._last_seen[sender] = time.monotonic()
            self.client_online_status[sender] = True
            key = (sender, client_round)
            if key in self._seen_uploads:
                # keep-first: a transport duplicate (or forged replay) of
                # an already-folded (sender, version) pair never folds
                # twice.  Re-dispatch is idempotent (an already-current
                # rank just parks), and it un-sticks a restarted client
                # whose pre-restart upload was the one that counted.
                _async_updates.labels(run_id=self._run_label,
                                      outcome="duplicate").inc()
                ledger.event("async", "duplicate", round_idx=version,
                             client=sender, trained_version=client_round)
                logging.debug("async server: duplicate upload %s", key)
                self._redispatch(sender)
                return
            staleness = version - client_round
            if staleness > self.staleness_cutoff:
                # past the staleness cutoff (e.g. a retransmit that beat
                # the reliable plane's deadline into a much later
                # version): expired, NOT adversarial.  The reliable
                # wrapper already ACKed on delivery; marking the key seen
                # makes the drop idempotent; the flushed buffers it
                # missed stay closed.  The silo is alive and its work is
                # worthless — hand it the frontier immediately.
                self._seen_uploads[key] = True
                self._trim_dedup()
                _async_updates.labels(run_id=self._run_label,
                                      outcome="expired_stale").inc()
                ledger.event("async", "expired", round_idx=version,
                             client=sender, staleness=staleness)
                logging.warning(
                    "async server: EXPIRED upload from %d (trained v%d, "
                    "now v%d > cutoff %d) — dropped, re-dispatching",
                    sender, client_round, version, self.staleness_cutoff)
                self._redispatch(sender)
                return
            model_params = self._decode_upload(msg, client_round)
            if model_params is _MISSING_REF:
                # a delta we can no longer reconstruct (its version's
                # reference predates a crash-resume): same treatment as
                # past-cutoff lateness — drop, never quarantine, and hand
                # the silo the frontier so its next delta is decodable
                self._seen_uploads[key] = True
                self._trim_dedup()
                _async_updates.labels(run_id=self._run_label,
                                      outcome="expired_stale").inc()
                ledger.event("async", "expired", round_idx=version,
                             client=sender, staleness=staleness,
                             reason="missing_ref")
                logging.warning(
                    "async server: upload from %d is a delta against "
                    "version %d whose reference is no longer held "
                    "(crash-resume?) — dropped as expired_stale, "
                    "re-dispatching", sender, client_round)
                self._redispatch(sender)
                return
            train_metrics = msg.get(MyMessage.MSG_ARG_KEY_TRAIN_METRICS)
            if isinstance(train_metrics, dict) and train_metrics:
                self._round_train_metrics[sender] = train_metrics
            reason = self.aggregator.admission_check(model_params)
            if reason is not None:
                # quarantined ≠ stale: poison is rejected outright.  The
                # key is NOT marked seen — a re-trained (honest) retry for
                # this version must get screened, not dedup-dropped.
                _async_updates.labels(run_id=self._run_label,
                                      outcome="quarantined").inc()
                ledger.event("async", "quarantined", round_idx=version,
                             client=sender, reason=reason)
                self.aggregator.quarantined_this_round[sender - 1] = reason
                n_prev = self._quarantine_resolicits.get(sender, 0)
                if n_prev < self._resolicit_max:
                    self._quarantine_resolicits[sender] = n_prev + 1
                    logging.warning(
                        "async server: QUARANTINED upload from %d (%s) — "
                        "re-soliciting (attempt %d/%d)", sender, reason,
                        n_prev + 1, self._resolicit_max)
                    self._dispatched_version.pop(sender, None)
                    self._redispatch(sender)
                else:
                    # budget spent: parked without work until next flush
                    self._waiting.add(sender)
                    self._maybe_flush_drained()
                return
            n_samples = float(msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0))
            weight = n_samples * staleness_weight(self._staleness_spec,
                                                  staleness)
            self._seen_uploads[key] = True
            self._trim_dedup()
            self._buffer.append((weight, model_params, sender, client_round))
            _async_updates.labels(run_id=self._run_label,
                                  outcome="folded").inc()
            ledger.event("async", "fold", round_idx=version, client=sender,
                         staleness=staleness, weight=round(weight, 6))
            _async_staleness_hist.labels(run_id=self._run_label).observe(
                float(staleness))
            _async_buffer.labels(run_id=self._run_label).set(
                len(self._buffer))
            if len(self._buffer) >= self.buffer_k:
                self._flush("count")
            # after a count-flush the version advanced, so this hands the
            # triggering sender the NEW global; otherwise it parks or gets
            # the current one
            self._redispatch(sender)

    def _decode_upload(self, msg: Message, client_round: int) -> Any:
        """Raw | negotiated wire codec | legacy TopK payload → model tree,
        or ``_MISSING_REF`` when the upload is a delta whose
        trained-against reference is no longer held (treated as
        expired_stale by the caller).  Caller holds ``_round_lock``."""
        model_params = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        if model_params is not None:
            return model_params
        wire_update = msg.get(MyMessage.MSG_ARG_KEY_WIRE_UPDATE)
        if wire_update is not None:
            from ...utils.compression import decode_delta

            ref = self._ref_for(client_round)
            if ref is None:
                return _MISSING_REF
            return decode_delta(wire_update, ref)
        compressed = msg.get(MyMessage.MSG_ARG_KEY_COMPRESSED_UPDATE)
        if compressed is not None:
            import jax

            from ...utils.compression import TopKCompressor, tree_spec

            # a legacy TopK link received the RAW global (it never
            # negotiated the wire codec), so its delta reconstructs
            # against the raw reference, not the decoded broadcast
            ref = self._ref_for(client_round, raw=True)
            if ref is None:
                return _MISSING_REF
            delta = TopKCompressor().decompress(compressed, tree_spec(ref))
            return jax.tree_util.tree_map(lambda g, d: g + d, ref, delta)
        return None

    def _trim_dedup(self) -> None:
        while len(self._seen_uploads) > _DEDUP_WINDOW:
            self._seen_uploads.popitem(last=False)

    # -- flushing ------------------------------------------------------------
    def _flush_loop(self) -> None:
        """Timer trigger: flush a non-empty buffer every ``flush_s``.  An
        empty check restarts the window — the timer measures time the
        OLDEST buffered update has waited, not absolute cadence."""
        while not self._flush_stop.wait(
                max(0.01, self._last_flush + self.flush_s
                    - time.monotonic())):
            with self._round_lock:
                if self._finishing:
                    return
                if (self._buffer and time.monotonic() - self._last_flush
                        >= self.flush_s * 0.999):
                    self._flush("timer")
                elif not self._buffer:
                    self._last_flush = time.monotonic()

    def _flush(self, trigger: str) -> None:
        """Fold the buffer into the global model and advance the version.
        Caller holds ``_round_lock``."""
        if not self._buffer:
            return
        version = int(self.args.round_idx)
        entries = [(w, m) for (w, m, _, _) in self._buffer]
        staleness = [version - t for (_, _, _, t) in self._buffer]
        n_folded = len(entries)
        self._buffer = []
        self._last_flush = time.monotonic()
        with tracing.use_ctx(
                self._round_span.ctx if self._round_span else None):
            self.aggregator.aggregate_buffer(entries,
                                             server_lr=self.server_lr)
            freq = int(getattr(self.args, "frequency_of_the_test", 1) or 1)
            if (version % freq == 0 or version == self.round_num - 1):
                self.aggregator.test_on_server_for_all_clients(version)
        _async_flushes.labels(run_id=self._run_label, trigger=trigger).inc()
        _async_buffer.labels(run_id=self._run_label).set(0)
        ledger.event("async", "flush", round_idx=version, trigger=trigger,
                     n_folded=n_folded,
                     max_staleness=max(staleness) if staleness else 0)
        slo.check_round_boundary(version)
        logging.info(
            "async server: flush v%d→v%d (%s): folded %d updates, "
            "staleness %s", version, version + 1, trigger, n_folded,
            staleness)
        self._finish_round_span(n_folded)
        self.args.round_idx = version + 1
        self._persist_round_state()
        if self.args.round_idx >= self.round_num:
            self.send_finish_to_all()
            mlops.log_aggregation_status("FINISHED")
            if self._run_span is not None:
                self._run_span.end()
                self._run_span = None
            self.finish()
            return
        self._caught_up_this_round = set()
        self._quarantine_resolicits = {}
        self._open_round_span()
        # release the parked frontier in ONE broadcast — per-rank calls
        # would re-encode the full model once per parked silo
        if self._waiting:
            self._broadcast_round(only_rank=set(self._waiting))

    def _finish_round_span(self, n_folded: int) -> None:
        from .fedml_server_manager import (
            _clients_reported,
            _round_seconds,
            _rounds_total,
        )

        _clients_reported.labels(run_id=self._run_label).set(n_folded)
        _rounds_total.labels(run_id=self._run_label).inc()
        losses = [m.get("train_loss")
                  for m in self._round_train_metrics.values()
                  if isinstance(m.get("train_loss"), (int, float))]
        self._round_train_metrics = {}
        if self._round_span is not None:
            if losses:
                self._round_span.set_attr(
                    "mean_client_train_loss", sum(losses) / len(losses))
            self._round_span.set_attr("clients_reported", n_folded)
            self._round_span.set_attr("async", True)
            _round_seconds.labels(run_id=self._run_label).observe(
                self._round_span.end())
            self._round_span = None
