"""Cross-silo server-side aggregation state machine.

Capability parity: reference `cross_silo/server/fedml_aggregator.py`
(add_local_trained_result / check_whether_all_receive / aggregate / client
sampling / data-silo selection / test_on_server_for_all_clients).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...core import mlops
from ...core.mlops import flight_recorder, ledger, metrics, tracing
from ...core.alg_frame.context import Context

_dup_uploads_total = metrics.counter(
    "fedml_round_duplicate_uploads_total",
    "Uploads that arrived for a client index already counted this round "
    "(should stay 0 when the reliable plane dedups the transport)",
    labels=("run_id",))
_quarantined_total = metrics.counter(
    "fedml_quarantined_updates_total",
    "Uploads rejected by admission control, by reason "
    "(structure_mismatch / non_finite / norm_outlier)",
    labels=("run_id", "reason"))


class FedMLAggregator:
    def __init__(self, args: Any, aggregator, test_global) -> None:
        self.args = args
        self.aggregator = aggregator            # ServerAggregator impl
        self.test_global = test_global
        self.client_num = int(args.client_num_per_round)
        self.model_dict: Dict[int, Any] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self._received_this_round: set = set()
        self.metrics_history: List[Dict[str, Any]] = []
        #: transport-level duplicate accounting: a second upload counted
        #: for the SAME index in the SAME round.  Re-solicited re-uploads
        #: never hit this (re-solicitation targets only missing indices)
        self.duplicate_uploads = 0
        self._run_label = str(getattr(args, "run_id", "0"))
        # update admission control (docs/ROBUSTNESS.md "Data-plane
        # robustness"): validate every upload against the global tree
        # before it can enter the received set
        self.admission_control = bool(
            getattr(args, "admission_control", False))
        self.admission_norm_bound = float(
            getattr(args, "admission_norm_bound", 0) or 0)
        #: per-round quarantine ledger {client index: last rejection
        #: reason}, cleared by aggregate() — introspection/ops surface
        #: (re-solicitation itself is driven by the
        #: add_local_trained_result return value)
        self.quarantined_this_round: Dict[int, str] = {}
        self.quarantined_total = 0

    def get_global_model_params(self):
        return self.aggregator.get_model_params()

    def set_global_model_params(self, params):
        self.aggregator.set_model_params(params)

    def add_local_trained_result(self, index: int, model_params,
                                 sample_num):
        """Admit one upload into the round's received set.

        Returns ``None`` on acceptance, else the quarantine reason string
        (the caller re-solicits the client like a missing upload).

        Keep-first on duplicates: a second upload for an index already
        counted this round increments the duplicate counters but can
        NEVER overwrite the aggregated-in result — a late or forged
        duplicate would otherwise replace the update the round already
        committed to (and checkpointed).
        """
        round_idx = int(getattr(self.args, "round_idx", 0) or 0)
        if index in self._received_this_round:
            self.duplicate_uploads += 1
            _dup_uploads_total.labels(run_id=self._run_label).inc()
            ledger.event("aggregator", "duplicate", round_idx=round_idx,
                         client=index + 1)
            return None
        if self.admission_control:
            reason = self._admit(model_params)
            if reason is not None:
                self.quarantined_this_round[index] = reason
                self.quarantined_total += 1
                _quarantined_total.labels(
                    run_id=self._run_label, reason=reason).inc()
                ledger.event("aggregator", "quarantined",
                             round_idx=round_idx, client=index + 1,
                             reason=reason)
                logging.warning(
                    "server: QUARANTINED upload from client index %d "
                    "(%s) — not counted, will be re-solicited",
                    index, reason)
                return reason
        self.model_dict[index] = model_params
        self.sample_num_dict[index] = float(sample_num)
        self._received_this_round.add(index)
        ledger.event("aggregator", "admitted", round_idx=round_idx,
                     client=index + 1)
        return None

    def _admit(self, model_params) -> Optional[str]:
        """Validate an upload against the global tree: structure/shape/
        dtype match, NaN/Inf scan, and (when ``admission_norm_bound`` > 0)
        an update-norm outlier screen.  One fused device reduction, one
        host sync per upload — this runs in the receive handler, not a
        hot loop.  Returns the rejection reason or None."""
        import jax
        import jax.numpy as jnp

        from ...core.fhe import FedMLFHE

        global_tree = self.get_global_model_params()
        if FedMLFHE.is_encrypted(model_params):
            # content checks on ciphertext are meaningless by design
            return None
        if isinstance(model_params, tuple):
            # pair payloads (params, variates) have no single global
            # counterpart for the structure/norm checks, but the NaN/Inf
            # scan applies to the whole tuple tree unchanged
            global_tree = None
        if global_tree is not None:
            ref_leaves, ref_def = jax.tree_util.tree_flatten(global_tree)
            try:
                got_leaves, got_def = jax.tree_util.tree_flatten(
                    model_params)
            except Exception:  # noqa: BLE001 — unflattenable payload
                return "structure_mismatch"
            if (got_def != ref_def
                    or any(jnp.shape(g) != jnp.shape(r)
                           or jnp.asarray(g).dtype != jnp.asarray(r).dtype
                           for g, r in zip(got_leaves, ref_leaves))):
                return "structure_mismatch"
        finite = jnp.array(True)
        sq_delta = jnp.zeros((), jnp.float32)
        ref = (jax.tree_util.tree_leaves(global_tree)
               if global_tree is not None else None)
        for i, leaf in enumerate(jax.tree_util.tree_leaves(model_params)):
            x = jnp.asarray(leaf)
            if not jnp.issubdtype(x.dtype, jnp.floating):
                continue
            xf = x.astype(jnp.float32)
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(xf)))
            if self.admission_norm_bound > 0 and ref is not None:
                d = xf - jnp.asarray(ref[i]).astype(jnp.float32)
                sq_delta = sq_delta + jnp.sum(d * d)
        finite_host, sq_host = jax.device_get((finite, sq_delta))
        if not bool(finite_host):
            return "non_finite"
        if (self.admission_norm_bound > 0
                and float(sq_host) > self.admission_norm_bound ** 2):
            return "norm_outlier"
        return None

    def admission_check(self, model_params) -> Optional[str]:
        """Admission screen WITHOUT the round-set bookkeeping — the
        buffered-async path screens uploads before folding them into the
        buffer (its dedup/staleness accounting lives in the async server
        manager, not the per-round received set).  Returns the quarantine
        reason or None; counts the quarantine metric like the sync path."""
        if not self.admission_control:
            return None
        reason = self._admit(model_params)
        if reason is not None:
            self.quarantined_total += 1
            _quarantined_total.labels(
                run_id=self._run_label, reason=reason).inc()
        return reason

    def aggregate_buffer(self, entries: List[Tuple[float, Any]],
                         server_lr: float = 1.0) -> Any:
        """Fold one buffered-async batch of ``(weight, model)`` pairs into
        the global model.  Runs the SAME funnel as the sync path
        (on_before → robust-agg/defense aggregate → on_after), so a
        byzantine update that slipped past admission still meets the
        robust operator, then mixes the result into the global:
        ``global ← global + server_lr · (agg − global)`` (``server_lr`` =
        1.0 replaces it outright, the sync-equivalent).  Unlike
        ``aggregate`` there is no received-set to clear — the async
        manager owns buffer/dedup state."""
        global_model = self.get_global_model_params()
        with tracing.span("server.aggregate_async", n_updates=len(entries)):
            with mlops.span("server.agg"), \
                    flight_recorder.phase("device_compute",
                                          program="server/aggregate"):
                raw = self.aggregator.on_before_aggregation(list(entries))
                agg = self.aggregator.aggregate(raw)
                agg = self.aggregator.on_after_aggregation(agg)
        if server_lr != 1.0:
            # shared with the jittable async/aggregate_buffer registry
            # entry (agg_operator.fold_buffer) so the perf/mesh lint
            # tiers trace the SAME mixing arithmetic the server runs
            from ...ml.aggregator.agg_operator import mix_global

            agg = mix_global(global_model, agg, server_lr)
        self.aggregator.set_model_params(agg)
        return agg

    def receive_count(self) -> int:
        return len(self._received_this_round)

    def has_received(self, index: int) -> bool:
        return index in self._received_this_round

    def check_whether_all_receive(self) -> bool:
        return len(self._received_this_round) >= self.client_num

    def reset_round_state(self) -> None:
        """Abandon the in-flight round's received set WITHOUT aggregating
        (hierarchical regional segments: a newer global segment supersedes
        an uncompleted one — its partial uploads must not leak into the
        new segment's fold)."""
        self._received_this_round = set()
        self.quarantined_this_round = {}

    # -- crash-resume state (PR 4: RoundCheckpointer wiring) -----------------
    def export_round_state(self) -> Dict[str, Any]:
        """The in-flight round's received results, keyed by stringified
        client index (checkpoint codecs want string keys).  Empty dicts are
        omitted entirely — a round-boundary checkpoint carries no models."""
        idxs = sorted(self._received_this_round)
        if not idxs:
            return {}
        return {
            "models": {str(i): self.model_dict[i] for i in idxs},
            "num_samples": {str(i): float(self.sample_num_dict[i])
                            for i in idxs},
        }

    def restore_round_state(self, state: Dict[str, Any]) -> None:
        models = state.get("models") or {}
        num_samples = state.get("num_samples") or {}
        for key, tree in models.items():
            index = int(key)
            self.model_dict[index] = tree
            self.sample_num_dict[index] = float(
                np.asarray(num_samples.get(key, 1.0)))
            self._received_this_round.add(index)

    def aggregate(self) -> Any:
        """Aggregates over the clients that reported THIS round — a partial
        set when the elastic round timeout dropped stragglers (liveness/
        dropout tolerance, reference SecAgg reconstruction + async planes).
        Clears the received set for the next round."""
        idxs = sorted(self._received_this_round)
        self._received_this_round = set()
        self.quarantined_this_round = {}
        raw = [(self.sample_num_dict[i], self.model_dict[i]) for i in idxs]
        # nests under the server manager's round span via use_ctx; the
        # legacy "server.agg" event pair rides along inside mlops.span
        with tracing.span("server.aggregate", n_clients=len(idxs)):
            with mlops.span("server.agg"), \
                    flight_recorder.phase("device_compute",
                                          program="server/aggregate"):
                raw = self.aggregator.on_before_aggregation(raw)
                agg = self.aggregator.aggregate(raw)
                agg = self.aggregator.on_after_aggregation(agg)
        self.aggregator.set_model_params(agg)
        ledger.event("aggregator", "aggregate",
                     round_idx=int(getattr(self.args, "round_idx", 0) or 0),
                     n_clients=len(idxs))
        return agg

    # -- selection (reference :113-160) -------------------------------------
    def _round_rng(self, round_idx: int, stream: int) -> np.random.Generator:
        """Deterministic per-``(run_id, round_idx)`` RNG.  The reference
        seeds the GLOBAL ``np.random`` state with the bare round index —
        any concurrent numpy consumer (another run in-process, a data
        loader) perturbs the stream, and a crash-resumed server could
        re-solicit a DIFFERENT cohort than the one it checkpointed.  A
        private Generator keyed on the run identity makes the cohort a
        pure function of (run_id, random_seed, round_idx)."""
        import zlib

        seq = np.random.SeedSequence([
            zlib.crc32(self._run_label.encode()),
            int(getattr(self.args, "random_seed", 0) or 0),
            int(round_idx), int(stream)])
        return np.random.default_rng(seq)

    def client_sampling(self, round_idx: int, client_num_in_total: int,
                        client_num_per_round: int) -> List[int]:
        if client_num_in_total <= client_num_per_round:
            return list(range(client_num_in_total))
        rng = self._round_rng(round_idx, stream=0)
        return [int(c) for c in rng.choice(
            client_num_in_total, client_num_per_round, replace=False)]

    def data_silo_selection(self, round_idx: int, data_silo_num_in_total: int,
                            client_num_in_total: int) -> List[int]:
        if data_silo_num_in_total == client_num_in_total:
            return list(range(data_silo_num_in_total))
        rng = self._round_rng(round_idx, stream=1)
        return [int(c) for c in rng.choice(
            data_silo_num_in_total, client_num_in_total, replace=True)]

    def test_on_server_for_all_clients(self, round_idx: int) -> Dict[str, Any]:
        with tracing.span("server.eval", round=round_idx):
            metrics = self.aggregator.test(self.test_global, None, self.args)
        metrics["round"] = round_idx
        self.metrics_history.append(metrics)
        mlops.log(metrics)
        logging.info("cross-silo round %d server eval: %s", round_idx, metrics)
        return metrics
