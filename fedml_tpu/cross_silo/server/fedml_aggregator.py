"""Cross-silo server-side aggregation state machine.

Capability parity: reference `cross_silo/server/fedml_aggregator.py`
(add_local_trained_result / check_whether_all_receive / aggregate / client
sampling / data-silo selection / test_on_server_for_all_clients).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...core import mlops
from ...core.mlops import metrics, tracing
from ...core.alg_frame.context import Context

_dup_uploads_total = metrics.counter(
    "fedml_round_duplicate_uploads_total",
    "Uploads that arrived for a client index already counted this round "
    "(should stay 0 when the reliable plane dedups the transport)",
    labels=("run_id",))


class FedMLAggregator:
    def __init__(self, args: Any, aggregator, test_global) -> None:
        self.args = args
        self.aggregator = aggregator            # ServerAggregator impl
        self.test_global = test_global
        self.client_num = int(args.client_num_per_round)
        self.model_dict: Dict[int, Any] = {}
        self.sample_num_dict: Dict[int, float] = {}
        self._received_this_round: set = set()
        self.metrics_history: List[Dict[str, Any]] = []
        #: transport-level duplicate accounting: a second upload counted
        #: for the SAME index in the SAME round.  Re-solicited re-uploads
        #: never hit this (re-solicitation targets only missing indices)
        self.duplicate_uploads = 0
        self._run_label = str(getattr(args, "run_id", "0"))

    def get_global_model_params(self):
        return self.aggregator.get_model_params()

    def set_global_model_params(self, params):
        self.aggregator.set_model_params(params)

    def add_local_trained_result(self, index: int, model_params,
                                 sample_num) -> None:
        if index in self._received_this_round:
            self.duplicate_uploads += 1
            _dup_uploads_total.labels(run_id=self._run_label).inc()
        self.model_dict[index] = model_params
        self.sample_num_dict[index] = float(sample_num)
        self._received_this_round.add(index)

    def receive_count(self) -> int:
        return len(self._received_this_round)

    def has_received(self, index: int) -> bool:
        return index in self._received_this_round

    def check_whether_all_receive(self) -> bool:
        return len(self._received_this_round) >= self.client_num

    # -- crash-resume state (PR 4: RoundCheckpointer wiring) -----------------
    def export_round_state(self) -> Dict[str, Any]:
        """The in-flight round's received results, keyed by stringified
        client index (checkpoint codecs want string keys).  Empty dicts are
        omitted entirely — a round-boundary checkpoint carries no models."""
        idxs = sorted(self._received_this_round)
        if not idxs:
            return {}
        return {
            "models": {str(i): self.model_dict[i] for i in idxs},
            "num_samples": {str(i): float(self.sample_num_dict[i])
                            for i in idxs},
        }

    def restore_round_state(self, state: Dict[str, Any]) -> None:
        models = state.get("models") or {}
        num_samples = state.get("num_samples") or {}
        for key, tree in models.items():
            index = int(key)
            self.model_dict[index] = tree
            self.sample_num_dict[index] = float(
                np.asarray(num_samples.get(key, 1.0)))
            self._received_this_round.add(index)

    def aggregate(self) -> Any:
        """Aggregates over the clients that reported THIS round — a partial
        set when the elastic round timeout dropped stragglers (liveness/
        dropout tolerance, reference SecAgg reconstruction + async planes).
        Clears the received set for the next round."""
        idxs = sorted(self._received_this_round)
        self._received_this_round = set()
        raw = [(self.sample_num_dict[i], self.model_dict[i]) for i in idxs]
        # nests under the server manager's round span via use_ctx; the
        # legacy "server.agg" event pair rides along inside mlops.span
        with tracing.span("server.aggregate", n_clients=len(idxs)):
            with mlops.span("server.agg"):
                raw = self.aggregator.on_before_aggregation(raw)
                agg = self.aggregator.aggregate(raw)
                agg = self.aggregator.on_after_aggregation(agg)
        self.aggregator.set_model_params(agg)
        return agg

    # -- selection (reference :113-160) -------------------------------------
    def client_sampling(self, round_idx: int, client_num_in_total: int,
                        client_num_per_round: int) -> List[int]:
        if client_num_in_total == client_num_per_round:
            return list(range(client_num_in_total))
        np.random.seed(round_idx)
        return [int(c) for c in np.random.choice(
            range(client_num_in_total), client_num_per_round, replace=False)]

    def data_silo_selection(self, round_idx: int, data_silo_num_in_total: int,
                            client_num_in_total: int) -> List[int]:
        if data_silo_num_in_total == client_num_in_total:
            return list(range(data_silo_num_in_total))
        np.random.seed(round_idx)
        return [int(c) for c in np.random.choice(
            range(data_silo_num_in_total), client_num_in_total,
            replace=True)]

    def test_on_server_for_all_clients(self, round_idx: int) -> Dict[str, Any]:
        with tracing.span("server.eval", round=round_idx):
            metrics = self.aggregator.test(self.test_global, None, self.args)
        metrics["round"] = round_idx
        self.metrics_history.append(metrics)
        mlops.log(metrics)
        logging.info("cross-silo round %d server eval: %s", round_idx, metrics)
        return metrics
