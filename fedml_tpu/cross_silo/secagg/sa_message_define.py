"""SecAgg message schema (reference `cross_silo/secagg/sa_message_define.py:
16-35`): public keys, secret shares, masked models, active-client set,
secret-share reconstruction."""


class SAMessage:
    # the round hello is the fresh public-key advertisement itself
    MSG_TYPE_C2S_PUBLIC_KEY = "C2S_PUBLIC_KEY"
    MSG_TYPE_S2C_PUBLIC_KEYS = "S2C_PUBLIC_KEYS"
    MSG_TYPE_C2C_SECRET_SHARE = "C2C_SECRET_SHARE"
    MSG_TYPE_S2C_INIT_CONFIG = "S2C_INIT_CONFIG_SA"
    MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT = "S2C_SYNC_MODEL_SA"
    MSG_TYPE_C2S_MASKED_MODEL = "C2S_MASKED_MODEL_SA"
    MSG_TYPE_S2C_UNMASK_REQUEST = "S2C_UNMASK_REQUEST"
    MSG_TYPE_C2S_SS_RECONSTRUCTION = "C2S_SS_RECONSTRUCTION"
    MSG_TYPE_S2C_FINISH = "S2C_FINISH_SA"

    ARG_MODEL_PARAMS = "model_params"
    ARG_MASKED_VECTOR = "masked_vector"
    ARG_CLIENT_INDEX = "client_idx"
    ARG_NUM_SAMPLES = "num_samples"
    ARG_ROUND = "round_idx"
    ARG_PUBLIC_KEY = "public_key"
    ARG_PUBLIC_KEYS = "public_keys"          # dict rank -> pk
    ARG_SS_B = "share_of_b"                  # share of self-mask seed
    ARG_SS_SK = "share_of_sk"                # share of DH secret key
    ARG_ACTIVE_SET = "active_set"            # survivors (uploaded a model)
    ARG_DROPPED_SET = "dropped_set"          # selected but missing
    ARG_B_SHARES = "b_shares"                # dict rank -> share of b
    ARG_SK_SHARES = "sk_shares"              # dict rank -> share of sk
    ARG_PROTO = "sa_proto"                   # dict(d, n, t, scale)
