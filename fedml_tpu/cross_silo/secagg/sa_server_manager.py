"""SecAgg server manager.

Capability parity: reference `cross_silo/secagg/sa_fedml_server_manager.py` +
`sa_fedml_aggregator.py` (317 LoC): per round — collect the cohort's fresh
public keys, broadcast them, collect double-masked models, detect in-round
dropouts, request reconstruction shares (b for survivors, sk for dropped),
Shamir-reconstruct, strip self- and orphaned pairwise masks, average,
advance.

Keys are rotated every round (client side), so a reconstructed sk opens only
the round it was revealed for — never a round in which that client's model
was actually aggregated.

Liveness caveat (same as the reference implementation): each protocol stage
gates on replies from the full expected cohort, so a client that dies
mid-stage stalls the round until the transport surfaces the disconnect; the
Shamir threshold t covers *observable* dropout between upload and
reconstruction, not silent mid-stage crashes (production deployments add
per-stage timeouts at the transport layer).
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import numpy as np

from ...core import mlops
from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.mpc.secagg import FIELD_PRIME, shamir_reconstruct
from ..lightsecagg.lsa_utils import (
    tree_to_field_vector,
    weighted_sum_to_mean_tree,
)
from ..server.fedml_aggregator import FedMLAggregator
from .sa_message_define import SAMessage
from .sa_utils import remove_dropped_pairwise_masks, remove_self_masks


class SAServerManager(FedMLCommManager):
    def __init__(self, args: Any, aggregator: FedMLAggregator, comm=None,
                 rank: int = 0, client_num: int = 0,
                 backend: str = "INPROC") -> None:
        if client_num < 2:
            raise ValueError(
                "SecAgg needs at least 2 clients per round (pairwise masks "
                f"and Shamir reconstruction are meaningless for "
                f"client_num={client_num}); use plain FedAvg instead")
        super().__init__(args, comm, rank, client_num + 1, backend)
        self.aggregator = aggregator
        self.round_num = int(args.comm_round)
        self.args.round_idx = 0
        self.client_num = client_num
        self.scale = 1 << 10
        # reconstruction threshold: t+1 shares open a secret; must be
        # reachable even if the maximum tolerated dropout occurs
        self.t = max(1, min(client_num - 1, client_num // 2))
        self.public_keys: Dict[int, int] = {}   # current round's cohort keys
        self._pk_round: Dict[int, int] = {}     # rank -> round of its last pk
        self.masked: Dict[int, np.ndarray] = {}
        self.sample_nums: Dict[int, float] = {}
        # reconstruction shares: owner rank -> {share index -> share}
        self.b_shares: Dict[int, Dict[int, np.ndarray]] = {}
        self.sk_shares: Dict[int, Dict[int, np.ndarray]] = {}
        # replies are keyed by SENDER, not counted: a transport-duplicated
        # reconstruction reply must not trip the threshold early
        self.reconstruction_repliers: set = set()
        # stage transitions are idempotent: a duplicated masked upload
        # arriving after the cohort is complete must not re-broadcast the
        # unmask request (clients would reply twice, corrupting the count)
        self._unmask_requested = False
        self.d = None
        self._template = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_C2S_PUBLIC_KEY, self.handle_public_key)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_C2S_MASKED_MODEL, self.handle_masked_model)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_C2S_SS_RECONSTRUCTION,
            self.handle_reconstruction)

    # -- per-round: collect + broadcast fresh public keys --------------------
    def handle_public_key(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        rnd = int(msg.get(SAMessage.ARG_ROUND, 0))
        self.public_keys[sender] = int(msg.get(SAMessage.ARG_PUBLIC_KEY))
        self._pk_round[sender] = rnd
        current = [r for r, rr in self._pk_round.items()
                   if rr == self.args.round_idx]
        if len(current) == self.client_num:
            self._broadcast_keys(first_round=(self.args.round_idx == 0))

    def _broadcast_keys(self, first_round: bool) -> None:
        global_model = self.aggregator.get_global_model_params()
        self._template = global_model
        qvec, _ = tree_to_field_vector(global_model, self.scale)
        self.d = int(len(qvec))
        proto = {"d": self.d, "n": self.client_num, "t": self.t,
                 "scale": self.scale}
        for i in range(self.client_num):
            msg = Message(SAMessage.MSG_TYPE_S2C_PUBLIC_KEYS,
                          self.get_sender_id(), i + 1)
            msg.add_params(SAMessage.ARG_PUBLIC_KEYS, dict(self.public_keys))
            msg.add_params(SAMessage.ARG_PROTO, proto)
            msg.add_params(SAMessage.ARG_ROUND, self.args.round_idx)
            self.send_message(msg)
        if first_round:
            self._send_round_start(SAMessage.MSG_TYPE_S2C_INIT_CONFIG)

    def _send_round_start(self, msg_type: str) -> None:
        ids = self.aggregator.client_sampling(
            self.args.round_idx, int(self.args.client_num_in_total),
            self.client_num)
        global_model = self.aggregator.get_global_model_params()
        self._template = global_model
        for i in range(self.client_num):
            msg = Message(msg_type, self.get_sender_id(), i + 1)
            msg.add_params(SAMessage.ARG_MODEL_PARAMS, global_model)
            msg.add_params(SAMessage.ARG_CLIENT_INDEX, ids[i % len(ids)])
            msg.add_params(SAMessage.ARG_ROUND, self.args.round_idx)
            self.send_message(msg)

    # -- masked model collection ---------------------------------------------
    def handle_masked_model(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        self.masked[sender] = np.asarray(
            msg.get(SAMessage.ARG_MASKED_VECTOR), np.int64)
        self.sample_nums[sender] = float(
            msg.get(SAMessage.ARG_NUM_SAMPLES, 1.0))
        # dropout emulation hook for tests: ranks listed here never "arrive"
        drop = set(getattr(self.args, "sa_simulate_dropout_ranks", []) or [])
        expected = self.client_num - len(drop)
        if sender in drop:
            del self.masked[sender]
            self.sample_nums.pop(sender, None)
            return
        if len(self.masked) >= expected and not self._unmask_requested:
            self._unmask_requested = True
            active = sorted(self.masked.keys())
            dropped = sorted(set(range(1, self.client_num + 1)) - set(active))
            for r in active:
                req = Message(SAMessage.MSG_TYPE_S2C_UNMASK_REQUEST,
                              self.get_sender_id(), r)
                req.add_params(SAMessage.ARG_ACTIVE_SET, active)
                req.add_params(SAMessage.ARG_DROPPED_SET, dropped)
                req.add_params(SAMessage.ARG_ROUND, self.args.round_idx)
                self.send_message(req)

    # -- reconstruction ------------------------------------------------------
    def handle_reconstruction(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        for owner, share in dict(msg.get(SAMessage.ARG_B_SHARES, {})).items():
            self.b_shares.setdefault(int(owner), {})[sender - 1] = \
                np.asarray(share, np.int64)
        for owner, share in dict(msg.get(SAMessage.ARG_SK_SHARES, {})).items():
            self.sk_shares.setdefault(int(owner), {})[sender - 1] = \
                np.asarray(share, np.int64)
        self.reconstruction_repliers.add(sender)
        if len(self.reconstruction_repliers) < len(self.masked):
            return
        try:
            self._unmask_and_advance()
        except Exception:
            # reconstruction failure (below-threshold survivors, corrupt
            # shares) is unrecoverable for the round — tell the clients to
            # exit instead of leaving them blocked on a next-round sync
            # that will never come, then surface the error
            logging.exception("SA server: reconstruction failed in round "
                              "%s — aborting the run", self.args.round_idx)
            self._abort_run()
            raise

    def _abort_run(self) -> None:
        try:
            self._broadcast_finish()
        finally:
            mlops.log_aggregation_status("FAILED")
            self.finish()

    def _broadcast_finish(self) -> None:
        for r in range(1, self.client_num + 1):
            try:
                self.send_message(Message(SAMessage.MSG_TYPE_S2C_FINISH,
                                          self.get_sender_id(), r))
            except Exception:
                # best-effort: one dead transport must not strand the
                # remaining clients without their FINISH
                logging.exception("SA server: FINISH to rank %d failed", r)

    def _unmask_and_advance(self) -> None:
        active = sorted(self.masked.keys())
        dropped = sorted(set(range(1, self.client_num + 1)) - set(active))
        if len(active) < self.t + 1:
            raise RuntimeError(
                f"SecAgg round {self.args.round_idx}: only {len(active)} "
                f"survivors < reconstruction threshold t+1={self.t + 1}; "
                "the masked sum cannot be opened")
        qsum = np.zeros(self.d, np.int64)
        for r in active:
            qsum = (qsum + self.masked[r]) % FIELD_PRIME

        b_seeds = {r: int(shamir_reconstruct(self.b_shares[r])[0])
                   for r in active}
        qsum = remove_self_masks(qsum, b_seeds)
        if dropped:
            dropped_sks = {r: int(shamir_reconstruct(self.sk_shares[r])[0])
                           for r in dropped if r in self.sk_shares}
            qsum = remove_dropped_pairwise_masks(
                qsum, active, dropped_sks, self.public_keys)
            logging.info("SA server: reconstructed %d dropped clients' "
                         "round keys (rotated next round)", len(dropped))

        # sample-weighted FedAvg under masking: clients field-multiplied
        # their quantized update by n_samples, so the opened sum divides by
        # the total sample count
        total_w = sum(self.sample_nums.get(r, 1.0) for r in active) or 1.0
        avg_tree = weighted_sum_to_mean_tree(qsum, self._template, total_w,
                                             self.scale)
        self.aggregator.set_global_model_params(avg_tree)

        freq = int(getattr(self.args, "frequency_of_the_test", 1) or 1)
        if (self.args.round_idx % freq == 0
                or self.args.round_idx == self.round_num - 1):
            self.aggregator.test_on_server_for_all_clients(self.args.round_idx)

        self.masked.clear()
        self.sample_nums.clear()
        self.b_shares.clear()
        self.sk_shares.clear()
        self.reconstruction_repliers = set()
        self._unmask_requested = False
        self.args.round_idx += 1
        if self.args.round_idx >= self.round_num:
            self._broadcast_finish()
            mlops.log_aggregation_status("FINISHED")
            self.finish()
            return
        self._send_round_start(SAMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT)
