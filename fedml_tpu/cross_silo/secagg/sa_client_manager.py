"""SecAgg client manager.

Capability parity: reference `cross_silo/secagg/sa_fedml_client_manager.py`.

Per-round protocol (Bonawitz et al., re-run every round so no long-lived
secret ever protects more than one upload — a reconstructed key compromises
only the round it was revealed for, never past or future uploads):

  1. advertise a FRESH DH public key for this round
  2. receive the cohort's round keys → derive pairwise seeds
  3. Shamir-share this round's DH secret key and self-mask seed to peers
  4. train → upload the double-masked model
  5. answer the server's reconstruction request: b-shares for survivors,
     sk-shares for dropped — never both for the same client, and only one
     request per round (enforced, not assumed)
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import numpy as np

from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.mpc.secagg import shamir_share
from ..client.trainer_dist_adapter import TrainerDistAdapter
from ..lightsecagg.lsa_utils import tree_to_weighted_field_vector
from .sa_message_define import SAMessage
from .sa_utils import dh_keypair, dh_shared_seed, mask_upload


class SAClientManager(FedMLCommManager):
    def __init__(self, args: Any, trainer_dist_adapter: TrainerDistAdapter,
                 comm=None, rank: int = 0, size: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, comm, rank, size, backend)
        self.adapter = trainer_dist_adapter
        self.round_idx = 0
        self.proto: Dict[str, int] = {}
        self._rng = np.random.RandomState(
            int(getattr(args, "random_seed", 0) or 0) * 1000 + rank)
        # per-round secrets (rotated each round)
        self.sk = 0
        self.pk = 0
        self.b_seed = 0
        self.public_keys: Dict[int, int] = {}
        self.shared_seeds: Dict[int, int] = {}
        self._seeds_round = -1  # round the current seeds/b were derived for
        # shares this client HOLDS for peers, keyed by round
        self.held_b_shares: Dict[int, Dict[int, np.ndarray]] = {}
        self.held_sk_shares: Dict[int, Dict[int, np.ndarray]] = {}
        self._pending_model = None
        self._answered_unmask: set = set()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_S2C_PUBLIC_KEYS, self.handle_public_keys)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_C2C_SECRET_SHARE, self.handle_secret_share)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_round)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.handle_round)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_S2C_UNMASK_REQUEST, self.handle_unmask_request)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        self._advertise_round_key(0)
        self.com_manager.handle_receive_message()

    def _advertise_round_key(self, round_idx: int) -> None:
        """Fresh DH keypair every round: a later reconstruction of this
        round's sk must not open any other round's upload."""
        self.sk, self.pk = dh_keypair(self._rng)
        msg = Message(SAMessage.MSG_TYPE_C2S_PUBLIC_KEY,
                      self.get_sender_id(), 0)
        msg.add_params(SAMessage.ARG_PUBLIC_KEY, self.pk)
        msg.add_params(SAMessage.ARG_ROUND, round_idx)
        self.send_message(msg)

    # -- key distribution + secret sharing (every round) ---------------------
    def handle_public_keys(self, msg: Message) -> None:
        rnd = int(msg.get(SAMessage.ARG_ROUND, 0))
        self.public_keys = {int(k): int(v) for k, v in
                            dict(msg.get(SAMessage.ARG_PUBLIC_KEYS)).items()}
        self.proto = dict(msg.get(SAMessage.ARG_PROTO))
        n, t = int(self.proto["n"]), int(self.proto["t"])
        self.shared_seeds = {
            peer: dh_shared_seed(self.sk, pk)
            for peer, pk in self.public_keys.items() if peer != self.rank}
        self._seeds_round = rnd
        self.b_seed = int(self._rng.randint(1, 2**31 - 1))
        sk_shares = shamir_share(np.array([self.sk]), n, t, self._rng)
        b_shares = shamir_share(np.array([self.b_seed]), n, t, self._rng)
        for j in range(n):
            peer_rank = j + 1
            if peer_rank == self.rank:
                self.held_sk_shares.setdefault(rnd, {})[self.rank] = \
                    sk_shares[j]
                self.held_b_shares.setdefault(rnd, {})[self.rank] = \
                    b_shares[j]
                continue
            share_msg = Message(SAMessage.MSG_TYPE_C2C_SECRET_SHARE,
                                self.get_sender_id(), peer_rank)
            share_msg.add_params(SAMessage.ARG_SS_SK, sk_shares[j])
            share_msg.add_params(SAMessage.ARG_SS_B, b_shares[j])
            share_msg.add_params(SAMessage.ARG_ROUND, rnd)
            self.send_message(share_msg)
        self._maybe_upload()

    def handle_secret_share(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        rnd = int(msg.get(SAMessage.ARG_ROUND, 0))
        sk_share = msg.get(SAMessage.ARG_SS_SK, None)
        if sk_share is not None:
            self.held_sk_shares.setdefault(rnd, {})[sender] = np.asarray(
                sk_share, np.int64)
        b_share = msg.get(SAMessage.ARG_SS_B, None)
        if b_share is not None:
            self.held_b_shares.setdefault(rnd, {})[sender] = np.asarray(
                b_share, np.int64)
        self._maybe_upload()

    # -- per-round: train + masked upload ------------------------------------
    def handle_round(self, msg: Message) -> None:
        client_index = msg.get(SAMessage.ARG_CLIENT_INDEX)
        self.round_idx = int(msg.get(SAMessage.ARG_ROUND, 0))
        if self.round_idx > 0:
            self._advertise_round_key(self.round_idx)
        self.adapter.update_dataset(int(client_index))
        self.adapter.update_model(msg.get(SAMessage.ARG_MODEL_PARAMS))
        weights, n_samples = self.adapter.train(self.round_idx)
        self._pending_model = (weights, n_samples)
        self._maybe_upload()

    def _maybe_upload(self) -> None:
        """Upload once training is done AND this round's key broadcast and
        full share exchange completed (training races key distribution)."""
        n = int(self.proto.get("n", 0))
        rnd = self.round_idx
        if (self._pending_model is None or n == 0
                or self._seeds_round != rnd
                or len(self.held_sk_shares.get(rnd, {})) < n
                or len(self.held_b_shares.get(rnd, {})) < n):
            return
        weights, n_samples = self._pending_model
        self._pending_model = None
        scale = int(self.proto.get("scale", 1 << 10))
        # pre-scale by n_samples (exact integer field multiply after
        # quantization) so the server's opened sum is the sample-weighted
        # FedAvg numerator; only the scalar n_samples travels in clear
        qvec, _ = tree_to_weighted_field_vector(weights, n_samples, scale)
        peer_ranks = sorted(self.public_keys.keys())
        y = mask_upload(qvec, self.b_seed, self.rank, peer_ranks,
                        self.shared_seeds)
        up = Message(SAMessage.MSG_TYPE_C2S_MASKED_MODEL,
                     self.get_sender_id(), 0)
        up.add_params(SAMessage.ARG_MASKED_VECTOR, y)
        up.add_params(SAMessage.ARG_NUM_SAMPLES, int(n_samples))
        up.add_params(SAMessage.ARG_ROUND, rnd)
        self.send_message(up)

    # -- reconstruction ------------------------------------------------------
    def handle_unmask_request(self, msg: Message) -> None:
        rnd = int(msg.get(SAMessage.ARG_ROUND, self.round_idx))
        active = {int(r) for r in msg.get(SAMessage.ARG_ACTIVE_SET)}
        dropped = {int(r) for r in msg.get(SAMessage.ARG_DROPPED_SET, [])}
        # the server is the adversary here: refuse requests that would
        # reveal BOTH shares for one client, and answer once per round
        if active & dropped:
            logging.warning("SA client %d: unmask request with overlapping "
                            "active/dropped sets — refused", self.rank)
            return
        if rnd in self._answered_unmask:
            logging.warning("SA client %d: duplicate unmask request for "
                            "round %d — refused", self.rank, rnd)
            return
        self._answered_unmask.add(rnd)
        round_b = self.held_b_shares.pop(rnd, {})
        round_sk = self.held_sk_shares.pop(rnd, {})
        reply = Message(SAMessage.MSG_TYPE_C2S_SS_RECONSTRUCTION,
                        self.get_sender_id(), 0)
        reply.add_params(SAMessage.ARG_B_SHARES, {
            r: round_b[r] for r in sorted(active) if r in round_b})
        reply.add_params(SAMessage.ARG_SK_SHARES, {
            r: round_sk[r] for r in sorted(dropped) if r in round_sk})
        reply.add_params(SAMessage.ARG_ROUND, rnd)
        self.send_message(reply)

    def handle_finish(self, msg: Message) -> None:
        logging.info("SA client %d: finish", self.rank)
        self.finish()
