"""SecAgg client manager.

Capability parity: reference `cross_silo/secagg/sa_fedml_client_manager.py`:
advertise public key → receive the cohort's keys → Shamir-share the DH
secret key and the self-mask seed to peers → train → upload the
double-masked model → answer the server's reconstruction request with the
shares it holds for survivors' b and dropped clients' sk.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import numpy as np

from ...core.distributed.communication.message import Message
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...core.mpc.secagg import shamir_share
from ..client.trainer_dist_adapter import TrainerDistAdapter
from ..lightsecagg.lsa_utils import tree_to_weighted_field_vector
from .sa_message_define import SAMessage
from .sa_utils import dh_keypair, dh_shared_seed, mask_upload


class SAClientManager(FedMLCommManager):
    def __init__(self, args: Any, trainer_dist_adapter: TrainerDistAdapter,
                 comm=None, rank: int = 0, size: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, comm, rank, size, backend)
        self.adapter = trainer_dist_adapter
        self.round_idx = 0
        self.proto: Dict[str, int] = {}
        self._rng = np.random.RandomState(
            int(getattr(args, "random_seed", 0) or 0) * 1000 + rank)
        self.sk, self.pk = dh_keypair(self._rng)
        self.b_seed = 0
        self.public_keys: Dict[int, int] = {}
        self.shared_seeds: Dict[int, int] = {}
        # shares this client HOLDS for peers: sk once per federation,
        # b fresh each round (the server learns survivors' b at unmask time,
        # so reusing one b across rounds would void the mask)
        self.held_b_shares: Dict[int, Dict[int, np.ndarray]] = {}  # round →
        self.held_sk_shares: Dict[int, np.ndarray] = {}
        self._pending_model = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_S2C_PUBLIC_KEYS, self.handle_public_keys)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_C2C_SECRET_SHARE, self.handle_secret_share)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_S2C_INIT_CONFIG, self.handle_round)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self.handle_round)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_S2C_UNMASK_REQUEST, self.handle_unmask_request)
        self.register_message_receive_handler(
            SAMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        msg = Message(SAMessage.MSG_TYPE_C2S_PUBLIC_KEY,
                      self.get_sender_id(), 0)
        msg.add_params(SAMessage.ARG_PUBLIC_KEY, self.pk)
        self.send_message(msg)
        self.com_manager.handle_receive_message()

    # -- round 0: key agreement + secret sharing -----------------------------
    def handle_public_keys(self, msg: Message) -> None:
        self.public_keys = {int(k): int(v) for k, v in
                            dict(msg.get(SAMessage.ARG_PUBLIC_KEYS)).items()}
        self.proto = dict(msg.get(SAMessage.ARG_PROTO))
        n, t = int(self.proto["n"]), int(self.proto["t"])
        for peer, pk in self.public_keys.items():
            if peer != self.rank:
                self.shared_seeds[peer] = dh_shared_seed(self.sk, pk)
        # Shamir-share the long-lived DH secret key once
        sk_shares = shamir_share(np.array([self.sk]), n, t, self._rng)
        for j in range(n):
            peer_rank = j + 1
            if peer_rank == self.rank:
                self.held_sk_shares[self.rank] = sk_shares[j]
                continue
            share_msg = Message(SAMessage.MSG_TYPE_C2C_SECRET_SHARE,
                                self.get_sender_id(), peer_rank)
            share_msg.add_params(SAMessage.ARG_SS_SK, sk_shares[j])
            share_msg.add_params(SAMessage.ARG_ROUND, -1)
            self.send_message(share_msg)

    def _share_fresh_b(self) -> None:
        n, t = int(self.proto["n"]), int(self.proto["t"])
        self.b_seed = int(self._rng.randint(1, 2**31 - 1))
        b_shares = shamir_share(np.array([self.b_seed]), n, t, self._rng)
        for j in range(n):
            peer_rank = j + 1
            if peer_rank == self.rank:
                self.held_b_shares.setdefault(
                    self.round_idx, {})[self.rank] = b_shares[j]
                continue
            share_msg = Message(SAMessage.MSG_TYPE_C2C_SECRET_SHARE,
                                self.get_sender_id(), peer_rank)
            share_msg.add_params(SAMessage.ARG_SS_B, b_shares[j])
            share_msg.add_params(SAMessage.ARG_ROUND, self.round_idx)
            self.send_message(share_msg)

    def handle_secret_share(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        sk_share = msg.get(SAMessage.ARG_SS_SK, None)
        if sk_share is not None:
            self.held_sk_shares[sender] = np.asarray(sk_share, np.int64)
        b_share = msg.get(SAMessage.ARG_SS_B, None)
        if b_share is not None:
            rnd = int(msg.get(SAMessage.ARG_ROUND, 0))
            self.held_b_shares.setdefault(rnd, {})[sender] = np.asarray(
                b_share, np.int64)
        self._maybe_upload()

    # -- per-round: train + masked upload ------------------------------------
    def handle_round(self, msg: Message) -> None:
        client_index = msg.get(SAMessage.ARG_CLIENT_INDEX)
        self.round_idx = int(msg.get(SAMessage.ARG_ROUND, 0))
        self._share_fresh_b()
        self.adapter.update_dataset(int(client_index))
        self.adapter.update_model(msg.get(SAMessage.ARG_MODEL_PARAMS))
        weights, n_samples = self.adapter.train(self.round_idx)
        self._pending_model = (weights, n_samples)
        self._maybe_upload()

    def _maybe_upload(self) -> None:
        """Upload once training is done AND every peer's sk-share and this
        round's b-shares arrived (round 0 races key distribution against
        S2C_INIT; later rounds race the b-share exchange)."""
        n = int(self.proto.get("n", 0))
        if (self._pending_model is None or n == 0
                or len(self.held_sk_shares) < n
                or len(self.held_b_shares.get(self.round_idx, {})) < n):
            return
        weights, n_samples = self._pending_model
        self._pending_model = None
        scale = int(self.proto.get("scale", 1 << 10))
        # pre-scale by n_samples so the server's opened sum is the
        # sample-weighted FedAvg numerator (weights stay private; only the
        # scalar n_samples travels in clear, as in the plain path)
        qvec, _ = tree_to_weighted_field_vector(weights, n_samples, scale)
        peer_ranks = sorted(self.public_keys.keys())
        y = mask_upload(qvec, self.b_seed, self.rank, peer_ranks,
                        self.shared_seeds)
        up = Message(SAMessage.MSG_TYPE_C2S_MASKED_MODEL,
                     self.get_sender_id(), 0)
        up.add_params(SAMessage.ARG_MASKED_VECTOR, y)
        up.add_params(SAMessage.ARG_NUM_SAMPLES, n_samples)
        up.add_params(SAMessage.ARG_ROUND, self.round_idx)
        self.send_message(up)

    # -- reconstruction ------------------------------------------------------
    def handle_unmask_request(self, msg: Message) -> None:
        active = [int(r) for r in msg.get(SAMessage.ARG_ACTIVE_SET)]
        dropped = [int(r) for r in msg.get(SAMessage.ARG_DROPPED_SET, [])]
        reply = Message(SAMessage.MSG_TYPE_C2S_SS_RECONSTRUCTION,
                        self.get_sender_id(), 0)
        # reveal b-shares ONLY for survivors and sk-shares ONLY for dropped —
        # never both for the same client (the SecAgg privacy invariant)
        round_b = self.held_b_shares.get(self.round_idx, {})
        reply.add_params(SAMessage.ARG_B_SHARES, {
            r: round_b[r] for r in active if r in round_b})
        reply.add_params(SAMessage.ARG_SK_SHARES, {
            r: self.held_sk_shares[r] for r in dropped
            if r in self.held_sk_shares})
        reply.add_params(SAMessage.ARG_ROUND, self.round_idx)
        self.send_message(reply)
        # b-shares for this round are now spent
        self.held_b_shares.pop(self.round_idx - 2, None)

    def handle_finish(self, msg: Message) -> None:
        logging.info("SA client %d: finish", self.rank)
        self.finish()
