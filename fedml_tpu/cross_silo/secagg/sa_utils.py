"""Pairwise-mask SecAgg math (Bonawitz et al. 2017).

Capability parity: reference `cross_silo/secagg/` + `core/mpc/secagg.py` —
Diffie-Hellman pairwise agreement in the prime field, PRG mask expansion,
the signed pairwise-mask sum, and reconstruction of dropped clients' masks
from Shamir shares.

All of this is control-plane-sized host math (the model vector is the only
O(d) object); field ops are numpy int64 over p = 2^31 − 1 so products of
residues are exact (SURVEY §7 hard part (c)).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ...core.mpc.secagg import FIELD_PRIME, pow_mod

DH_GENERATOR = 7  # primitive root mod 2^31 - 1


def dh_keypair(rng: np.random.RandomState):
    """(secret, public = g^secret mod p)."""
    sk = int(rng.randint(2, int(FIELD_PRIME - 1)))
    pk = int(pow_mod(np.int64(DH_GENERATOR), sk))
    return sk, pk


def dh_shared_seed(sk_self: int, pk_peer: int) -> int:
    """Shared seed = pk_peer^sk_self mod p — equal on both ends."""
    return int(pow_mod(np.int64(pk_peer), int(sk_self)))


def prg_field_vector(seed: int, d: int) -> np.ndarray:
    """Expand a seed into a length-d field vector (the PRG both masker and
    reconstructor run)."""
    rng = np.random.RandomState(seed % (2**32 - 1))
    # randint upper bound is exclusive and must fit int32 on some platforms;
    # draw two 16-bit halves to cover [0, p)
    hi = rng.randint(0, 1 << 15, size=d).astype(np.int64)
    lo = rng.randint(0, 1 << 16, size=d).astype(np.int64)
    return ((hi << 16) | lo) % FIELD_PRIME


def pairwise_mask(rank: int, peer_ranks: Sequence[int],
                  shared_seeds: Dict[int, int], d: int) -> np.ndarray:
    """sum_{j<i} PRG(s_ij) − sum_{j>i} PRG(s_ij) mod p: cancels exactly in
    the sum over all surviving pairs."""
    m = np.zeros(d, np.int64)
    for j in peer_ranks:
        if j == rank:
            continue
        pm = prg_field_vector(shared_seeds[j], d)
        if j < rank:
            m = (m + pm) % FIELD_PRIME
        else:
            m = (m - pm) % FIELD_PRIME
    return m


def mask_upload(qvec: np.ndarray, b_seed: int, rank: int,
                peer_ranks: Sequence[int], shared_seeds: Dict[int, int]
                ) -> np.ndarray:
    """y_i = x_i + PRG(b_i) + pairwise_mask_i mod p."""
    d = len(qvec)
    y = (np.asarray(qvec, np.int64)
         + prg_field_vector(b_seed, d)
         + pairwise_mask(rank, peer_ranks, shared_seeds, d)) % FIELD_PRIME
    return y


def remove_self_masks(qsum: np.ndarray, b_seeds: Dict[int, int]) -> np.ndarray:
    """Subtract every survivor's PRG(b_i) from the masked sum."""
    d = len(qsum)
    out = np.asarray(qsum, np.int64) % FIELD_PRIME
    for b in b_seeds.values():
        out = (out - prg_field_vector(int(b), d)) % FIELD_PRIME
    return out


def remove_dropped_pairwise_masks(qsum: np.ndarray, active: List[int],
                                  dropped_sks: Dict[int, int],
                                  public_keys: Dict[int, int]) -> np.ndarray:
    """For each dropped client u (whose pairwise masks did NOT cancel),
    recompute s_uv with every active v from u's reconstructed secret key and
    remove u's contribution to each v's upload: v added +PRG(s_uv) if u<v
    else −PRG(s_uv)."""
    d = len(qsum)
    out = np.asarray(qsum, np.int64) % FIELD_PRIME
    for u, sk_u in dropped_sks.items():
        for v in active:
            if v == u:
                continue
            s_uv = dh_shared_seed(int(sk_u), int(public_keys[v]))
            pm = prg_field_vector(s_uv, d)
            if u < v:
                out = (out - pm) % FIELD_PRIME  # v added +PRG
            else:
                out = (out + pm) % FIELD_PRIME  # v added −PRG
    return out
