"""Regional aggregator of the geo-distributed hierarchy.

Two cooperating roles per region:

* ``RegionalAggregatorManager`` — the LAN face: a ``FedMLServerManager``
  the region's silos cannot tell from a flat server (stock
  ``ClientMasterManager``s, the unmodified S2C/C2S wire).  It does NOT
  own the round clock: a round segment opens when the global server's
  sync arrives through the uplink, the silo uploads fold locally with
  the fused epilogue (regional FedBuff-style partial buffer = the
  per-round received set, regional staleness decay on silo upload age,
  regional robust op, default ``trimmed_mean``), and the fold — ONE
  pre-reduced model — is handed to the uplink's fold sink.  The round
  index never self-advances: only the next G2R sync does.  Crash-resume
  rides ``RoundCheckpointer`` unchanged, extended with the fold marker
  and the per-silo round map, so a SIGKILLed regional aggregator
  re-enters its segment and re-solicits ONLY its missing silos (the
  base late-join catch-up re-solicits each silo on its first
  post-restart heartbeat).

* ``RegionUplink`` — the WAN face: announces the region, receives round
  segments, ships the fold (codec-compressed delta against the decoded
  segment broadcast) with the ``(silo rank, silo round)`` pairs that
  the global server audits as ``(region, silo, round)`` dedup triples,
  and heartbeats into the global failure detector.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ...core import mlops
from ...core.mlops import ledger, metrics, slo, tracing
from ...core.distributed.communication.message import Message
from ...core.distributed.communication.reliable import ARG_VOLATILE
from ...core.distributed.fedml_comm_manager import FedMLCommManager
from ...ml.aggregator.staleness import parse_staleness, staleness_weight
from ...utils.compression import WIRE_BYTES as _wire_bytes
from ..message_define import MyMessage
from ..server.fedml_aggregator import FedMLAggregator
from ..server.fedml_server_manager import FedMLServerManager
from .message_define import HierMessage

_region_fold_seconds = metrics.histogram(
    "fedml_region_fold_seconds",
    "Wall-clock duration of a regional round segment (segment open to "
    "local fold)", labels=("run_id", "region"),
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0))
_silo_uploads = metrics.counter(
    "fedml_region_silo_uploads_total",
    "Silo uploads handled by a regional aggregator, by outcome (folded | "
    "expired | quarantined)", labels=("run_id", "region", "outcome"))

#: a silo delta whose trained-against segment reference is gone
_MISSING_REF = object()

#: bound on the cross-segment (silo, round) keep-first audit window
_SILO_DEDUP_WINDOW = 4096


class RegionalAggregatorManager(FedMLServerManager):
    def __init__(self, args: Any, aggregator: FedMLAggregator, region: str,
                 silo_indices: List[int], comm=None, rank: int = 0,
                 client_num: int = 0, backend: str = "INPROC") -> None:
        # subclass state FIRST: the base __init__ may run _try_resume,
        # which this class overrides and which reads these fields
        self._region = str(region)
        self._silo_indices = list(silo_indices)
        #: silo rank → the silo round its folded upload trained (becomes
        #: the (region, silo, round) dedup triples on the WAN)
        self._silo_rounds: Dict[int, int] = {}
        #: every (silo rank, silo round) already folded into a SHIPPED
        #: segment — keep-first across segments: a retransmitted or
        #: re-trained duplicate (e.g. a crash-resume catch-up made the
        #: silo train the same round twice) must never enter a second
        #: fold, or the global triple audit rejects that fold whole
        self._folded_silo_rounds: "OrderedDict" = OrderedDict()
        #: True once the in-flight segment's fold was computed (and, on
        #: the happy path, shipped) — a resume from this state must NOT
        #: re-open the segment locally; the global server drives
        self._segment_folded = False
        self._fold_sink: Optional[Callable] = None
        # pending segment held as SPLIT fields (index apart from model
        # payload) so the round index never rides a tensor container
        self._pending_round: Optional[int] = None
        self._pending_model: Any = None
        self._silos_ready = False
        self._segment_t0: Optional[float] = None
        #: segment → (decoded ref, raw ref) for decoding stale silo uploads
        self._version_refs: "OrderedDict" = OrderedDict()
        self._region_staleness_spec = parse_staleness(
            getattr(args, "hier_region_staleness", None))
        self._region_staleness_cutoff = int(
            getattr(args, "hier_region_staleness_cutoff", 2) or 2)
        super().__init__(args, aggregator, comm, rank, client_num, backend)

    def set_fold_sink(self, sink: Callable) -> None:
        """``sink(segment, fold, n_silos, expected, silo_rounds, weight)``
        — the uplink's ship-one-delta-over-the-WAN entrypoint."""
        self._fold_sink = sink

    # -- segment lifecycle (the global server owns the round clock) ----------
    def _start_training(self) -> None:
        """All silos online.  Unlike the flat server there is nothing to
        broadcast yet — the segment opens when the global sync arrives."""
        with self._round_lock:
            self._silos_ready = True
            if self._pending_round is not None and not self.is_initialized:
                seg = self._pending_round
                model = self._pending_model
                self._pending_round = None
                self._pending_model = None
                self._begin_segment(seg, model)

    def start_global_round(self, round_idx: int, global_model: Any) -> None:
        """Uplink hand-off: the global server opened (or re-solicited)
        round ``round_idx`` for this region."""
        with self._round_lock:
            if self._finishing:
                return
            if self.is_initialized and int(round_idx) == int(
                    self.args.round_idx):
                # re-solicited segment already in flight (the global's
                # deadline pacer re-sent it): keep folding, don't restart
                return
            self._pending_round = int(round_idx)
            self._pending_model = global_model
            if self._silos_ready or self.is_initialized:
                seg = self._pending_round
                model = self._pending_model
                self._pending_round = None
                self._pending_model = None
                self._begin_segment(seg, model)

    def _begin_segment(self, round_idx: int, global_model: Any) -> None:
        """Open round segment ``round_idx``: adopt the global model,
        broadcast to the region's silos, arm the pacers.  Caller holds
        ``_round_lock``."""
        with self._round_lock:
            if self._finishing:
                return
            abandoned = self.aggregator.receive_count()
            if abandoned:
                # a newer segment supersedes an uncompleted one (our fold
                # for it was lost, or the quorum closed without us): its
                # partial uploads must not leak into the new fold
                logging.warning(
                    "region %s: abandoning segment %d with %d partial "
                    "uploads — global moved to %d", self._region,
                    self.args.round_idx, abandoned, round_idx)
                self.aggregator.reset_round_state()
            if self._run_span is None:
                mlops.log_aggregation_status("RUNNING")
                self._run_span = tracing.start_span(
                    "region_run", run_id=self._run_label,
                    region=self._region)
            self.aggregator.set_global_model_params(global_model)
            self.args.round_idx = int(round_idx)
            self._segment_folded = False
            self._segment_t0 = time.monotonic()
            self._silo_rounds = {}
            self._caught_up_this_round = set()
            self._quarantine_resolicits = {}
            self._round_train_metrics = {}
            self.is_initialized = True
            # the cohort IS the region's silo slice — global data-silo
            # indexes, fixed per region, never resampled
            self.client_id_list_in_this_round = list(self._silo_indices)
            self.data_silo_index_of_client = list(self._silo_indices)
            self._open_round_span()
            self._broadcast_round()
            self._arm_round_timer()
            self._arm_deadline_timer()
            self._persist_round_state()

    # -- versioned delta references (stale silo uploads still decode) --------
    def _note_round_ref(self, ref: Any, raw: Optional[Any] = None) -> None:
        super()._note_round_ref(ref, raw)
        version = int(self.args.round_idx)
        self._version_refs[version] = (ref, ref if raw is None else raw)
        while len(self._version_refs) > self._region_staleness_cutoff + 2:
            self._version_refs.popitem(last=False)

    def _ref_for(self, upload_round: int, raw: bool = False) -> Any:
        pair = self._version_refs.get(int(upload_round))
        if pair is not None:
            return pair[1] if raw else pair[0]
        return None

    # -- silo upload ingest (dedup → staleness → admission) ------------------
    def handle_message_receive_model_from_client(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        n_samples = msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES)
        with self._round_lock:
            if self._finishing:
                return
            if not self.is_initialized:
                # no segment open (fold already shipped, or waiting for
                # the first sync): a late upload cannot enter a closed
                # fold — the silo rejoins on the next segment broadcast
                logging.debug(
                    "region %s: dropping upload from silo %d outside an "
                    "open segment", self._region, sender)
                return
            seg = int(self.args.round_idx)
            upload_round = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND, seg))
            self._last_seen[sender] = time.monotonic()
            self.client_online_status[sender] = True
            if (sender, upload_round) in self._folded_silo_rounds:
                # dedup FIRST (before staleness/admission): this exact
                # silo upload is already inside a shipped fold
                _silo_uploads.labels(run_id=self._run_label,
                                     region=self._region,
                                     outcome="duplicate").inc()
                ledger.event("hier", "silo_duplicate", round_idx=seg,
                             client=sender, region=self._region,
                             upload_round=upload_round)
                logging.info(
                    "region %s: duplicate upload from silo %d for round "
                    "%d — already folded, dropped (keep-first)",
                    self._region, sender, upload_round)
                return
            staleness = seg - upload_round
            if staleness < 0:
                logging.warning(
                    "region %s: upload from silo %d claims FUTURE round "
                    "%d (segment %d) — dropped", self._region, sender,
                    upload_round, seg)
                return
            if staleness > self._region_staleness_cutoff:
                self._note_expired_upload(sender, staleness, "stale")
                return
            model = self._decode_upload(msg, upload_round)
            if model is None or model is _MISSING_REF:
                self._note_expired_upload(sender, staleness, "missing_ref")
                return
            train_metrics = msg.get(MyMessage.MSG_ARG_KEY_TRAIN_METRICS)
            if isinstance(train_metrics, dict) and train_metrics:
                self._round_train_metrics[sender] = train_metrics
            ledger.event("server", "receive", round_idx=seg, client=sender,
                         samples=n_samples, region=self._region)
            # regional staleness decay folds into the sample weight the
            # fused epilogue consumes — an old-but-admitted silo update
            # counts, just less
            weight = float(n_samples or 1.0) * staleness_weight(
                self._region_staleness_spec, float(staleness))
            reason = self.aggregator.add_local_trained_result(
                sender - 1, model, weight)
            if reason is not None:
                _silo_uploads.labels(run_id=self._run_label,
                                     region=self._region,
                                     outcome="quarantined").inc()
                n_prev = self._quarantine_resolicits.get(sender, 0)
                if n_prev < self._resolicit_max:
                    self._quarantine_resolicits[sender] = n_prev + 1
                    logging.warning(
                        "region %s: re-soliciting silo %d after "
                        "quarantined upload (%s, attempt %d/%d)",
                        self._region, sender, reason, n_prev + 1,
                        self._resolicit_max)
                    ledger.event("server", "resolicit", round_idx=seg,
                                 client=sender, reason=reason,
                                 attempt=n_prev + 1)
                    self._broadcast_round(only_rank=sender)
                else:
                    self._maybe_complete_early()
                return
            self._silo_rounds[sender] = upload_round
            _silo_uploads.labels(run_id=self._run_label,
                                 region=self._region, outcome="folded").inc()
            self._persist_round_state()
            if self.aggregator.check_whether_all_receive():
                self._complete_round()
                return
            self._maybe_complete_early()

    def _note_expired_upload(self, sender: int, staleness: int,
                             reason: str) -> None:
        """Expired silo upload: lateness, never quarantined.  Hand the
        silo the CURRENT segment (once per segment) so its next upload
        counts.  Caller holds ``_round_lock``."""
        _silo_uploads.labels(run_id=self._run_label, region=self._region,
                             outcome="expired").inc()
        ledger.event("hier", "silo_expired",
                     round_idx=int(self.args.round_idx), client=sender,
                     region=self._region, staleness=int(staleness),
                     reason=reason)
        logging.warning(
            "region %s: EXPIRED upload from silo %d (staleness %d, %s) — "
            "dropped, re-syncing to the segment", self._region, sender,
            staleness, reason)
        if sender not in self._caught_up_this_round:
            self._caught_up_this_round.add(sender)
            self._broadcast_round(only_rank=sender)

    def _decode_upload(self, msg: Message, upload_round: int) -> Any:
        """Raw | wire-codec | legacy TopK silo payload → model tree, or
        ``_MISSING_REF`` when the delta reference for ``upload_round`` is
        gone.  Caller holds ``_round_lock``."""
        model_params = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        if model_params is not None:
            return model_params
        wire_update = msg.get(MyMessage.MSG_ARG_KEY_WIRE_UPDATE)
        if wire_update is not None:
            from ...utils.compression import decode_delta

            ref = self._ref_for(upload_round)
            if ref is None:
                return _MISSING_REF
            return decode_delta(wire_update, ref)
        compressed = msg.get(MyMessage.MSG_ARG_KEY_COMPRESSED_UPDATE)
        if compressed is not None:
            import jax

            from ...utils.compression import TopKCompressor, tree_spec

            ref = self._ref_for(upload_round, raw=True)
            if ref is None:
                return _MISSING_REF
            delta = TopKCompressor().decompress(compressed, tree_spec(ref))
            return jax.tree_util.tree_map(lambda g, d: g + d, ref, delta)
        return None

    # -- the fold (regional round close) -------------------------------------
    def _complete_round(self) -> None:
        """Close the segment LOCALLY: fold the received silo set through
        the aggregator funnel (regional robust op) and hand the result to
        the uplink.  The round index does NOT advance — the next G2R sync
        is the only thing that opens a new segment.  Caller holds
        ``_round_lock``."""
        sink = None
        shipment = None
        with self._round_lock:
            if self._round_timer is not None:
                self._round_timer.cancel()
            if self._deadline_timer is not None:
                self._deadline_timer.cancel()
            closed = getattr(self, "_round_close_reason", None) or "full"
            self._round_close_reason = None
            if not self.is_initialized or self._segment_folded:
                return
            seg = int(self.args.round_idx)
            n_silos = self.aggregator.receive_count()
            if n_silos == 0:
                return
            expected = len(self.client_id_list_in_this_round)
            silo_rounds = dict(self._silo_rounds)
            total_weight = sum(
                float(self.aggregator.sample_num_dict[i])
                for i in range(self.client_num)
                if self.aggregator.has_received(i))
            with tracing.use_ctx(
                    self._round_span.ctx if self._round_span else None):
                fold = self.aggregator.aggregate()
            fold_s = (time.monotonic() - self._segment_t0
                      if self._segment_t0 else 0.0)
            _region_fold_seconds.labels(run_id=self._run_label,
                                        region=self._region).observe(fold_s)
            ledger.event("hier", "region_fold", round_idx=seg,
                         region=self._region, n_silos=int(n_silos),
                         expected=int(expected), closed=closed,
                         fold_s=round(fold_s, 3))
            slo.check_round_boundary(seg)
            if self._round_span is not None:
                self._round_span.set_attr("region", self._region)
                self._round_span.set_attr("clients_reported", n_silos)
                self._round_span.end()
                self._round_span = None
            self._segment_folded = True
            self.is_initialized = False
            for rank, trained in silo_rounds.items():
                self._folded_silo_rounds[(int(rank), int(trained))] = True
            while len(self._folded_silo_rounds) > _SILO_DEDUP_WINDOW:
                self._folded_silo_rounds.popitem(last=False)
            self._silo_rounds = {}
            # boundary checkpoint carries the fold marker: a resume from
            # here waits for the global to drive instead of re-training
            self._persist_round_state()
            sink = self._fold_sink
            shipment = (seg, fold, int(n_silos), int(expected), silo_rounds,
                        float(total_weight))
            logging.info(
                "region %s: folded segment %d (%d/%d silos, %s close, "
                "%.2fs)", self._region, seg, n_silos, expected, closed,
                fold_s)
        if sink is not None and shipment is not None:
            # ship OUTSIDE the lock: the WAN send may block (chaos
            # latency/bandwidth shaping) and must not stall silo ingest
            sink(*shipment)

    # -- crash-resume (RoundCheckpointer, fold-marker aware) -----------------
    def _persist_round_state(self) -> None:
        if self._ckpt is None or self._ckpt_writer is None:
            return
        state = {
            "round_idx": int(self.args.round_idx),
            "global_model": self.aggregator.get_global_model_params(),
            "hier_folded": np.asarray(1 if self._segment_folded else 0),
        }
        if self._silo_rounds:
            state["hier_silo_rounds"] = {
                str(k): np.asarray(int(v))
                for k, v in self._silo_rounds.items()}
        if self._folded_silo_rounds:
            state["hier_folded_pairs"] = np.asarray(
                [[r, t] for r, t in self._folded_silo_rounds], dtype=np.int64)
        state.update(self.aggregator.export_round_state())
        self._ckpt_writer.submit(
            self._write_round_state, int(self.args.round_idx), state)

    def _try_resume(self, resume: Any) -> None:
        if resume is True or str(resume).strip().lower() in (
                "latest", "true", "yes"):
            step = None
        else:
            step = int(resume)
        state = self._ckpt.restore(step)
        if state is None:
            logging.warning(
                "region %s: resume_from=%r but no usable checkpoint in %s "
                "— starting fresh", self._region, resume, self._ckpt.dir)
            return
        self.args.round_idx = int(np.asarray(state["round_idx"]))
        self.aggregator.set_global_model_params(state["global_model"])
        self.aggregator.restore_round_state(state)
        self._segment_folded = bool(
            int(np.asarray(state.get("hier_folded", 0))))
        self._silo_rounds = {
            int(k): int(np.asarray(v))
            for k, v in (state.get("hier_silo_rounds") or {}).items()}
        pairs = state.get("hier_folded_pairs")
        if pairs is not None:
            for rank, trained in np.asarray(pairs).reshape(-1, 2):
                self._folded_silo_rounds[(int(rank), int(trained))] = True
        self._resumed = True
        logging.warning(
            "region %s: resumed at segment %d with %d/%d silo results "
            "(folded=%s)", self._region, self.args.round_idx,
            self.aggregator.receive_count(), self.client_num,
            self._segment_folded)

    def _resume_training(self) -> None:
        """Re-enter the checkpointed segment.  Two cases:

        * fold already computed before the crash → nothing to redo
          locally; wait for the global server to drive (its dedup absorbs
          a duplicate fold if ours landed; its deadline re-solicit
          re-opens the segment if it never did);
        * mid-segment crash → re-open the segment and re-solicit ONLY the
          missing silos: each surviving silo's first post-restart
          heartbeat is an unseen-rank sighting, and the base late-join
          catch-up re-sends the segment to exactly the ranks whose
          uploads aren't in the restored received set."""
        with self._round_lock:
            seg = int(self.args.round_idx)
            # the silos announced ONLINE to the PREVIOUS incarnation and
            # will only heartbeat from here — without this, the segment
            # after the resumed one parks in _pending forever waiting for
            # announces that never come
            self._silos_ready = True
            if self._segment_folded:
                logging.warning(
                    "region %s: segment %d was already folded before the "
                    "crash — waiting for the global server to drive",
                    self._region, seg)
                return
            mlops.log_aggregation_status("RUNNING")
            self._run_span = tracing.start_span(
                "region_run", run_id=self._run_label, region=self._region,
                resumed_at=seg)
            self.is_initialized = True
            self._segment_t0 = time.monotonic()
            self.client_id_list_in_this_round = list(self._silo_indices)
            self.data_silo_index_of_client = list(self._silo_indices)
            self._open_round_span()
            # re-register the restored global as the segment's delta
            # reference; silos re-solicited via catch-up get a fresh
            # broadcast (and a fresh ref) anyway
            self._note_round_ref(self.aggregator.get_global_model_params())
            self._arm_round_timer()
            self._arm_deadline_timer()
            if self.aggregator.check_whether_all_receive():
                logging.warning(
                    "region %s: resumed segment %d already has every silo "
                    "— folding immediately", self._region, seg)
                self._complete_round()

    def region_finish(self) -> None:
        """G2R FINISH relay: wind down the region's silos and this node."""
        with self._round_lock:
            if self._finishing:
                return
        logging.info("region %s: finish", self._region)
        self.send_finish_to_all()
        mlops.log_aggregation_status("FINISHED")
        if self._run_span is not None:
            self._run_span.end()
            self._run_span = None
        self.finish()


class RegionUplink(FedMLCommManager):
    """The region's WAN face (rank = region index on the WAN plane)."""

    def __init__(self, args: Any, region: str,
                 region_manager: RegionalAggregatorManager, comm=None,
                 rank: int = 0, size: int = 0,
                 backend: str = "INPROC") -> None:
        self._region = str(region)
        self._region_mgr = region_manager
        self._wire_codec = None
        self._wire_codec_spec = ""
        #: segment → decoded global broadcast (the fold's delta reference)
        self._segment_refs: "OrderedDict" = OrderedDict()
        self._hb_stop = threading.Event()
        super().__init__(args, comm, rank, size, backend)
        # the fold sink reference wires the LAN fold into the WAN send —
        # the one emission that lets every global round reach FINISH
        region_manager.set_fold_sink(self.send_fold)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            HierMessage.MSG_TYPE_G2R_INIT_CONFIG,
            self.handle_message_global_segment)
        self.register_message_receive_handler(
            HierMessage.MSG_TYPE_G2R_SYNC_MODEL,
            self.handle_message_global_segment)
        self.register_message_receive_handler(
            HierMessage.MSG_TYPE_G2R_FINISH, self.handle_message_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.send_region_status()
        self._start_heartbeat()
        self.com_manager.handle_receive_message()

    def finish(self) -> None:
        self._hb_stop.set()
        super().finish()

    # -- liveness (the global failure detector judges REGIONS) ---------------
    def _start_heartbeat(self) -> None:
        interval = float(getattr(self.args, "heartbeat_interval_s", 0) or 0)
        if interval <= 0:
            return

        def _loop() -> None:
            while not self._hb_stop.wait(interval):
                try:
                    msg = Message(MyMessage.MSG_TYPE_HEARTBEAT,
                                  self.get_sender_id(), 0)
                    msg.add_params(MyMessage.MSG_ARG_KEY_HEARTBEAT_TS,
                                   time.time())
                    msg.add_params(ARG_VOLATILE, True)
                    self.send_message(msg)
                except Exception:  # noqa: BLE001 — a failed beat is a
                    # missed beat, nothing to escalate from here
                    logging.debug("region %s: heartbeat send failed",
                                  self._region, exc_info=True)

        threading.Thread(target=_loop, daemon=True,
                         name=f"hier-heartbeat-{self._region}").start()

    # -- protocol ------------------------------------------------------------
    def send_region_status(self) -> None:
        from ...utils.compression import WIRE_CAPS

        msg = Message(HierMessage.MSG_TYPE_R2G_REGION_STATUS,
                      self.get_sender_id(), 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                       MyMessage.CLIENT_STATUS_ONLINE)
        msg.add_params(HierMessage.MSG_ARG_KEY_REGION, self._region)
        msg.add_params(HierMessage.MSG_ARG_KEY_EXPECTED_SILOS,
                       int(self._region_mgr.client_num))
        msg.add_params(MyMessage.MSG_ARG_KEY_WIRE_CAPS, list(WIRE_CAPS))
        self.send_message(msg)

    def handle_message_global_segment(self, msg: Message) -> None:
        """G2R segment broadcast: decode (mirroring the silo client's
        broadcast unpack), remember the delta reference, hand the segment
        to the regional aggregator."""
        global_model = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        if msg.get(MyMessage.MSG_ARG_KEY_MODEL_ENCODED):
            from ...utils.compression import WireCodec

            global_model = WireCodec.decode_model(global_model)
        codec_spec = msg.get(MyMessage.MSG_ARG_KEY_WIRE_CODEC)
        if codec_spec and str(codec_spec) != self._wire_codec_spec:
            from ...utils.compression import WireCodec

            self._wire_codec = WireCodec(str(codec_spec))
            self._wire_codec_spec = str(codec_spec)
        elif not codec_spec:
            self._wire_codec = None
            self._wire_codec_spec = ""
        round_idx = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND, 0))
        self._segment_refs[round_idx] = global_model
        while len(self._segment_refs) > 8:
            self._segment_refs.popitem(last=False)
        self._region_mgr.start_global_round(round_idx, global_model)

    def send_fold(self, segment: int, fold: Any, n_silos: int,
                  expected: int, silo_rounds: Dict[int, int],
                  total_weight: float) -> None:
        """Ship the region's ONE pre-reduced delta for ``segment`` over
        the WAN — codec-compressed against the decoded segment broadcast
        when a wire codec was negotiated."""
        from ...utils.serialization import estimate_nbytes

        msg = Message(HierMessage.MSG_TYPE_R2G_REGION_FOLD,
                      self.get_sender_id(), 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, int(segment))
        msg.add_params(HierMessage.MSG_ARG_KEY_REGION, self._region)
        msg.add_params(HierMessage.MSG_ARG_KEY_N_SILOS, int(n_silos))
        msg.add_params(HierMessage.MSG_ARG_KEY_EXPECTED_SILOS, int(expected))
        msg.add_params(HierMessage.MSG_ARG_KEY_SILO_ROUNDS,
                       [[int(r), int(t)]
                        for r, t in sorted(silo_rounds.items())])
        msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES,
                       float(total_weight))
        ref = self._segment_refs.get(int(segment))
        if self._wire_codec is not None and ref is not None:
            payload = self._wire_codec.encode_delta(fold, ref)
            msg.add_params(MyMessage.MSG_ARG_KEY_WIRE_UPDATE, payload)
            codec = self._wire_codec.spec.kind
            nbytes = estimate_nbytes(payload)
        else:
            msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, fold)
            codec = "raw"
            nbytes = estimate_nbytes(fold)
        run_label = str(getattr(self.args, "run_id", "0"))
        _wire_bytes.labels(run_id=run_label, direction="up",
                           codec=codec).inc(nbytes)
        from .global_server_manager import _wan_bytes

        _wan_bytes.labels(run_id=run_label, direction="up").inc(nbytes)
        ledger.event("hier", "region_ship", round_idx=int(segment),
                     region=self._region, nbytes=int(nbytes), codec=codec,
                     n_silos=int(n_silos), expected=int(expected))
        logging.info(
            "region %s: shipping fold for segment %d over the WAN "
            "(%d/%d silos, %d bytes, %s)", self._region, segment, n_silos,
            expected, nbytes, codec)
        self.send_message(msg)

    def handle_message_finish(self, msg: Message) -> None:
        self._region_mgr.region_finish()
        self.finish()
