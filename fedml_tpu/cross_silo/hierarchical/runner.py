"""Hierarchical federation runner: edge → region → global in one process.

Topology derivation: ``--hier-regions R`` splits the
``client_num_in_total`` silos into R contiguous slices, one regional
aggregator + WAN uplink per slice.  Two comm planes share the INPROC
hub by run_id:

* the WAN plane (global rank 0 + uplink ranks 1..R) runs on the base
  ``run_id`` — so ``fedml_wire_bytes_total{run_id=<base>}`` counts ONLY
  bytes that cross the WAN, the quantity the hierarchy exists to shrink;
* each region's LAN plane runs on ``<run_id>/lan-<region>`` with the
  regional manager at rank 0 and STOCK silo clients at ranks 1..k.

Per-tier knobs (all optional, ``getattr`` with defaults): the WAN tier
reads ``min_regions`` (quorum floor, default all regions),
``hier_global_robust_agg`` (default ``median``),
``hier_global_staleness`` / ``hier_staleness_cutoff``,
``hier_round_timeout_s`` / ``hier_round_deadline_s`` /
``hier_heartbeat_interval_s`` (default: the flat-tier values) and
``hier_wan_compression`` / ``hier_wan_reliable`` (default: the flat
wire settings).  The region tier reads ``hier_region_robust_agg``
(default ``trimmed_mean:0.2``), ``hier_region_staleness`` /
``hier_region_staleness_cutoff`` and ``hier_min_silos``.

``RegionNode.hard_kill()`` is the SIGKILL analog for the in-process
plane (receive loops stopped with no protocol goodbye), and
``HierarchicalFederationRunner.restart_region()`` rebuilds the region's
manager + uplink resuming from its round-boundary checkpoint — the
chaos soak's crash lever.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ...ml.trainer.default_trainer import DefaultServerAggregator
from ..server.fedml_aggregator import FedMLAggregator
from .global_server_manager import GlobalServerManager
from .regional_manager import RegionalAggregatorManager, RegionUplink


def _clone_args(args: Any, **overrides: Any) -> Any:
    from ...arguments import Config

    base = (args.to_dict() if hasattr(args, "to_dict")
            else dict(vars(args)))
    base.update(overrides)
    return Config(**base)


def hier_layout(args: Any) -> List[Tuple[str, List[int]]]:
    """``(region name, global silo indices)`` per region — contiguous
    slices of the silo population, remainder spread over the first
    regions."""
    n_regions = int(getattr(args, "hier_regions", 0) or 0)
    if n_regions < 2:
        raise ValueError(
            f"hier_regions={n_regions}: the hierarchy needs >= 2 regions "
            "(use the flat runner for one)")
    total = int(args.client_num_in_total)
    if total < n_regions:
        raise ValueError(
            f"client_num_in_total={total} < hier_regions={n_regions}: "
            "every region needs at least one silo")
    names = getattr(args, "hier_region_names", None)
    names = ([str(x) for x in names] if names
             else [f"r{i}" for i in range(n_regions)])
    if len(names) != n_regions:
        raise ValueError(
            f"hier_region_names has {len(names)} entries for "
            f"{n_regions} regions")
    base, rem = divmod(total, n_regions)
    layout, start = [], 0
    for i in range(n_regions):
        count = base + (1 if i < rem else 0)
        layout.append((names[i], list(range(start, start + count))))
        start += count
    return layout


class RegionNode:
    """One region's aggregation pair: LAN manager + WAN uplink.  Its silo
    clients are NOT part of the node — they are separate (surviving)
    processes in spirit, so a hard-killed region leaves them running and
    the resumed manager re-solicits only the missing ones."""

    def __init__(self, name: str, region_rank: int, silo_indices: List[int],
                 region_args: Any, uplink_args: Any, dataset: Tuple,
                 bundle: Any, n_regions: int, lan_backend: str,
                 wan_backend: str) -> None:
        self.name = name
        self.region_rank = int(region_rank)
        impl = DefaultServerAggregator(bundle, region_args)
        agg = FedMLAggregator(region_args, impl, dataset[3])
        self.manager = RegionalAggregatorManager(
            region_args, agg, name, silo_indices, rank=0,
            client_num=len(silo_indices), backend=lan_backend)
        self.uplink = RegionUplink(
            uplink_args, name, self.manager, rank=region_rank,
            size=n_regions + 1, backend=wan_backend)
        self.threads: List[threading.Thread] = []

    def start(self) -> None:
        self.threads = [self.manager.run_async(), self.uplink.run_async()]

    def hard_kill(self) -> None:
        """SIGKILL analog for the in-process plane: silence the node with
        NO protocol goodbye — receive loops stop, timers and heartbeats
        die, nothing is broadcast.  Queued round-boundary checkpoint
        writes are drained first (the write-first-delete-after layout
        makes a torn write unreadable anyway; draining keeps the test
        lever deterministic)."""
        with self.manager._round_lock:
            self.manager._finishing = True
            for timer in (self.manager._round_timer,
                          self.manager._init_timer,
                          self.manager._deadline_timer):
                if timer is not None:
                    timer.cancel()
        self.manager._hb_stop.set()
        self.uplink._hb_stop.set()
        for node in (self.manager, self.uplink):
            try:
                node.com_manager.stop_receive_message()
            except Exception:  # noqa: BLE001 — a dead node stays dead
                logging.debug("region %s: hard-kill stop failed",
                              self.name, exc_info=True)
        if self.manager._ckpt_writer is not None:
            self.manager._ckpt_writer.shutdown(wait=True)
            self.manager._ckpt_writer = None
        logging.warning("region %s: HARD-KILLED (no goodbye)", self.name)

    def join(self, timeout: Optional[float] = None) -> None:
        for t in self.threads:
            t.join(timeout=timeout)


class HierarchicalFederationRunner:
    """Global server + R (regional aggregator, uplink) pairs + stock silo
    clients over INPROC threads; ``train()`` blocks until the global run
    finishes and returns its final eval metrics."""

    JOIN_TIMEOUT_S = 30.0

    def __init__(self, args: Any, device: Any, dataset: Tuple, bundle: Any,
                 client_trainer: Optional[Any] = None,
                 server_aggregator: Optional[Any] = None) -> None:
        self.args = args
        self.dataset = dataset
        self.bundle = bundle
        self.client_trainer = client_trainer
        self.server_aggregator = server_aggregator
        self.layout = hier_layout(args)
        self.n_regions = len(self.layout)
        backend = str(getattr(args, "backend", "INPROC")).upper()
        self.wan_backend = str(
            getattr(args, "hier_wan_backend", backend) or backend).upper()
        self.lan_backend = str(
            getattr(args, "hier_lan_backend", backend) or backend).upper()
        self.global_manager: Optional[GlobalServerManager] = None
        self.regions: Dict[str, RegionNode] = {}
        self._region_args: Dict[str, Any] = {}
        self._silo_threads: List[threading.Thread] = []
        self._global_thread: Optional[threading.Thread] = None

    # -- per-tier argument derivation ----------------------------------------
    def _ckpt_subdir(self, leaf: str) -> Optional[str]:
        root = getattr(self.args, "checkpoint_dir", None)
        return os.path.join(str(root), leaf) if root else None

    def _wan_args(self, **overrides: Any) -> Any:
        a = self.args
        min_regions = (int(getattr(a, "min_regions", 0) or 0)
                       or self.n_regions)
        return _clone_args(
            a,
            client_num_in_total=self.n_regions,
            client_num_per_round=self.n_regions,
            over_provision=0,
            min_clients_per_round=min_regions,
            min_aggregation_clients=min_regions,
            min_regions=min_regions,
            robust_agg=str(getattr(a, "hier_global_robust_agg", "median")
                           or "median"),
            wire_compression=getattr(
                a, "hier_wan_compression",
                getattr(a, "wire_compression", None)),
            reliable=bool(getattr(a, "hier_wan_reliable",
                                  getattr(a, "reliable", False))),
            heartbeat_interval_s=float(getattr(
                a, "hier_heartbeat_interval_s",
                getattr(a, "heartbeat_interval_s", 0) or 0) or 0),
            round_timeout_s=float(getattr(
                a, "hier_round_timeout_s",
                getattr(a, "round_timeout_s", 0) or 0) or 0),
            round_deadline_s=float(getattr(
                a, "hier_round_deadline_s",
                getattr(a, "round_deadline_s", 0) or 0) or 0),
            checkpoint_dir=self._ckpt_subdir("global"),
            **overrides)

    def region_args_for(self, name: str, n_silos: int,
                        resume: Any = None) -> Any:
        a = self.args
        min_silos = int(getattr(a, "hier_min_silos", 1) or 1)
        return _clone_args(
            a,
            run_id=f"{getattr(a, 'run_id', '0')}/lan-{name}",
            client_num_in_total=n_silos,
            client_num_per_round=n_silos,
            over_provision=0,
            min_clients_per_round=min_silos,
            min_aggregation_clients=min_silos,
            robust_agg=str(getattr(a, "hier_region_robust_agg",
                                   "trimmed_mean:0.2")
                           or "trimmed_mean:0.2"),
            checkpoint_dir=self._ckpt_subdir(f"region-{name}"),
            resume_from=resume if resume is not None
            else getattr(a, "resume_from", None))

    # -- construction --------------------------------------------------------
    def _build_global(self) -> GlobalServerManager:
        import jax

        wan_args = self._wan_args()
        impl = (self.server_aggregator
                or DefaultServerAggregator(self.bundle, wan_args))
        if impl.get_model_params() is None:
            rng = jax.random.PRNGKey(
                int(getattr(wan_args, "random_seed", 0) or 0))
            impl.set_model_params(self.bundle.init_variables(rng))
        agg = FedMLAggregator(wan_args, impl, self.dataset[3])
        return GlobalServerManager(wan_args, agg, rank=0,
                                   client_num=self.n_regions,
                                   backend=self.wan_backend)

    def _build_region(self, name: str, region_rank: int,
                      silo_indices: List[int],
                      resume: Any = None) -> RegionNode:
        region_args = self.region_args_for(name, len(silo_indices), resume)
        self._region_args[name] = region_args
        uplink_args = self._wan_args()
        return RegionNode(name, region_rank, silo_indices, region_args,
                          uplink_args, self.dataset, self.bundle,
                          self.n_regions, self.lan_backend,
                          self.wan_backend)

    def _trainer_for(self, rank: int):
        """``rank`` is the FLAT silo rank (global silo index + 1) so a
        callable trainer targets the same client it would in the flat
        runner, independent of the region layout."""
        if callable(self.client_trainer) and not hasattr(
                self.client_trainer, "train"):
            return self.client_trainer(rank)
        return self.client_trainer

    # -- lifecycle -----------------------------------------------------------
    def launch(self) -> "HierarchicalFederationRunner":
        from ..runner import init_client

        self.global_manager = self._build_global()
        for region_rank, (name, silos) in enumerate(self.layout, start=1):
            node = self._build_region(name, region_rank, silos)
            self.regions[name] = node
        # stock silo clients per region LAN plane (ranks 1..k) — they are
        # deliberately NOT owned by the RegionNode: a killed region leaves
        # its silos running, like real silo hosts surviving an aggregator
        # crash
        for name, silos in self.layout:
            region_args = self._region_args[name]
            for local_rank in range(1, len(silos) + 1):
                client = init_client(region_args, self.dataset, self.bundle,
                                     local_rank,
                                     self._trainer_for(
                                         silos[local_rank - 1] + 1),
                                     backend=self.lan_backend)
                t = threading.Thread(target=client.run, daemon=True,
                                     name=f"silo-{name}-{local_rank}")
                t.start()
                self._silo_threads.append(t)
        for node in self.regions.values():
            node.start()
        self._global_thread = self.global_manager.run_async()
        return self

    def restart_region(self, name: str) -> RegionNode:
        """Rebuild a (hard-killed) region's manager + uplink, resuming
        from its round-boundary checkpoint.  Its silos kept running — the
        resumed manager re-solicits only the ones missing from the
        restored received set."""
        old = self.regions[name]
        silos = dict(self.layout)[name]
        node = self._build_region(name, old.region_rank, silos,
                                  resume="latest")
        self.regions[name] = node
        node.start()
        logging.warning("region %s: RESTARTED from checkpoint", name)
        return node

    def wait(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        self._global_thread.join(timeout=timeout)
        for node in self.regions.values():
            node.join(timeout=self.JOIN_TIMEOUT_S)
        for t in self._silo_threads:
            t.join(timeout=self.JOIN_TIMEOUT_S)
        hist = self.global_manager.aggregator.metrics_history
        return hist[-1] if hist else {}

    def train(self) -> Dict[str, Any]:
        self.launch()
        return self.wait()
