"""Global server of the geo-distributed aggregation hierarchy.

A ``FedMLServerManager`` whose "clients" are REGIONS: the PR-4 heartbeat
failure detector, elastic round timeout, deadline pacer, late-join
catch-up, quarantine re-solicitation and round-boundary checkpointing
all run unchanged over region ranks — a region dead or partitioned is
dropped from the round exactly like a dead silo, the global round closes
on a ``--min-regions`` quorum, and a rejoining region is re-admitted
with a frontier catch-up broadcast.

What changes is the wire and the dedup domain:

* broadcasts go out as ``G2R_INIT_CONFIG`` / ``G2R_SYNC_MODEL`` (one per
  region, codec-negotiated per WAN link) and uploads arrive as
  ``R2G_REGION_FOLD`` — ONE pre-reduced delta per region per round
  segment, so uplink WAN bytes drop by ~silo-fanout before codecs apply;
* the robustness composition repeats at this tier, in the same strict
  order as every other ingest path (docs/ROBUSTNESS.md): **dedup**
  (keep-first on ``(region, fold_round)`` PLUS a ``(region, silo,
  round)`` triple audit — a retransmitted or re-computed regional fold
  can never double-count any silo upload), **staleness** (global decay
  on region arrival round, cutoff → expired + frontier re-sync),
  **admission** (the same quarantine screen, fold-level), **robust
  aggregation** (``--hier-global-robust-agg``, default ``median`` over
  regions — a whole byzantine region is one outlier among R).
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict
from typing import Any, Dict, Optional

from ...core.mlops import flight_recorder, ledger, metrics, tracing
from ...core.distributed.communication.message import Message
from ...ml.aggregator.staleness import parse_staleness, staleness_weight
from ...utils.compression import WIRE_BYTES as _wire_bytes
from ..message_define import MyMessage
from ..server.fedml_aggregator import FedMLAggregator
from ..server.fedml_server_manager import FedMLServerManager
from .message_define import HierMessage

_region_folds = metrics.counter(
    "fedml_region_folds_total",
    "Regional folds handled by the global server, by outcome (folded | "
    "duplicate | expired | quarantined)", labels=("run_id", "outcome"))
_region_dropouts = metrics.counter(
    "fedml_region_dropouts_total",
    "Regions dropped from a global round by a fault-domain verdict "
    "(heartbeat | deadline)", labels=("run_id", "cause"))
_wan_bytes = metrics.counter(
    "fedml_wan_bytes_total",
    "Bytes crossing the WAN tier of the aggregation hierarchy (broadcast "
    "segments down, regional folds up) — LAN silo traffic excluded",
    labels=("run_id", "direction"))

#: bound on the (region, fold_round) / (region, silo, round) audit windows
_FOLD_DEDUP_WINDOW = 4096

#: a fold delta whose trained-against global reference is no longer held
_MISSING_REF = object()


class GlobalServerManager(FedMLServerManager):
    def __init__(self, args: Any, aggregator: FedMLAggregator, comm=None,
                 rank: int = 0, client_num: int = 0,
                 backend: str = "INPROC") -> None:
        #: WAN rank → region name (learned from R2G_REGION_STATUS)
        self._region_names: Dict[int, str] = {}
        #: WAN rank → silo count the region expects per segment (its LAN
        #: fleet size) — partial folds (n_silos < this) are visible in
        #: the round anatomy
        self._region_expected: Dict[int, int] = {}
        #: keep-first dedup over (region rank, fold_round)
        self._seen_folds: "OrderedDict" = OrderedDict()
        #: every (region rank, silo rank, silo round) triple already
        #: counted into SOME global round — the cross-tier dedup key: a
        #: re-computed fold overlapping a counted triple is rejected whole
        self._counted_triples: "OrderedDict" = OrderedDict()
        #: round → (decoded ref, raw ref) for decoding stale fold deltas
        self._version_refs: "OrderedDict" = OrderedDict()
        super().__init__(args, aggregator, comm, rank, client_num, backend)
        self._staleness_spec = parse_staleness(
            getattr(args, "hier_global_staleness", None))
        self._staleness_cutoff = int(
            getattr(args, "hier_staleness_cutoff", 3) or 3)
        # --min-regions is the quorum floor for BOTH pacers: a global
        # round never closes below it, and init force-starts at it
        min_regions = int(getattr(args, "min_regions", 0) or 0)
        if min_regions:
            self.min_clients = max(self.min_clients, min_regions)
            self.min_agg_clients = max(self.min_agg_clients, min_regions)

    def _region_name(self, rank: int) -> str:
        return self._region_names.get(rank, f"region{rank}")

    # -- protocol ------------------------------------------------------------
    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            HierMessage.MSG_TYPE_R2G_REGION_STATUS,
            self.handle_message_region_status)
        self.register_message_receive_handler(
            HierMessage.MSG_TYPE_R2G_REGION_FOLD,
            self.handle_message_region_fold)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_HEARTBEAT, self.handle_message_heartbeat)

    def handle_message_region_status(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        region = msg.get(HierMessage.MSG_ARG_KEY_REGION)
        caps = msg.get(MyMessage.MSG_ARG_KEY_WIRE_CAPS)
        expected = msg.get(HierMessage.MSG_ARG_KEY_EXPECTED_SILOS)
        with self._round_lock:
            if region:
                self._region_names[sender] = str(region)
            if expected:
                self._region_expected[sender] = int(expected)
            if caps:
                self._peer_caps[sender] = tuple(str(c) for c in caps)
            self._mark_alive(sender, announce=True)
            n_online = sum(self.client_online_status.values())
        logging.info("global: region %s (rank %d) online (%d/%d regions)",
                     self._region_name(sender), sender, n_online,
                     self.client_num)

    def _mark_alive(self, sender: int, announce: bool = False) -> None:
        with self._round_lock:
            if self.client_online_status.get(sender) is False:
                ledger.event("hier", "region_rejoin",
                             round_idx=int(self.args.round_idx),
                             region=self._region_name(sender))
            super()._mark_alive(sender, announce)

    def _note_peers_dead(self, ranks, cause: str) -> None:
        """Fault-domain verdict at the REGION tier: heartbeat-dead or
        deadline-dropped regions are per-tier telemetry, not just a
        shrunken cohort."""
        for rank in ranks:
            _region_dropouts.labels(run_id=self._run_label,
                                    cause=cause).inc()
            ledger.event("hier", "region_drop",
                         round_idx=int(self.args.round_idx),
                         region=self._region_name(rank), cause=cause)

    # -- broadcast (G2R wire, one segment per region) ------------------------
    def _note_round_ref(self, ref: Any, raw: Optional[Any] = None) -> None:
        """Version the delta references like the async manager: a fold for
        segment t decodes against ref[t], not the frontier."""
        super()._note_round_ref(ref, raw)
        version = int(self.args.round_idx)
        self._version_refs[version] = (ref, ref if raw is None else raw)
        while len(self._version_refs) > self._staleness_cutoff + 2:
            self._version_refs.popitem(last=False)

    def _ref_for(self, fold_round: int, raw: bool = False) -> Any:
        pair = self._version_refs.get(int(fold_round))
        if pair is not None:
            return pair[1] if raw else pair[0]
        return None

    def _broadcast_round(self, only_rank=None) -> None:
        """Ship the current round segment to every region (or just the
        re-solicited/rejoining ones).  Same shape as the flat broadcast —
        per-link codec negotiation, one full-model encode per round via
        ``_enc_cache`` — but on the G2R wire, with the region's name in
        place of a client index.  Caller holds ``_round_lock``."""
        with self._round_lock:
            from ...utils.serialization import estimate_nbytes

            only = (None if only_rank is None
                    else {only_rank} if isinstance(only_rank, int)
                    else set(only_rank))
            mtype = (HierMessage.MSG_TYPE_G2R_SYNC_MODEL
                     if self.args.round_idx else
                     HierMessage.MSG_TYPE_G2R_INIT_CONFIG)
            global_model = self.aggregator.get_global_model_params()
            enc_payload = None
            if self._wire_spec is not None:
                from ...utils.compression import WireCodec

                version = int(self.args.round_idx)
                if (self._enc_cache is not None
                        and self._enc_cache[0] == version):
                    _, enc_payload, decoded = self._enc_cache
                else:
                    enc_payload = WireCodec.encode_model(
                        global_model,
                        "bf16" if self._wire_spec.kind == "bf16" else "int8")
                    decoded = WireCodec.decode_model(enc_payload)
                    self._enc_cache = (version, enc_payload, decoded)
                self._note_round_ref(decoded, raw=global_model)
            else:
                self._note_round_ref(global_model)
            with flight_recorder.phase("comm",
                                       program="hier/global_broadcast"):
                for rank in range(1, self.client_num + 1):
                    if only is not None and rank not in only:
                        continue
                    use_codec = (enc_payload is not None
                                 and self._link_codec(rank))
                    msg = Message(mtype, self.get_sender_id(), rank)
                    msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                                   enc_payload if use_codec else global_model)
                    if use_codec:
                        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_ENCODED,
                                       True)
                        msg.add_params(
                            MyMessage.MSG_ARG_KEY_WIRE_CODEC,
                            str(getattr(self.args, "wire_compression")))
                    msg.add_params(HierMessage.MSG_ARG_KEY_REGION,
                                   self._region_name(rank))
                    msg.add_params(MyMessage.MSG_ARG_KEY_ROUND,
                                   self.args.round_idx)
                    if self._round_span is not None:
                        msg.add_params(MyMessage.MSG_ARG_KEY_TRACE_CTX,
                                       tracing.inject(self._round_span.ctx))
                    nbytes = estimate_nbytes(
                        enc_payload if use_codec else global_model)
                    codec = self._wire_spec.kind if use_codec else "raw"
                    _wire_bytes.labels(run_id=self._run_label,
                                       direction="down", codec=codec).inc(
                        nbytes)
                    _wan_bytes.labels(run_id=self._run_label,
                                      direction="down").inc(nbytes)
                    flight_recorder.note_transfer("comm", nbytes)
                    ledger.event("hier", "segment_solicit",
                                 round_idx=int(self.args.round_idx),
                                 region=self._region_name(rank),
                                 nbytes=int(nbytes), codec=codec)
                    self.send_message(msg)

    # -- the fold ingest path ------------------------------------------------
    def handle_message_region_fold(self, msg: Message) -> None:
        """One pre-reduced regional delta.  Composition order is strict:
        dedup → staleness cutoff → admission → robust aggregation (via the
        aggregator funnel at round close)."""
        sender = msg.get_sender_id()
        with self._round_lock:
            if self._finishing or not self.is_initialized:
                return
            version = int(self.args.round_idx)
            region = str(msg.get(HierMessage.MSG_ARG_KEY_REGION)
                         or self._region_name(sender))
            self._region_names.setdefault(sender, region)
            fold_round = int(msg.get(MyMessage.MSG_ARG_KEY_ROUND, version))
            self._last_seen[sender] = time.monotonic()
            was_online = self.client_online_status.get(sender)
            self.client_online_status[sender] = True
            if was_online is False:
                ledger.event("hier", "region_rejoin", round_idx=version,
                             region=region)
            n_silos = int(msg.get(HierMessage.MSG_ARG_KEY_N_SILOS, 0) or 0)
            expected = int(
                msg.get(HierMessage.MSG_ARG_KEY_EXPECTED_SILOS, 0)
                or self._region_expected.get(sender, 0) or 0)
            pairs = msg.get(HierMessage.MSG_ARG_KEY_SILO_ROUNDS) or []
            triples = {(sender, int(r), int(t)) for r, t in pairs}
            key = (sender, fold_round)
            retransmit = key in self._seen_folds
            if retransmit or any(
                    t in self._counted_triples for t in triples):
                # keep-first: a retransmitted fold, or a re-computed one
                # (post-crash regional re-fold) overlapping ANY silo
                # upload already counted into some global round, never
                # folds twice — the (region, silo, round) triples are the
                # cross-tier dedup key
                _region_folds.labels(run_id=self._run_label,
                                     outcome="duplicate").inc()
                ledger.event("hier", "fold_duplicate", round_idx=version,
                             region=region, fold_round=fold_round)
                logging.info(
                    "global: duplicate fold from region %s for round %d "
                    "— dropped (keep-first)", region, fold_round)
                if not retransmit:
                    # triple overlap under a FRESH (region, fold_round)
                    # key: a re-computed fold, not a wire retransmit —
                    # this round has NO usable fold from the region yet,
                    # so re-solicit the segment (bounded, like the
                    # quarantine path) for a re-fold from fresh uploads
                    self._seen_folds[key] = True
                    self._trim_windows()
                    n_prev = self._quarantine_resolicits.get(sender, 0)
                    if n_prev < self._resolicit_max:
                        self._quarantine_resolicits[sender] = n_prev + 1
                        self._broadcast_round(only_rank=sender)
                return
            staleness = version - fold_round
            if staleness < 0:
                logging.warning(
                    "global: fold from region %s claims FUTURE round %d "
                    "(now %d) — dropped", region, fold_round, version)
                return
            if staleness > self._staleness_cutoff:
                # lateness, not hostility: the fold expired past the
                # staleness cutoff — drop it and re-sync the region to
                # the frontier so its next segment counts
                self._seen_folds[key] = True
                self._trim_windows()
                _region_folds.labels(run_id=self._run_label,
                                     outcome="expired").inc()
                ledger.event("hier", "fold_expired", round_idx=version,
                             region=region, staleness=staleness)
                logging.warning(
                    "global: EXPIRED fold from region %s (segment %d, now "
                    "%d > cutoff %d) — dropped, re-syncing to frontier",
                    region, fold_round, version, self._staleness_cutoff)
                self._broadcast_round(only_rank=sender)
                return
            model = self._decode_fold(msg, fold_round)
            if model is None or model is _MISSING_REF:
                self._seen_folds[key] = True
                self._trim_windows()
                _region_folds.labels(run_id=self._run_label,
                                     outcome="expired").inc()
                ledger.event("hier", "fold_expired", round_idx=version,
                             region=region, staleness=staleness,
                             reason="missing_ref")
                logging.warning(
                    "global: fold from region %s is a delta against "
                    "segment %d whose reference is no longer held — "
                    "dropped as expired, re-syncing", region, fold_round)
                self._broadcast_round(only_rank=sender)
                return
            n_samples = float(
                msg.get(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 1.0) or 1.0)
            weight = n_samples * staleness_weight(self._staleness_spec,
                                                  float(staleness))
            reason = self.aggregator.add_local_trained_result(
                sender - 1, model, weight)
            if reason is not None:
                # the whole fold failed admission (a fold-level quarantine
                # is a REGION-level fault): bounded re-solicit like the
                # flat path, then the quorum pacers complete without it
                _region_folds.labels(run_id=self._run_label,
                                     outcome="quarantined").inc()
                ledger.event("hier", "fold_quarantined", round_idx=version,
                             region=region, reason=reason)
                n_prev = self._quarantine_resolicits.get(sender, 0)
                if n_prev < self._resolicit_max:
                    self._quarantine_resolicits[sender] = n_prev + 1
                    logging.warning(
                        "global: QUARANTINED fold from region %s (%s) — "
                        "re-soliciting the segment (attempt %d/%d)",
                        region, reason, n_prev + 1, self._resolicit_max)
                    self._broadcast_round(only_rank=sender)
                else:
                    self._maybe_complete_early()
                return
            self._seen_folds[key] = True
            for t in triples:
                self._counted_triples[t] = True
            self._trim_windows()
            _region_folds.labels(run_id=self._run_label,
                                 outcome="folded").inc()
            ledger.event("hier", "fold_receive", round_idx=version,
                         region=region, fold_round=fold_round,
                         n_silos=n_silos, expected=expected,
                         staleness=staleness,
                         weight=round(weight, 6))
            self._persist_round_state()
            if self.aggregator.check_whether_all_receive():
                self._complete_round()
                return
            self._maybe_complete_early()

    def _decode_fold(self, msg: Message, fold_round: int) -> Any:
        """Raw | codec-delta fold payload → model tree, or ``_MISSING_REF``
        when the delta's trained-against segment reference is gone (e.g.
        it predates a crash-resume).  Caller holds ``_round_lock``."""
        model = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        if model is not None:
            return model
        wire_update = msg.get(MyMessage.MSG_ARG_KEY_WIRE_UPDATE)
        if wire_update is not None:
            from ...utils.compression import decode_delta

            ref = self._ref_for(fold_round)
            if ref is None:
                return _MISSING_REF
            return decode_delta(wire_update, ref)
        return None

    def _trim_windows(self) -> None:
        while len(self._seen_folds) > _FOLD_DEDUP_WINDOW:
            self._seen_folds.popitem(last=False)
        while len(self._counted_triples) > _FOLD_DEDUP_WINDOW:
            self._counted_triples.popitem(last=False)

    def run(self) -> None:
        try:
            super().run()
        finally:
            with self._round_lock:
                stranded = not self._finishing
                self._finishing = True
            if stranded:
                # abnormal receive-loop exit (a handler raised past the
                # dispatch guard): release the regions before tearing
                # down, or every regional node blocks on G2R_FINISH
                self.send_finish_to_all()
            self.finish()

    def send_finish_to_all(self) -> None:
        for rank in range(1, self.client_num + 1):
            msg = Message(HierMessage.MSG_TYPE_G2R_FINISH,
                          self.get_sender_id(), rank)
            self.send_message(msg)
