"""Geo-distributed aggregation hierarchy: edge → region → global.

Regional aggregators fold their silos locally (regional staleness +
robust op), ship ONE pre-reduced codec-compressed delta per round
segment over the WAN, and the global server composes the robustness
stack again over REGIONS — per-tier fault domains with the PR-4
heartbeat/deadline machinery, (region, silo, round) dedup, and
round-boundary crash-resume at every tier.  See docs/ROBUSTNESS.md
"Hierarchical aggregation".
"""

from .global_server_manager import GlobalServerManager
from .message_define import HierMessage
from .regional_manager import RegionalAggregatorManager, RegionUplink
from .runner import HierarchicalFederationRunner, RegionNode, hier_layout

__all__ = [
    "GlobalServerManager",
    "HierMessage",
    "HierarchicalFederationRunner",
    "RegionNode",
    "RegionalAggregatorManager",
    "RegionUplink",
    "hier_layout",
]
