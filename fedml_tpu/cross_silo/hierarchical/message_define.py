"""Wire schema for the geo-distributed aggregation hierarchy.

Two planes, two schemas:

* the LAN plane (regional aggregator ↔ its silos) speaks the UNMODIFIED
  flat cross-silo protocol (``..message_define.MyMessage``) — silos run
  stock ``ClientMasterManager``s and cannot tell a regional aggregator
  from a flat server;
* the WAN plane (global server ↔ regional aggregators) speaks this
  schema: a region announces itself (``R2G_REGION_STATUS``), receives
  round segments (``G2R_INIT_CONFIG`` / ``G2R_SYNC_MODEL``), and ships
  exactly ONE pre-reduced, codec-compressed fold per segment
  (``R2G_REGION_FOLD``).  Heartbeats reuse the flat plane's
  ``C2S_HEARTBEAT`` wire value — the global server IS a
  ``FedMLServerManager`` underneath and its failure detector is
  type-compatible by construction.

The fold payload carries the ``(silo rank, silo round)`` pairs that went
into it: together with the sending region they form the
``(region, silo, round)`` dedup triples the global server audits, so a
retransmitted or re-folded regional delta can never double-count any
silo's upload.
"""


class HierMessage:
    # region handshake (WAN analog of C2S_CLIENT_STATUS)
    MSG_TYPE_R2G_REGION_STATUS = "R2G_REGION_STATUS"
    #: ONE pre-reduced regional delta per round segment
    MSG_TYPE_R2G_REGION_FOLD = "R2G_REGION_FOLD"

    # global → region round segments (WAN analog of S2C init/sync/finish)
    MSG_TYPE_G2R_INIT_CONFIG = "G2R_INIT_CONFIG"
    MSG_TYPE_G2R_SYNC_MODEL = "G2R_SYNC_MODEL"
    MSG_TYPE_G2R_FINISH = "G2R_FINISH"

    # payload keys (model/round/codec keys are shared with MyMessage so
    # the wire codecs and tracing ride both planes unchanged)
    MSG_ARG_KEY_REGION = "region"
    #: silo uploads folded into this regional delta
    MSG_ARG_KEY_N_SILOS = "n_silos"
    #: silos the region solicited for the segment (fold may be partial)
    MSG_ARG_KEY_EXPECTED_SILOS = "expected_silos"
    #: ``[[silo rank, silo round], ...]`` — the fold's dedup triples
    MSG_ARG_KEY_SILO_ROUNDS = "silo_rounds"
