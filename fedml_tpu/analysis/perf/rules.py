"""PERF rule family — IR-level performance lints over registered jit
entrypoints (docs/STATIC_ANALYSIS.md "Perf tier" has the catalog).

Each rule reads a ``TracedEntrypoint`` (jaxpr + lowered StableHLO text +
lazy compile stats) and yields findings whose messages are LINE-FREE and
shape-keyed, so the shared fingerprint/baseline machinery stays stable
under unrelated source churn.  jax is never imported at module scope.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Tuple

from ..findings import SEV_ERROR, SEV_WARNING, Finding
from .tracing import TracedEntrypoint, aval_nbytes, nelems

#: PERF002/PERF004 ignore tensors smaller than this (elementwise noise)
DEFAULT_MIN_ELEMS = 4096
#: PERF001 ignores donated leaves smaller than this (bytes)
DEFAULT_MIN_DONATED_BYTES = 1024
#: PERF001's missing-donation clause needs this much matchable in→out
#: traffic before it speaks up (tiny programs gain nothing from donation)
DEFAULT_MIN_MATCH_BYTES = 64 * 1024
#: f32 accumulation sanctioned by design — the aggregation kernels widen
#: deliberately (a bf16 sum over many clients loses low-order bits)
SANCTIONED_WIDEN_PATHS = (
    "fedml_tpu/ml/aggregator/agg_operator.py",
    "fedml_tpu/ml/aggregator/robust.py",
)
#: source-text markers that make a transpose EXPLICIT (autodiff inserts
#: transposes too, attributed to the forward op's line — those lines
#: won't contain any of these tokens, so they are filtered out).  The
#: ``.T`` attribute is matched case-sensitively on a word boundary.
_TRANSPOSE_TOKENS = ("transpose", "swapaxes", "moveaxis", "einsum",
                     "rearrange", "permute")
_TRANSPOSE_ATTR_RE = None  # compiled lazily (avoids re at import in hot path)


def _is_explicit_transpose(text: str) -> bool:
    global _TRANSPOSE_ATTR_RE
    low = text.lower()
    if any(tok in low for tok in _TRANSPOSE_TOKENS):
        return True
    if _TRANSPOSE_ATTR_RE is None:
        import re

        _TRANSPOSE_ATTR_RE = re.compile(r"\.T\b")
    return bool(_TRANSPOSE_ATTR_RE.search(text))

_PERF_REGISTRY: List[type] = []


class PerfRule:
    """Base: one rule instance sees every traced entrypoint once."""

    id: str = ""
    severity: str = SEV_WARNING
    title: str = ""

    def check_entrypoint(self, traced: TracedEntrypoint
                         ) -> Iterable[Finding]:
        return ()


def register_perf(cls):
    _PERF_REGISTRY.append(cls)
    return cls


def make_perf_rules() -> List[PerfRule]:
    return [cls() for cls in _PERF_REGISTRY]


def perf_rule_ids() -> List[str]:
    return [cls.id for cls in _PERF_REGISTRY]


def _entry_site(traced: TracedEntrypoint) -> Tuple[str, int]:
    """(path, line) findings anchor to when they concern the whole
    entrypoint rather than one source equation — the registration site,
    so a ``# fedml: noqa[...]`` next to ``register_jit_entrypoint`` works."""
    return traced.spec.path or "fedml_tpu/analysis/perf/entrypoints.py", \
        int(traced.spec.meta.get("src_line", 1) or 1)


def _fmt_shape(dtype: str, shape: Tuple[int, ...]) -> str:
    return f"{dtype}[{','.join(str(s) for s in shape)}]"


@register_perf
class DonationAuditRule(PerfRule):
    """PERF001 — donated args the lowered program does not actually alias
    (dtype/layout mismatch silently drops donation → both buffers live at
    peak), and large in→out pytrees updated in place with no donation
    declared at all."""

    id = "PERF001"
    severity = SEV_WARNING
    title = "buffer-donation audit on jit entrypoints"

    def check_entrypoint(self, traced):
        spec = traced.spec
        path, line = _entry_site(traced)
        min_bytes = int(spec.meta.get(
            "donation_min_bytes", DEFAULT_MIN_DONATED_BYTES))
        leaves = traced.arg_leaves()
        if spec.donate_argnums:
            # the lower-time warning is the authoritative dropped set (it
            # fires exactly on mismatches, never on eliminated unused
            # args); leaf paths are attached as ATTRIBUTION, matched by
            # aval among the declared-donated leaves
            dropped = traced.dropped_donations()
            for dtype, shape in dropped:
                if aval_nbytes(dtype, shape) < min_bytes:
                    continue
                candidates = [leaf.path or f"arg{leaf.argnum}"
                              for leaf in leaves
                              if leaf.donated and leaf.dtype == dtype
                              and leaf.shape == shape]
                where = (" (candidate leaves: "
                         + ", ".join(sorted(set(candidates))[:4]) + ")"
                         if candidates else "")
                yield Finding(
                    self.id, self.severity, path, line, 0,
                    f"entrypoint '{spec.name}': a donated "
                    f"{_fmt_shape(dtype, shape)} buffer is not aliased "
                    f"by the lowered program — the donation is silently "
                    f"dropped and both buffers stay live at peak (fix "
                    f"the dtype/shape mismatch between the donated "
                    f"input and its output){where}")
            # vacuous-audit guard: the registration DECLARES donation but
            # the traced program aliases NOTHING and no mismatch warning
            # fired — the jit itself almost certainly lost its
            # donate_argnums (a declared+usable donation leaves
            # tf.aliasing_output; a declared+unusable one warns; an
            # unused one is eliminated silently).  Deliberately built on
            # EXACT module facts, not the per-leaf alignment: an
            # eliminated donated arg sharing a tensor type with a kept
            # one makes the alignment ambiguous, so the guard only fires
            # when every donated leaf's type multiset survives intact
            # (nothing of those types was eliminated).
            donated_leaves = [leaf for leaf in leaves if leaf.donated]
            if not dropped and donated_leaves \
                    and traced.alias_attr_count() == 0:
                from .tracing import aval_mlir_type

                hlo_counts = traced.hlo_arg_type_counts()
                leaf_counts: Dict[str, int] = {}
                for leaf in leaves:
                    t = aval_mlir_type(leaf.dtype, leaf.shape)
                    leaf_counts[t] = leaf_counts.get(t, 0) + 1
                donated_types = {aval_mlir_type(leaf.dtype, leaf.shape)
                                 for leaf in donated_leaves}
                unambiguous = all(
                    hlo_counts.get(t, 0) == leaf_counts.get(t, 0)
                    for t in donated_types)
                total = sum(leaf.nbytes for leaf in donated_leaves)
                if unambiguous and total >= min_bytes:
                    yield Finding(
                        self.id, self.severity, path, line, 0,
                        f"entrypoint '{spec.name}': registration "
                        f"declares donate_argnums="
                        f"{tuple(spec.donate_argnums)} but the traced "
                        f"program aliases NONE of the {total} donated "
                        f"input bytes and no mismatch warning fired — "
                        f"the jit call itself likely lost its "
                        f"donate_argnums (re-donate at the jax.jit, or "
                        f"fix the registration)")
            return
        if spec.donate_argnums == ():      # explicit, documented opt-out
            return
        # no donation declared: pair outputs with same-(shape,dtype) input
        # leaves; enough matchable bytes → the jit should donate
        out_shapes = self._output_avals(traced)
        matchable = 0
        budget: Dict[Tuple[str, Tuple[int, ...]], int] = {}
        for dtype, shape in out_shapes:
            budget[(dtype, shape)] = budget.get((dtype, shape), 0) + 1
        for leaf in leaves:
            if not leaf.present:
                continue
            key = (leaf.dtype, leaf.shape)
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                matchable += leaf.nbytes
        min_match = int(spec.meta.get(
            "donation_min_match_bytes", DEFAULT_MIN_MATCH_BYTES))
        if matchable >= min_match:
            yield Finding(
                self.id, self.severity, path, line, 0,
                f"entrypoint '{spec.name}': {matchable} bytes of inputs "
                f"have shape/dtype-identical outputs but the jit declares "
                f"no donate_argnums — an in-place update pytree that "
                f"could alias is copied instead (donate it, or register "
                f"with donate_argnums=() to record that inputs are "
                f"reused after the call)")

    @staticmethod
    def _output_avals(traced) -> List[Tuple[str, Tuple[int, ...]]]:
        return [(str(v.aval.dtype), tuple(v.aval.shape))
                for v in traced.jaxpr.jaxpr.outvars if hasattr(v, "aval")]


@register_perf
class DtypeWideningRule(PerfRule):
    """PERF002 — bf16/f16 tensors upcast to f32 inside the traced program
    (convert_element_type), outside the sanctioned f32 accumulation in the
    aggregation kernels and outside the entrypoint's ``widen_allow``
    paths.  Each distinct source site reports once per entrypoint."""

    id = "PERF002"
    severity = SEV_WARNING
    title = "silent low-precision→f32 widening in hot bodies"

    def check_entrypoint(self, traced):
        spec = traced.spec
        min_elems = int(spec.meta.get("widen_min_elems",
                                      DEFAULT_MIN_ELEMS))
        allow = tuple(SANCTIONED_WIDEN_PATHS) + tuple(
            spec.meta.get("widen_allow", ()))
        seen = set()
        for site in traced.eqn_sites():
            if site.primitive != "convert_element_type" or not site.invars:
                continue
            in_dtype, in_shape = site.invars[0]
            out_dtype = site.outvars[0][0] if site.outvars else ""
            if in_dtype not in ("bfloat16", "float16") \
                    or out_dtype != "float32":
                continue
            if nelems(in_shape) < min_elems:
                continue
            # frames outside the repo (flax norm internals etc.) implement
            # their own mixed-precision policy — not ours to lint
            if not site.file:
                continue
            if any(site.file.startswith(p) for p in allow):
                continue
            key = (site.file, site.line)
            if key in seen:
                continue
            seen.add(key)
            yield Finding(
                self.id, self.severity, site.file, site.line, 0,
                f"entrypoint '{spec.name}': "
                f"{_fmt_shape(in_dtype, in_shape)} widens to float32 in "
                f"the traced hot path ({nelems(in_shape)} elems — doubles "
                f"the bandwidth of every downstream op); keep the chain "
                f"in {in_dtype} or add the site to the entrypoint's "
                f"widen_allow with a justification")


@register_perf
class PaddingWasteRule(PerfRule):
    """PERF003 — static audit of a size-bucketing policy: per-bucket
    padded-vs-real-executed ratio from the dataset histogram the
    entrypoint registers (``meta["bucket_stats"]`` dict or
    ``meta["bucket_stats_fn"]`` callable).  Flags buckets whose padded
    compute exceeds the expected real samples by more than
    ``padding_bucket_threshold`` (default 25%) and the whole round when
    the total exceeds ``padding_round_threshold`` (default 20%)."""

    id = "PERF003"
    severity = SEV_WARNING
    title = "padded-vs-real waste in the size-bucket policy"

    def check_entrypoint(self, traced):
        spec = traced.spec
        stats = spec.meta.get("bucket_stats")
        fn = spec.meta.get("bucket_stats_fn")
        if stats is None and callable(fn):
            stats = fn()
        if not stats:
            return
        path, line = _entry_site(traced)
        thr_b = float(spec.meta.get("padding_bucket_threshold", 0.25))
        thr_r = float(spec.meta.get("padding_round_threshold", 0.20))
        tot_padded = tot_real = 0.0
        for i, b in enumerate(stats.get("buckets", ())):
            padded = float(b["padded"])
            real = max(float(b["real"]), 1e-9)
            tot_padded += padded
            tot_real += real
            if padded / real - 1.0 > thr_b and padded >= 64:
                yield Finding(
                    self.id, self.severity, path, line, 0,
                    f"entrypoint '{spec.name}': bucket {i} pads "
                    f"{int(padded)} sample slots for {real:.0f} expected "
                    f"real samples ({padded / real - 1.0:+.0%} waste) — "
                    f"cap the bucket's batch capacity nearer its size "
                    f"distribution (rotating window for over-cap clients)")
        if tot_real > 0 and tot_padded / tot_real - 1.0 > thr_r:
            yield Finding(
                self.id, self.severity, path, line, 0,
                f"entrypoint '{spec.name}': round-level padding waste "
                f"{tot_padded / tot_real - 1.0:+.0%} "
                f"({int(tot_padded)} padded vs {tot_real:.0f} real "
                f"samples per round) exceeds {thr_r:.0%} — tighten the "
                f"bucketing policy")


@register_perf
class ScanLayoutRule(PerfRule):
    """PERF004 — explicit layout-changing transposes/copies inside
    scan/while bodies (the ROADMAP-named rule).  Autodiff also inserts
    transposes, attributed to the forward op's source line; a site only
    fires when its source text actually spells a transpose-like call, so
    backward-pass artifacts are filtered out."""

    id = "PERF004"
    severity = SEV_WARNING
    title = "layout-changing transpose/copy inside a scan body"

    def check_entrypoint(self, traced):
        spec = traced.spec
        min_elems = int(spec.meta.get("layout_min_elems",
                                      DEFAULT_MIN_ELEMS))
        allow = tuple(spec.meta.get("layout_allow", ()))
        seen = set()
        for site in traced.eqn_sites():
            if site.primitive not in ("transpose", "copy"):
                continue
            if not site.in_scan or not site.invars:
                continue
            in_dtype, in_shape = site.invars[0]
            if nelems(in_shape) < min_elems:
                continue
            if not site.file or any(site.file.startswith(p)
                                    for p in allow):
                continue
            if not _is_explicit_transpose(
                    traced.source_line(site.file, site.line)):
                continue
            key = (site.file, site.line)
            if key in seen:
                continue
            seen.add(key)
            yield Finding(
                self.id, self.severity, site.file, site.line, 0,
                f"entrypoint '{spec.name}': explicit "
                f"{site.primitive} of {_fmt_shape(in_dtype, in_shape)} "
                f"inside a scan body — a layout-changing copy every "
                f"iteration; hoist it out of the loop or restructure the "
                f"layout so the loop body reads it contiguously")


@register_perf
class HostCallbackRule(PerfRule):
    """PERF005 — host callbacks / forced syncs reachable from a jitted
    entrypoint (escalates the JAX003 AST heuristic to an IR fact: the
    callback primitive is IN the traced program, so every execution round
    trips to the host)."""

    id = "PERF005"
    severity = SEV_ERROR
    title = "host callback reachable from a jit entrypoint"

    _PRIMS = ("debug_callback", "pure_callback", "io_callback",
              "host_callback", "outside_call", "infeed", "outfeed")

    def check_entrypoint(self, traced):
        spec = traced.spec
        seen = set()
        for site in traced.eqn_sites():
            if not any(site.primitive.startswith(p) for p in self._PRIMS):
                continue
            file = site.file or _entry_site(traced)[0]
            line = site.line or _entry_site(traced)[1]
            key = (file, line, site.primitive)
            if key in seen:
                continue
            seen.add(key)
            where = "a scan body" if site.in_scan else "the traced program"
            yield Finding(
                self.id, self.severity, file, line, 0,
                f"entrypoint '{spec.name}': {site.primitive} reachable "
                f"from {where} — every execution synchronizes with the "
                f"host; move the I/O outside the jit or behind a "
                f"device-buffered metrics path")
