"""Registered jit entrypoints — the repo's hot programs, as the perf-lint
tier traces them.

Every factory builds a SMALL synthetic instance of the real program (tiny
model, synthetic partition, scan chunk 4) purely to obtain the jitted
callable + abstract arg specs; structure — donation layout, dtype chains,
scan bodies, callback reachability — is identical to the production
config, only the shapes shrink, so the IR facts the rules check transfer.
Everything runs on CPU under ``JAX_PLATFORMS=cpu`` in well under the
60-second smoke budget.

Widen allowlists record the DELIBERATE mixed-precision policy:

* ``fedml_tpu/models/`` — model forwards upcast around normalization
  (flax BN/GN computes statistics in f32 by design) and emit f32 logits
  for the loss; both are the bf16 training recipe, not accidents.
* the aggregation kernels (``agg_operator.py`` / ``robust.py``) are
  sanctioned globally by the rule itself — f32 accumulation over the
  client axis is the documented contract.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from .registry import register_jit_entrypoint

#: repo root (…/fedml_tpu/analysis/perf/entrypoints.py → three up)
_ROOT = Path(__file__).resolve().parents[3]


# ---------------------------------------------------------------------------
# Parrot — the north-star simulation hot path
# ---------------------------------------------------------------------------
_MINI_PARROT = None


def _mini_parrot_api():
    """A structurally-faithful miniature of the bench ParrotAPI: bf16
    compute, size-bucketed with the bench's rotating-window cap (so the
    capped gather path is in the trace), synthetic data.  Memoized — the
    three parrot entries share one build per process (the fused entry's
    FUSED_CHUNK_ROUNDS override doesn't affect the other two, whose jits
    were built in __init__)."""
    global _MINI_PARROT
    if _MINI_PARROT is not None:
        return _MINI_PARROT
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="synthetic", model="lr", backend="parrot",
        client_num_in_total=8, client_num_per_round=4, comm_round=2,
        epochs=1, batch_size=8, learning_rate=0.1, data_scale=0.3,
        partition_alpha=0.3, frequency_of_the_test=1,
        enable_tracking=False, compute_dtype="bfloat16",
        hetero_buckets=2, hetero_bucket_cap=0.8, parrot_aot_cache=False))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    _MINI_PARROT = FedMLRunner(args, device, dataset, bundle).runner
    return _MINI_PARROT


def _sds(tree):
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


def _parrot_fused_scan():
    import jax
    import jax.numpy as jnp

    api = _mini_parrot_api()
    api.FUSED_CHUNK_ROUNDS = 4      # scan length is structural, not ruleful
    fn = api._build_multi_round_step()
    args = (_sds(api.device_data), _sds(api.global_vars),
            _sds(api.server_state),
            jax.ShapeDtypeStruct((2,), jnp.uint32),
            jax.ShapeDtypeStruct((), jnp.int32))
    return fn, args


def _parrot_bucketed_round():
    import jax
    import jax.numpy as jnp

    api = _mini_parrot_api()
    args = (_sds(api.device_data), _sds(api.global_vars),
            _sds(api.server_state), jax.ShapeDtypeStruct((2,), jnp.uint32))
    return api.bucketed_round_step, args


def _parrot_eval_step():
    api = _mini_parrot_api()
    batches = api._make_test_batches()
    return api.eval_step, (_sds(api.global_vars), _sds(batches))


def _northstar_bucket_stats():
    """PERF003 input: the committed north-star client-size histogram run
    through the live ``bucket_plan`` policy — the audit sees exactly the
    padding the bench config pays."""
    p = _ROOT / "benchmarks" / "northstar_client_sizes.json"
    if not p.is_file():
        return None
    d = json.loads(p.read_text(encoding="utf-8"))
    from ...simulation.parrot.parrot_api import bucket_plan

    plan = bucket_plan(np.asarray(d["sizes"]),
                       int(d["client_num_per_round"]),
                       int(d["batch_size"]),
                       int(d["hetero_buckets"]),
                       float(d.get("hetero_bucket_cap", 0.0)))
    return {"buckets": [{"padded": b["padded"], "real": b["real"]}
                        for b in plan]}


register_jit_entrypoint(
    "parrot/fused_round_scan", _parrot_fused_scan,
    donate_argnums=(1, 2),
    meta={"widen_allow": ("fedml_tpu/models/",),
          "bucket_stats_fn": _northstar_bucket_stats})

register_jit_entrypoint(
    "parrot/bucketed_round_step", _parrot_bucketed_round,
    donate_argnums=(1, 2),
    meta={"widen_allow": ("fedml_tpu/models/",)})

register_jit_entrypoint(
    # eval reuses global_vars/test batches every call — donating would be
    # a bug; donate_argnums=() records the audit decision
    "parrot/eval_step", _parrot_eval_step,
    donate_argnums=(),
    meta={"widen_allow": ("fedml_tpu/models/",)})


# ---------------------------------------------------------------------------
# Robust aggregation operators (shared by SP / cross-silo / Parrot)
# ---------------------------------------------------------------------------
def _stacked_tree(n=8, dtype="bfloat16"):
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    return {
        "conv": jax.ShapeDtypeStruct((n, 3, 3, 16, 32), dt),
        "dense": {"kernel": jax.ShapeDtypeStruct((n, 256, 64), dt),
                  "bias": jax.ShapeDtypeStruct((n, 64), dt)},
    }


def _robust_agg():
    import jax
    import jax.numpy as jnp

    from ...ml.aggregator.robust import parse_robust_agg, robust_agg_stacked

    spec = parse_robust_agg("trimmed_mean:0.2")

    def agg(stacked, weights):
        return robust_agg_stacked(spec, stacked, weights)

    return jax.jit(agg), (
        _stacked_tree(), jax.ShapeDtypeStruct((8,), jnp.float32))


def _agg_stacked():
    import jax
    import jax.numpy as jnp

    from ...ml.aggregator.agg_operator import agg_stacked

    return jax.jit(agg_stacked), (
        _stacked_tree(), jax.ShapeDtypeStruct((8,), jnp.float32))


register_jit_entrypoint("agg/robust_trimmed_mean", _robust_agg)
register_jit_entrypoint("agg/stacked_weighted_mean", _agg_stacked)


# ---------------------------------------------------------------------------
# Wire compression (cross-silo upload/broadcast codecs)
# ---------------------------------------------------------------------------
_WIRE_D = 1 << 18      # flat update length the codec entries trace at


def _ref_tree():
    """bf16 model-shaped reference the decode folds into (sums to _WIRE_D
    elements so the flat delta matches)."""
    import jax
    import jax.numpy as jnp

    return {"w": jax.ShapeDtypeStruct((512, 448), jnp.bfloat16),
            "b": jax.ShapeDtypeStruct((32768,), jnp.bfloat16)}


def _wire_quantize():
    import jax
    import jax.numpy as jnp

    from ...ops.wire_compression import quantize_int8_blocked

    return (jax.jit(lambda flat: quantize_int8_blocked(flat)),
            (jax.ShapeDtypeStruct((_WIRE_D,), jnp.float32),))


def _wire_decode_int8_delta():
    import jax
    import jax.numpy as jnp

    from ...ops.wire_compression import BLOCK
    from ...utils.compression import decode_delta

    n_scales = -(-_WIRE_D // BLOCK)

    def decode(ref, q, scales):
        return decode_delta(
            {"codec": "int8", "q": q, "scales": scales, "size": _WIRE_D},
            ref)

    return jax.jit(decode), (
        _ref_tree(),
        jax.ShapeDtypeStruct((_WIRE_D,), jnp.int8),
        jax.ShapeDtypeStruct((n_scales,), jnp.float32))


def _wire_decode_topk8_delta():
    import jax
    import jax.numpy as jnp

    from ...ops.wire_compression import BLOCK
    from ...utils.compression import decode_delta

    k = _WIRE_D // 10
    n_scales = -(-k // BLOCK)

    def decode(ref, q, scales, idx):
        return decode_delta(
            {"codec": "topk8", "values_q": q, "scales": scales,
             "idx": idx, "size": _WIRE_D}, ref)

    return jax.jit(decode), (
        _ref_tree(),
        jax.ShapeDtypeStruct((k,), jnp.int8),
        jax.ShapeDtypeStruct((n_scales,), jnp.float32),
        jax.ShapeDtypeStruct((k,), jnp.int32))


register_jit_entrypoint("wire/quantize_int8", _wire_quantize)
# the decode output shape-matches the reference tree, but the reference
# is the SHARED per-version broadcast every upload of that version
# reconstructs against — donating it would corrupt the next decode, so
# donate_argnums=() records the audited decision.  widen_allow: the
# per-leaf f32 add in _add_delta_tree is REQUIRED for bit-exact
# reconstruction (the EF residual and per-version reference contract
# model an exact apply); the fixed waste was the whole-model flat f32
# materialization, which is gone — the per-leaf chain fuses.
_WIRE_WIDEN_OK = ("fedml_tpu/utils/compression.py",)
register_jit_entrypoint("wire/decode_int8_delta", _wire_decode_int8_delta,
                        donate_argnums=(),
                        meta={"widen_allow": _WIRE_WIDEN_OK})
register_jit_entrypoint("wire/decode_topk8_delta",
                        _wire_decode_topk8_delta, donate_argnums=(),
                        meta={"widen_allow": _WIRE_WIDEN_OK})


# ---------------------------------------------------------------------------
# LLM SFT train step (functional LoRA epoch scan)
# ---------------------------------------------------------------------------
def _llm_train_epoch():
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from ...train.llm.trainer import LLMTrainConfig, LLMTrainer

    args = fedml_tpu.Config(model="transformer", dataset="shakespeare",
                            compute_dtype="float32")
    bundle = fedml_tpu.model.create(args, 90)
    cfg = LLMTrainConfig(seq_len=16, batch_size=2, lora_rank=2)
    trainer = LLMTrainer(bundle, cfg)
    trainable = trainer._trainables()
    opt_state = trainer.tx.init(trainable)
    base_params = trainer.variables["params"]
    model_state = {k: v for k, v in trainer.variables.items()
                   if k != "params"}
    batches = {
        "x": jax.ShapeDtypeStruct((2, 2, 16), jnp.int32),
        "y": jax.ShapeDtypeStruct((2, 2, 16), jnp.int32),
        "mask": jax.ShapeDtypeStruct((2, 2, 16), jnp.float32),
    }
    return trainer._train_epoch, (
        _sds(trainable), _sds(opt_state), _sds(base_params),
        _sds(model_state), batches,
        jax.ShapeDtypeStruct((2,), jnp.uint32))


register_jit_entrypoint("llm/train_epoch", _llm_train_epoch,
                        donate_argnums=(0, 1))
