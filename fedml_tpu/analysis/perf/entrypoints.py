"""Registered jit entrypoints — the repo's hot programs, as the perf-lint
tier traces them.

Every factory builds a SMALL synthetic instance of the real program (tiny
model, synthetic partition, scan chunk 4) purely to obtain the jitted
callable + abstract arg specs; structure — donation layout, dtype chains,
scan bodies, callback reachability — is identical to the production
config, only the shapes shrink, so the IR facts the rules check transfer.
Everything runs on CPU under ``JAX_PLATFORMS=cpu`` in well under the
60-second smoke budget.

Widen allowlists record the DELIBERATE mixed-precision policy:

* ``fedml_tpu/models/`` — model forwards upcast around normalization
  (flax BN/GN computes statistics in f32 by design) and emit f32 logits
  for the loss; both are the bf16 training recipe, not accidents.
* the aggregation kernels (``agg_operator.py`` / ``robust.py``) are
  sanctioned globally by the rule itself — f32 accumulation over the
  client axis is the documented contract.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from ..mesh.variants import OK_OUT, MeshVariant
from .registry import register_jit_entrypoint

#: repo root (…/fedml_tpu/analysis/perf/entrypoints.py → three up)
_ROOT = Path(__file__).resolve().parents[3]


# ---------------------------------------------------------------------------
# Parrot — the north-star simulation hot path
# ---------------------------------------------------------------------------
_MINI_PARROT = None


def _mini_parrot_api():
    """A structurally-faithful miniature of the bench ParrotAPI: bf16
    compute, size-bucketed with the bench's rotating-window cap (so the
    capped gather path is in the trace), synthetic data.  Memoized — the
    three parrot entries share one build per process (the fused entry's
    FUSED_CHUNK_ROUNDS override doesn't affect the other two, whose jits
    were built in __init__)."""
    global _MINI_PARROT
    if _MINI_PARROT is not None:
        return _MINI_PARROT
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="synthetic", model="lr", backend="parrot",
        client_num_in_total=8, client_num_per_round=4, comm_round=2,
        epochs=1, batch_size=8, learning_rate=0.1, data_scale=0.3,
        partition_alpha=0.3, frequency_of_the_test=1,
        enable_tracking=False, compute_dtype="bfloat16",
        hetero_buckets=2, hetero_bucket_cap=0.8, parrot_aot_cache=False))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    _MINI_PARROT = FedMLRunner(args, device, dataset, bundle).runner
    return _MINI_PARROT


def _sds(tree):
    import jax

    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


def _parrot_fused_scan():
    import jax
    import jax.numpy as jnp

    api = _mini_parrot_api()
    api.FUSED_CHUNK_ROUNDS = 4      # scan length is structural, not ruleful
    fn = api._build_multi_round_step()
    args = (_sds(api.device_data), _sds(api.global_vars),
            _sds(api.server_state),
            jax.ShapeDtypeStruct((2,), jnp.uint32),
            jax.ShapeDtypeStruct((), jnp.int32))
    return fn, args


def _parrot_bucketed_round():
    import jax
    import jax.numpy as jnp

    api = _mini_parrot_api()
    args = (_sds(api.device_data), _sds(api.global_vars),
            _sds(api.server_state), jax.ShapeDtypeStruct((2,), jnp.uint32))
    return api.bucketed_round_step, args


def _parrot_eval_step():
    api = _mini_parrot_api()
    batches = api._make_test_batches()
    return api.eval_step, (_sds(api.global_vars), _sds(batches))


_MINI_PARROT_MESH = {}


def _mini_parrot_api_mesh(clients_axis):
    """Mesh-backend twin of ``_mini_parrot_api`` (same mini config, same
    buckets) built over ``{"clients": clients_axis}``.  The mesh API bakes
    its ``with_sharding_constraint`` layout into the jit at construction —
    the mesh tier must lower a mesh-built instance, not reshard the
    single-device one.  ``clients_axis=2`` divides the per-bucket cohort
    (client-axis grid); ``clients_axis=8`` exceeds it, so the constraint
    falls through to the intra-batch axis (batch-axis grid) — the two
    variants cover both placements of ``_grid_sharding``."""
    if clients_axis in _MINI_PARROT_MESH:
        return _MINI_PARROT_MESH[clients_axis]
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="synthetic", model="lr", backend="mesh",
        mesh_shape={"clients": clients_axis},
        client_num_in_total=8, client_num_per_round=4, comm_round=2,
        epochs=1, batch_size=8, learning_rate=0.1, data_scale=0.3,
        partition_alpha=0.3, frequency_of_the_test=1,
        enable_tracking=False, compute_dtype="bfloat16",
        hetero_buckets=2, hetero_bucket_cap=0.8, parrot_aot_cache=False))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    _MINI_PARROT_MESH[clients_axis] = FedMLRunner(
        args, device, dataset, bundle).runner
    return _MINI_PARROT_MESH[clients_axis]


def _parrot_bucketed_mesh(clients_axis):
    def build():
        import jax
        import jax.numpy as jnp

        api = _mini_parrot_api_mesh(clients_axis)
        args = (_sds(api.device_data), _sds(api.global_vars),
                _sds(api.server_state),
                jax.ShapeDtypeStruct((2,), jnp.uint32))
        return api.bucketed_round_step, args

    return build


def _parrot_fused_mesh(clients_axis):
    def build():
        import jax
        import jax.numpy as jnp

        api = _mini_parrot_api_mesh(clients_axis)
        api.FUSED_CHUNK_ROUNDS = 4
        fn = api._build_multi_round_step()
        args = (_sds(api.device_data), _sds(api.global_vars),
                _sds(api.server_state),
                jax.ShapeDtypeStruct((2,), jnp.uint32),
                jax.ShapeDtypeStruct((), jnp.int32))
        return fn, args

    return build


#: SHARD003 contract for every parrot mesh variant: the dataset/index
#: grid (argnum 0) rides replicated BY DESIGN — per-round gather indices
#: address arbitrary clients from every device, so sharding the data
#: arrays would trade one resident copy for a per-round resharding
#: collective.  global_vars/server_state are the replicated global model
#: by definition (and are tiny in the mini).
_PARROT_MESH_NOTE = ("data grid replicated by design: per-round gathers "
                     "address arbitrary clients from every device")


def _parrot_mesh_variants(fn_factory_for):
    return (
        MeshVariant(
            "client_axis", {"clients": 2},
            fn_factory=fn_factory_for(2),
            replicate_ok=(0,), note=_PARROT_MESH_NOTE),
        MeshVariant(
            "batch_axis", {"clients": 8},
            fn_factory=fn_factory_for(8),
            replicate_ok=(0,), note=_PARROT_MESH_NOTE),
    )


def _northstar_bucket_stats():
    """PERF003 input: the committed north-star client-size histogram run
    through the live ``bucket_plan`` policy — the audit sees exactly the
    padding the bench config pays."""
    p = _ROOT / "benchmarks" / "northstar_client_sizes.json"
    if not p.is_file():
        return None
    d = json.loads(p.read_text(encoding="utf-8"))
    from ...simulation.parrot.parrot_api import bucket_plan

    plan = bucket_plan(np.asarray(d["sizes"]),
                       int(d["client_num_per_round"]),
                       int(d["batch_size"]),
                       int(d["hetero_buckets"]),
                       float(d.get("hetero_bucket_cap", 0.0)))
    return {"buckets": [{"padded": b["padded"], "real": b["real"]}
                        for b in plan]}


register_jit_entrypoint(
    "parrot/fused_round_scan", _parrot_fused_scan,
    donate_argnums=(1, 2),
    meta={"widen_allow": ("fedml_tpu/models/",),
          "bucket_stats_fn": _northstar_bucket_stats},
    mesh_variants=_parrot_mesh_variants(_parrot_fused_mesh))

register_jit_entrypoint(
    "parrot/bucketed_round_step", _parrot_bucketed_round,
    donate_argnums=(1, 2),
    meta={"widen_allow": ("fedml_tpu/models/",)},
    mesh_variants=_parrot_mesh_variants(_parrot_bucketed_mesh))

register_jit_entrypoint(
    # eval reuses global_vars/test batches every call — donating would be
    # a bug; donate_argnums=() records the audit decision
    "parrot/eval_step", _parrot_eval_step,
    donate_argnums=(),
    meta={"widen_allow": ("fedml_tpu/models/",)})


# ---------------------------------------------------------------------------
# Hyper-scale streaming round (simulation/parrot/hyperscale.py)
# ---------------------------------------------------------------------------
_MINI_STREAM = {}


def _mini_streaming_api(clients_axis=0):
    """Miniature StreamingParrotAPI: hierarchical sampling over 2 strata,
    SCAFFOLD so the sharded per-client state table's gather/scatter is in
    the trace, bf16 compute.  ``clients_axis=0`` → single-device;
    ``2`` divides the per-stratum quota (client-axis grids), ``8``
    exceeds it so the constraint falls through to the intra-batch axis —
    the same two `grid_sharding` placements the parrot variants pin."""
    if clients_axis in _MINI_STREAM:
        return _MINI_STREAM[clients_axis]
    import fedml_tpu
    from ...simulation.parrot.hyperscale import StreamingParrotAPI

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="synthetic", model="lr", backend="hyperscale",
        client_num_in_total=8, client_num_per_round=4, comm_round=2,
        epochs=1, batch_size=8, learning_rate=0.1, data_scale=0.3,
        partition_alpha=0.3, frequency_of_the_test=1,
        enable_tracking=False, compute_dtype="bfloat16",
        hetero_buckets=2, hetero_bucket_cap=0.8,
        cohort_sampling="hierarchical",
        federated_optimizer="SCAFFOLD",
        mesh_shape=({"clients": clients_axis} if clients_axis else None)))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    _MINI_STREAM[clients_axis] = StreamingParrotAPI(
        args, device, dataset, bundle, use_mesh=bool(clients_axis))
    return _MINI_STREAM[clients_axis]


def _streaming_round_args(api):
    import jax
    import jax.numpy as jnp

    staged = api._stage(0)
    return (_sds(staged.grids), _sds(staged.weights), _sds(staged.ids),
            _sds(api.global_vars), _sds(api.server_state),
            jax.ShapeDtypeStruct((2,), jnp.uint32))


def _streaming_round():
    api = _mini_streaming_api(0)
    return api.round_step, _streaming_round_args(api)


def _streaming_round_mesh(clients_axis):
    def build():
        api = _mini_streaming_api(clients_axis)
        return api.round_step, _streaming_round_args(api)

    return build


def _hyperscale_bucket_stats():
    """PERF003 input for the streaming path: the committed 100k
    heavy-tailed histogram under its policy of record."""
    p = _ROOT / "benchmarks" / "hyperscale_client_sizes.json"
    if not p.is_file():
        return None
    d = json.loads(p.read_text(encoding="utf-8"))
    from ...data.population import decode_sizes
    from ...simulation.parrot.parrot_api import bucket_plan

    # committed file is histogram-encoded ([size, count] pairs); stats
    # are multiset functions so the decode is exact
    plan = bucket_plan(decode_sizes(d),
                       int(d["client_num_per_round"]),
                       int(d["batch_size"]),
                       int(d["hetero_buckets"]),
                       float(d.get("hetero_bucket_cap", 0.0)))
    return {"buckets": [{"padded": b["padded"], "real": b["real"]}
                        for b in plan]}


#: SHARD003 contract for the streaming variants: cohort grids, weights
#: and ids (argnums 0-2) arrive PRE-SHARDED from `_stage`'s device_put —
#: the lint lowers them replicated-at-boundary, and the in-jit
#: constraint reshards to the production layout; global model /
#: SCAFFOLD c_global are replicated by definition.
_STREAM_MESH_NOTE = ("cohort grids arrive pre-sharded from the streaming "
                     "device_put; global model replicated by definition")

register_jit_entrypoint(
    "parrot/streaming_round_step", _streaming_round,
    donate_argnums=(3, 4),
    meta={"widen_allow": ("fedml_tpu/models/",),
          "bucket_stats_fn": _hyperscale_bucket_stats},
    mesh_variants=(
        MeshVariant(
            "client_axis", {"clients": 2},
            fn_factory=_streaming_round_mesh(2),
            replicate_ok=(0, 1, 2), note=_STREAM_MESH_NOTE),
        MeshVariant(
            "batch_axis", {"clients": 8},
            fn_factory=_streaming_round_mesh(8),
            replicate_ok=(0, 1, 2), note=_STREAM_MESH_NOTE),
    ))


# ---------------------------------------------------------------------------
# Robust aggregation operators (shared by SP / cross-silo / Parrot)
# ---------------------------------------------------------------------------
def _stacked_tree(n=8, dtype="bfloat16"):
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    return {
        "conv": jax.ShapeDtypeStruct((n, 3, 3, 16, 32), dt),
        "dense": {"kernel": jax.ShapeDtypeStruct((n, 256, 64), dt),
                  "bias": jax.ShapeDtypeStruct((n, 64), dt)},
    }


def _robust_agg():
    import jax
    import jax.numpy as jnp

    from ...ml.aggregator.robust import parse_robust_agg, robust_agg_stacked

    spec = parse_robust_agg("trimmed_mean:0.2")

    def agg(stacked, weights):
        return robust_agg_stacked(spec, stacked, weights)

    return jax.jit(agg), (
        _stacked_tree(), jax.ShapeDtypeStruct((8,), jnp.float32))


def _agg_stacked():
    import jax
    import jax.numpy as jnp

    from ...ml.aggregator.agg_operator import agg_stacked

    return jax.jit(agg_stacked), (
        _stacked_tree(), jax.ShapeDtypeStruct((8,), jnp.float32))


def _agg_mesh_variant():
    """Stacked updates shard over the client axis; the reduced global
    comes back replicated — exactly the cross-silo server's layout when
    the stacked buffer lives sharded across a pod slice."""
    return MeshVariant(
        "clients8", {"clients": 8},
        in_specs=(("clients",), ("clients",)),
        min_bytes=1 << 12)


# widen_allow for the epilogue kernels: the fused-epilogue contract
# REQUIRES f32 accumulation on bf16 leaves (agg_stacked's numerics of
# record — weights normalize and reduce in f32, cast back once at the
# end), and on TPU the widen lives in-register inside one pallas pass,
# not in HBM; the jnp fallback keeps the same math for bitwise parity
_EPILOGUE_WIDEN_OK = ("fedml_tpu/ops/epilogue.py",)

def _region_fold():
    """The hierarchical regional aggregator's fold: silo updates stacked
    in the region's FedBuff buffer reduce under the regional robust op
    (default trimmed_mean:0.2) with staleness-decayed weights — the
    device kernel behind one WAN-shipped delta per round segment."""
    import jax
    import jax.numpy as jnp

    from ...ml.aggregator.robust import parse_robust_agg, robust_agg_stacked

    spec = parse_robust_agg("trimmed_mean:0.2")

    def fold(stacked, weights):
        return robust_agg_stacked(spec, stacked, weights)

    return jax.jit(fold), (
        _stacked_tree(), jax.ShapeDtypeStruct((8,), jnp.float32))


register_jit_entrypoint("agg/robust_trimmed_mean", _robust_agg,
                        mesh_variants=(_agg_mesh_variant(),))
register_jit_entrypoint("agg/stacked_weighted_mean", _agg_stacked,
                        meta={"widen_allow": _EPILOGUE_WIDEN_OK},
                        mesh_variants=(_agg_mesh_variant(),))
register_jit_entrypoint("hier/region_fold", _region_fold,
                        mesh_variants=(_agg_mesh_variant(),))


# ---------------------------------------------------------------------------
# Fused round epilogue (ops/epilogue.py — reduce + mix + server-opt +
# cast-back in one pass per leaf)
# ---------------------------------------------------------------------------
def _epilogue_opt_state(global_tree, with_t=True):
    import jax
    import jax.numpy as jnp

    f32 = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, jnp.float32), global_tree)
    state = {"m": f32,
             "v": jax.tree_util.tree_map(lambda s: s, f32)}
    if with_t:
        state["t"] = jax.ShapeDtypeStruct((), jnp.int32)
    return state


def _fused_epilogue():
    """The host-funnel fold: stacked client updates + weights reduce,
    mix at ``server_lr`` and step the server optimizer (adam — the
    FedOpt default) into the DONATED global, opt state threaded through
    donated too — ``FedMLAggregator.aggregate_buffer``'s device program
    when the fused channel is on."""
    import jax
    import jax.numpy as jnp

    from ...ops.epilogue import EpilogueSpec, fused_epilogue

    spec = EpilogueSpec(opt="adam", lr=1e-3)
    stacked = _stacked_tree()
    global_tree = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype), stacked)

    def step(g, stacked_updates, weights, opt_state):
        return fused_epilogue(g, stacked_updates, weights, 1.0, spec,
                              opt_state)

    return jax.jit(step, donate_argnums=(0, 3)), (
        global_tree, stacked, jax.ShapeDtypeStruct((8,), jnp.float32),
        _epilogue_opt_state(global_tree))


def _parrot_fused_epilogue():
    """The in-jit form: Parrot's ``build_aggregate`` FEDOPT channel —
    f32 params (the round-step carry), per-cohort weights, NOTHING
    donated (the enclosing round jit owns the carry's aliasing)."""
    import jax
    import jax.numpy as jnp

    from ...ops.epilogue import EpilogueSpec, fused_epilogue

    spec = EpilogueSpec(opt="adam", lr=1e-3)
    stacked = _stacked_tree(dtype="float32")
    global_tree = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype), stacked)

    def step(g, stacked_updates, weights, opt_state):
        return fused_epilogue(g, stacked_updates, weights, 1.0, spec,
                              opt_state)

    return jax.jit(step), (
        global_tree, stacked, jax.ShapeDtypeStruct((8,), jnp.float32),
        _epilogue_opt_state(global_tree))


_EPILOGUE_MESH_NOTE = ("the global + server-opt state mix and "
                       "re-broadcast every round — replicated state by "
                       "definition; only the stacked client axis shards")

register_jit_entrypoint(
    "agg/fused_epilogue", _fused_epilogue,
    donate_argnums=(0, 3),
    meta={"widen_allow": _EPILOGUE_WIDEN_OK},
    mesh_variants=(MeshVariant(
        "clients8", {"clients": 8},
        in_specs=(None, ("clients",), ("clients",), None),
        replicate_ok=(0, 3), note=_EPILOGUE_MESH_NOTE,
        min_bytes=1 << 12),))

register_jit_entrypoint(
    "parrot/fused_epilogue", _parrot_fused_epilogue,
    donate_argnums=(),
    meta={"widen_allow": _EPILOGUE_WIDEN_OK},
    mesh_variants=(MeshVariant(
        "clients8", {"clients": 8},
        in_specs=(None, ("clients",), ("clients",), None),
        replicate_ok=(0, 3), note=_EPILOGUE_MESH_NOTE,
        min_bytes=1 << 12),))


# ---------------------------------------------------------------------------
# Buffered-async fold (PR-6 aggregate_buffer device hot path)
# ---------------------------------------------------------------------------
def _async_fold_buffer():
    """The buffered-async server's device-side fold: staleness-decayed
    weights reduce the stacked update buffer and the result mixes into
    the (donated) global at ``server_lr`` — ``agg_operator.fold_buffer``,
    the jittable core of ``FedMLAggregator.aggregate_buffer``."""
    import jax
    import jax.numpy as jnp

    from ...ml.aggregator.agg_operator import fold_buffer

    stacked = _stacked_tree()
    global_tree = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype), stacked)
    return jax.jit(fold_buffer, donate_argnums=(0,)), (
        global_tree, stacked, jax.ShapeDtypeStruct((8,), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.float32))


register_jit_entrypoint(
    # the global tree (argnum 0) is donated: aggregate_buffer writes the
    # mixed result straight back as the next global, so the fold updates
    # in place instead of holding old+new globals at peak
    "async/aggregate_buffer", _async_fold_buffer,
    donate_argnums=(0,),
    meta={"widen_allow": _EPILOGUE_WIDEN_OK},
    mesh_variants=(MeshVariant(
        "clients8", {"clients": 8},
        # buffer shards over clients; global/weights/lr replicated (the
        # global must be resident everywhere to mix and to donate into)
        in_specs=(None, ("clients",), ("clients",), None),
        replicate_ok=(0,),
        note=("the global tree mixes and re-broadcasts every fold — it "
              "is replicated state by definition"),
        min_bytes=1 << 12),))


# ---------------------------------------------------------------------------
# Wire compression (cross-silo upload/broadcast codecs)
# ---------------------------------------------------------------------------
_WIRE_D = 1 << 18      # flat update length the codec entries trace at


def _ref_tree():
    """bf16 model-shaped reference the decode folds into (sums to _WIRE_D
    elements so the flat delta matches)."""
    import jax
    import jax.numpy as jnp

    return {"w": jax.ShapeDtypeStruct((512, 448), jnp.bfloat16),
            "b": jax.ShapeDtypeStruct((32768,), jnp.bfloat16)}


def _wire_quantize():
    import jax
    import jax.numpy as jnp

    from ...ops.wire_compression import quantize_int8_blocked

    return (jax.jit(lambda flat: quantize_int8_blocked(flat)),
            (jax.ShapeDtypeStruct((_WIRE_D,), jnp.float32),))


def _wire_decode_int8_delta():
    import jax
    import jax.numpy as jnp

    from ...ops.wire_compression import BLOCK
    from ...utils.compression import decode_delta

    n_scales = -(-_WIRE_D // BLOCK)

    def decode(ref, q, scales):
        return decode_delta(
            {"codec": "int8", "q": q, "scales": scales, "size": _WIRE_D},
            ref)

    return jax.jit(decode), (
        _ref_tree(),
        jax.ShapeDtypeStruct((_WIRE_D,), jnp.int8),
        jax.ShapeDtypeStruct((n_scales,), jnp.float32))


def _wire_decode_topk8_delta():
    import jax
    import jax.numpy as jnp

    from ...ops.wire_compression import BLOCK
    from ...utils.compression import decode_delta

    k = _WIRE_D // 10
    n_scales = -(-k // BLOCK)

    def decode(ref, q, scales, idx):
        return decode_delta(
            {"codec": "topk8", "values_q": q, "scales": scales,
             "idx": idx, "size": _WIRE_D}, ref)

    return jax.jit(decode), (
        _ref_tree(),
        jax.ShapeDtypeStruct((k,), jnp.int8),
        jax.ShapeDtypeStruct((n_scales,), jnp.float32),
        jax.ShapeDtypeStruct((k,), jnp.int32))


register_jit_entrypoint("wire/quantize_int8", _wire_quantize)
# the decode output shape-matches the reference tree, but the reference
# is the SHARED per-version broadcast every upload of that version
# reconstructs against — donating it would corrupt the next decode, so
# donate_argnums=() records the audited decision.  widen_allow: the
# per-leaf f32 add in _add_delta_tree is REQUIRED for bit-exact
# reconstruction (the EF residual and per-version reference contract
# model an exact apply); the fixed waste was the whole-model flat f32
# materialization, which is gone — the per-leaf chain fuses.
_WIRE_WIDEN_OK = ("fedml_tpu/utils/compression.py",)
register_jit_entrypoint(
    "wire/decode_int8_delta", _wire_decode_int8_delta,
    donate_argnums=(),
    meta={"widen_allow": _WIRE_WIDEN_OK},
    # the mesh variant PINS the codec at zero collectives: decode is
    # replicated host-adjacent work (the reference tree is the shared
    # per-version broadcast, the payload is one silo's upload) — if a
    # sharding change ever makes the partitioner insert a collective
    # here, the SHARD004 budget of 0 catches it
    mesh_variants=(MeshVariant(
        "replicated8", {"data": 8},
        replicate_ok=(0, 1, 2),
        note=("codec runs replicated: reference tree is the shared "
              "per-version broadcast, payload is one silo's upload")),))
register_jit_entrypoint("wire/decode_topk8_delta",
                        _wire_decode_topk8_delta, donate_argnums=(),
                        meta={"widen_allow": _WIRE_WIDEN_OK})


# ---------------------------------------------------------------------------
# LLM SFT train step (functional LoRA epoch scan)
# ---------------------------------------------------------------------------
def _llm_train_epoch():
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from ...train.llm.trainer import LLMTrainConfig, LLMTrainer

    args = fedml_tpu.Config(model="transformer", dataset="shakespeare",
                            compute_dtype="float32")
    bundle = fedml_tpu.model.create(args, 90)
    cfg = LLMTrainConfig(seq_len=16, batch_size=2, lora_rank=2)
    trainer = LLMTrainer(bundle, cfg)
    trainable = trainer._trainables()
    opt_state = trainer.tx.init(trainable)
    base_params = trainer.variables["params"]
    model_state = {k: v for k, v in trainer.variables.items()
                   if k != "params"}
    batches = {
        "x": jax.ShapeDtypeStruct((2, 2, 16), jnp.int32),
        "y": jax.ShapeDtypeStruct((2, 2, 16), jnp.int32),
        "mask": jax.ShapeDtypeStruct((2, 2, 16), jnp.float32),
    }
    return trainer._train_epoch, (
        _sds(trainable), _sds(opt_state), _sds(base_params),
        _sds(model_state), batches,
        jax.ShapeDtypeStruct((2,), jnp.uint32))


_LLM_MESH = None


def _llm_train_epoch_mesh():
    """Mesh twin of ``_llm_train_epoch`` at the production layout
    (trainer.train: batches ``P(None, "data")``, base params per
    strategy, LoRA/opt replicated).  Batch dim 8 so the ``data`` axis
    divides it on both the fsdp and tp_fsdp grids."""
    global _LLM_MESH
    if _LLM_MESH is not None:
        return _LLM_MESH
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from ...train.llm.trainer import LLMTrainConfig, LLMTrainer

    args = fedml_tpu.Config(model="transformer", dataset="shakespeare",
                            compute_dtype="float32")
    bundle = fedml_tpu.model.create(args, 90)
    # strategy="fsdp" so the built epoch carries the pin-frozen-base
    # constraint (trainer._build_epoch_fn) exactly as production does on
    # a sharded mesh; the tp_fsdp variant lowers the SAME program under
    # its finer grid (the trainer itself only models dp/fsdp)
    cfg = LLMTrainConfig(seq_len=16, batch_size=8, lora_rank=2,
                         strategy="fsdp", data_parallel=8)
    trainer = LLMTrainer(bundle, cfg)
    trainable = trainer._trainables()
    opt_state = trainer.tx.init(trainable)
    base_params = trainer.variables["params"]
    model_state = {k: v for k, v in trainer.variables.items()
                   if k != "params"}
    batches = {
        "x": jax.ShapeDtypeStruct((2, 8, 16), jnp.int32),
        "y": jax.ShapeDtypeStruct((2, 8, 16), jnp.int32),
        "mask": jax.ShapeDtypeStruct((2, 8, 16), jnp.float32),
    }
    _LLM_MESH = (trainer._train_epoch, (
        _sds(trainable), _sds(opt_state), _sds(base_params),
        _sds(model_state), batches,
        jax.ShapeDtypeStruct((2,), jnp.uint32)))
    return _LLM_MESH


#: per-arg layout of the llm epoch under SPMD — mirrors trainer.train():
#: (trainable, opt_state, base_params, model_state, batches, rng);
#: LoRA/opt replicated (small by construction), base params per strategy,
#: batch dim over `data`
_LLM_IN_SPECS = lambda strategy: (  # noqa: E731 — spec table, not logic
    None, None, strategy, None, (None, "data"), None)

register_jit_entrypoint(
    "llm/train_epoch", _llm_train_epoch,
    donate_argnums=(0, 1),
    mesh_variants=(
        MeshVariant(
            "fsdp", {"data": 8},
            fn_factory=_llm_train_epoch_mesh,
            in_specs=_LLM_IN_SPECS("fsdp"),
            replicate_ok=(0, 1),
            # argnum 2: the frozen base gathers ONCE at epoch entry (the
            # pin-frozen-base constraint) and stays fsdp-sharded at rest;
            # OK_OUT: the updated adapters/opt state gather back to the
            # replicated contract once per epoch, outside the step loop
            reshard_ok=(2, OK_OUT),
            note=("LoRA adapters + optimizer state replicate (small by "
                  "construction); frozen base gathers once per epoch at "
                  "entry, epoch-final output gathers are per-epoch not "
                  "per-step")),
        MeshVariant(
            "tp_fsdp", {"data": 4, "model": 2},
            fn_factory=_llm_train_epoch_mesh,
            in_specs=_LLM_IN_SPECS("tp_fsdp"),
            replicate_ok=(0, 1),
            reshard_ok=(2, OK_OUT),
            note=("LoRA adapters + optimizer state replicate (small by "
                  "construction); frozen base gathers once per epoch at "
                  "entry, epoch-final output gathers are per-epoch not "
                  "per-step")),
    ))


# ---------------------------------------------------------------------------
# Fed-LLM server round boundary (delta fold + LoRA merge)
# ---------------------------------------------------------------------------
def _fed_llm_delta_round():
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from ...train.fed_llm.delta_round import (
        make_delta_round,
        zeros_like_adapters,
    )
    from ...train.llm.lora import init_lora

    args = fedml_tpu.Config(model="transformer", dataset="shakespeare",
                            compute_dtype="float32")
    bundle = fedml_tpu.model.create(args, 90)
    variables = bundle.init_variables(jax.random.PRNGKey(0), batch_size=2)
    base = variables["params"]
    # only shapes/dtypes feed the trace; init_lora's deterministic
    # PRNGKey(0) fallback is exactly the standalone use it documents
    adapters = init_lora(base, rank=2)
    fn = make_delta_round(16.0)
    return fn, (_sds(adapters), _sds(base),
                _sds(zeros_like_adapters(adapters)),
                jax.ShapeDtypeStruct((), jnp.float32))


#: per-arg layout under SPMD — (adapters, base_params, agg_delta,
#: server_lr); adapters/delta replicated (tiny by construction — the
#: whole point of the plane), base per strategy
_FED_LLM_IN_SPECS = lambda strategy: (  # noqa: E731 — spec table, not logic
    None, strategy, None, None)

# donate (2,): the aggregated delta is freshly produced each round,
# shape-matches the new adapters and is never read again — XLA aliases
# its buffers.  Argnum 0 (the global adapters) is NOT donated: the
# buffered-async server re-reads the pre-fold global for mix_global after
# aggregate() returns; argnum 1 (base) is frozen shared state.
register_jit_entrypoint(
    "fed_llm/delta_round", _fed_llm_delta_round,
    donate_argnums=(2,),
    mesh_variants=(
        MeshVariant(
            "fsdp", {"data": 8},
            in_specs=_FED_LLM_IN_SPECS("fsdp"),
            replicate_ok=(0, 2),
            reshard_ok=(1, OK_OUT),
            note=("adapter tree + delta replicate (tiny by construction "
                  "— they are the wire format); the fsdp-sharded base "
                  "gathers once per ROUND for the serve/eval merge, and "
                  "the merged output resharding is likewise per-round, "
                  "amortized over every local step the silos run")),
        MeshVariant(
            "tp_fsdp", {"data": 4, "model": 2},
            in_specs=_FED_LLM_IN_SPECS("tp_fsdp"),
            replicate_ok=(0, 2),
            reshard_ok=(1, OK_OUT),
            note=("adapter tree + delta replicate (tiny by construction "
                  "— they are the wire format); the fsdp-sharded base "
                  "gathers once per ROUND for the serve/eval merge, and "
                  "the merged output resharding is likewise per-round, "
                  "amortized over every local step the silos run")),
    ))
