"""Jit-entrypoint registry for the perf-lint tier.

The AST plane (rules/) and whole-program plane (wholeprogram/) read source
text; the performance bugs that cap MFU — dropped buffer donation, silent
bf16→f32 widening, padding waste, layout-changing copies — are only
visible in the traced jaxpr and compiled HLO.  This registry is the bridge:
hot jitted programs register a *factory* (so nothing heavy happens at
import time) plus abstract argument specs (``jax.ShapeDtypeStruct`` trees
— tracing needs shapes and dtypes, never real data), and ``fedml lint
--perf`` traces each entry and lints its IR.

Registration is declarative and lazy:

    from fedml_tpu.analysis.perf import register_jit_entrypoint

    register_jit_entrypoint(
        "parrot/bucketed_round_step",
        fn_factory=_build_mini_parrot_round,   # () -> (jitted_fn, args)
        abstract_args=None,                    # or a tuple of SDS trees
        donate_argnums=(1, 2),
        meta={"widen_allow": ("fedml_tpu/models/",)},
    )

``fn_factory`` returns either the jitted callable (when ``abstract_args``
is given) or a ``(fn, args)`` pair (when the specs depend on the built
object, e.g. a model's parameter tree).  Factories run on CPU under
``JAX_PLATFORMS=cpu`` in CI — they must stay small and synthetic.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: marker severity tags an entry can carry; "cold" marks entrypoints that
#: are NOT on the training hot path (their findings default to baseline
#: candidates rather than must-fix)
TAG_HOT = "hot"
TAG_COLD = "cold"


@dataclasses.dataclass
class EntrypointSpec:
    """One registered jit program (lazy — nothing traced until the pass)."""

    name: str
    fn_factory: Callable[[], Any]
    abstract_args: Optional[Any] = None
    #: argnums the jit DECLARES donated (audited by PERF001); None when
    #: the entrypoint donates nothing on purpose
    donate_argnums: Optional[Tuple[int, ...]] = None
    #: repo-relative posix path the findings anchor to when an eqn has no
    #: usable source frame (e.g. the registering module)
    path: str = ""
    #: free-form rule knobs: widen_allow (PERF002 path prefixes),
    #: bucket_stats / bucket_stats_fn (PERF003), min_elems overrides …
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)
    tag: str = TAG_HOT
    #: mesh-tier coverage: ``analysis.mesh.MeshVariant`` declarations
    #: (typed loosely — the registry must import without the mesh tier)
    mesh_variants: Tuple[Any, ...] = ()

    def build(self) -> Tuple[Any, Tuple[Any, ...]]:
        """Resolve the factory → (jitted_fn, abstract_args tuple)."""
        out = self.fn_factory()
        if isinstance(out, tuple) and len(out) == 2 and callable(out[0]):
            fn, args = out
        else:
            fn, args = out, self.abstract_args
        if args is None:
            raise ValueError(
                f"entrypoint {self.name!r}: no abstract args — pass "
                f"abstract_args at registration or return (fn, args) "
                f"from the factory")
        if not isinstance(args, tuple):
            args = (args,)
        return fn, args


class EntrypointRegistry:
    """Ordered name → EntrypointSpec map.  A second registration of the
    same name replaces the first (latest wins) so tests and notebooks can
    re-register without duplicate findings."""

    def __init__(self) -> None:
        self._entries: Dict[str, EntrypointSpec] = {}

    def register(self, spec: EntrypointSpec) -> EntrypointSpec:
        self._entries[spec.name] = spec
        return spec

    def entries(self) -> List[EntrypointSpec]:
        return list(self._entries.values())

    def names(self) -> List[str]:
        return list(self._entries.keys())

    def get(self, name: str) -> Optional[EntrypointSpec]:
        return self._entries.get(name)

    def __len__(self) -> int:
        return len(self._entries)


class EntrypointBuildCache:
    """Memoizes ``spec.build()`` per spec name so ONE ``run_lint`` call
    that runs both the perf and mesh tiers (``--rules`` mixing PERF and
    SHARD ids, or ``--update-baseline``) builds each entrypoint's
    factory once — the build (e.g. the mini-Parrot API) is the expensive
    half; each tier still lowers its own way."""

    def __init__(self) -> None:
        self._built: Dict[str, Tuple[Any, Tuple[Any, ...]]] = {}

    def build(self, spec: "EntrypointSpec") -> Tuple[Any, Tuple[Any, ...]]:
        if spec.name not in self._built:
            self._built[spec.name] = spec.build()
        return self._built[spec.name]


#: process-wide default registry — ``entrypoints.py`` populates it with the
#: repo's real hot programs; tests build their own private registries
_DEFAULT = EntrypointRegistry()


def default_registry() -> EntrypointRegistry:
    return _DEFAULT


def register_jit_entrypoint(
        name: str,
        fn_factory: Callable[[], Any],
        abstract_args: Optional[Any] = None,
        *,
        donate_argnums: Optional[Sequence[int]] = None,
        path: str = "",
        meta: Optional[Dict[str, Any]] = None,
        tag: str = TAG_HOT,
        mesh_variants: Optional[Sequence[Any]] = None,
        registry: Optional[EntrypointRegistry] = None) -> EntrypointSpec:
    """Register a jitted program for the perf-lint pass (see module doc).

    ``mesh_variants`` (``analysis.mesh.MeshVariant`` instances) opt the
    entry into the mesh tier: ``fedml lint --mesh`` lowers it SPMD-
    partitioned per variant and runs the SHARD002-006 rules."""
    meta = dict(meta or {})
    if "src_file" not in meta:
        # anchor whole-entry findings at the registration call site so a
        # `# fedml: noqa[PERF00x]` comment next to it applies
        import inspect

        frame = inspect.currentframe()
        caller = frame.f_back if frame is not None else None
        if caller is not None:
            meta["src_file"] = caller.f_code.co_filename
            meta["src_line"] = caller.f_lineno
    spec = EntrypointSpec(
        name=name, fn_factory=fn_factory, abstract_args=abstract_args,
        donate_argnums=(tuple(donate_argnums)
                        if donate_argnums is not None else None),
        path=path, meta=meta, tag=tag,
        mesh_variants=tuple(mesh_variants or ()))
    return (registry if registry is not None else _DEFAULT).register(spec)


def load_default_entrypoints() -> EntrypointRegistry:
    """Import the repo's registrations (idempotent) and return the default
    registry.  Kept separate from module import so ``fedml lint`` without
    ``--perf`` never pays the jax import."""
    from . import entrypoints  # noqa: F401 — importing registers

    return _DEFAULT
