"""Perf-lint tier: trace registered jit entrypoints and lint their IR.

Third analysis tier next to the AST plane (``analysis/rules``) and the
whole-program plane (``analysis/wholeprogram``): ``fedml lint --perf``
resolves every ``register_jit_entrypoint`` entry (ShapeDtypeStruct specs,
no real data), traces it with ``jax.make_jaxpr``-equivalent staging, and
runs the PERF rule family over the jaxpr / lowered StableHLO / optional
compile stats.  Findings share the noqa fingerprints, the
``.fedml-lint-baseline.json`` ratchet, the text/JSON output and the exit
codes of the other tiers.

jax imports stay inside the pass — ``fedml lint`` without ``--perf``
never pays them.  When the pass runs in a process that has not picked a
JAX platform yet, it pins ``JAX_PLATFORMS=cpu`` first: lint tracing is
abstract and must never grab an accelerator.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from ..findings import SEV_ERROR, Finding
from .registry import (
    EntrypointBuildCache,
    EntrypointRegistry,
    EntrypointSpec,
    default_registry,
    load_default_entrypoints,
    register_jit_entrypoint,
)
from .rules import make_perf_rules, perf_rule_ids

__all__ = [
    "EntrypointRegistry", "EntrypointSpec", "EntrypointBuildCache",
    "register_jit_entrypoint", "default_registry",
    "load_default_entrypoints", "run_perf_pass", "make_perf_rules",
    "perf_rule_ids",
]


def _pin_cpu_platform() -> None:
    """Abstract tracing must not initialize an accelerator backend (or
    hang probing for one).  Respect an explicit JAX_PLATFORMS; otherwise
    pin cpu — importing ``fedml_tpu`` already imports jax, so the check
    is whether a BACKEND is initialized yet (lazy), not the module."""
    if os.environ.get("JAX_PLATFORMS"):
        return
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "jax" in sys.modules:
        try:
            from jax._src import xla_bridge

            if not xla_bridge.backends_are_initialized():
                sys.modules["jax"].config.update("jax_platforms", "cpu")
        except Exception:       # backend already live: use it as-is
            pass


def run_perf_pass(root: Path,
                  registry: Optional[EntrypointRegistry] = None,
                  rule_ids: Optional[Sequence[str]] = None,
                  cache=None) -> Tuple[List[Finding], List[str]]:
    """Trace every registered entrypoint and run the requested PERF rules.

    Returns (findings, notes).  A factory/trace failure becomes a
    PERF000 *error* finding (a broken registration must fail the gate,
    not silently shrink coverage) plus a surfaced note.
    """
    _pin_cpu_platform()
    from .tracing import TracedEntrypoint

    reg = registry if registry is not None else load_default_entrypoints()
    wanted = ({r.strip().upper() for r in rule_ids} if rule_ids else None)
    rules = [r for r in make_perf_rules()
             if wanted is None or r.id.upper() in wanted]
    findings: List[Finding] = []
    notes: List[str] = []
    if not reg.entries():
        notes.append("perf pass: no registered jit entrypoints")
        return findings, notes
    for spec in reg.entries():
        path = _rel_or_default(spec, root)
        try:
            prebuilt = cache.build(spec) if cache is not None else None
            traced = TracedEntrypoint(spec, root, prebuilt=prebuilt)
        except Exception as exc:  # noqa: BLE001 — converted to a finding
            msg = f"{exc.__class__.__name__}: {str(exc).splitlines()[0][:160]}" \
                if str(exc) else exc.__class__.__name__
            findings.append(Finding(
                "PERF000", SEV_ERROR, path,
                int(spec.meta.get("src_line", 1) or 1), 0,
                f"entrypoint '{spec.name}' failed to build/trace — {msg}"))
            notes.append(f"perf pass: entrypoint '{spec.name}' failed to "
                         f"trace ({msg})")
            continue
        spec.path = path  # rules anchor whole-entry findings here
        for rule in rules:
            findings.extend(rule.check_entrypoint(traced))
    return findings, notes


def _rel_or_default(spec: EntrypointSpec, root: Path) -> str:
    """Relativize the registration site to the lint root so noqa comments
    next to ``register_jit_entrypoint`` calls apply."""
    src = spec.path or spec.meta.get("src_file")
    if not src:
        return "fedml_tpu/analysis/perf/entrypoints.py"
    try:
        return Path(src).resolve().relative_to(
            Path(root).resolve()).as_posix()
    except Exception:
        return str(src)
