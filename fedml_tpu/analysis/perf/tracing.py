"""IR capture for one registered entrypoint: jaxpr, lowered module text,
donation table, optional compile stats — everything the PERF rules read.

jax is imported lazily (this module must be importable in environments
that only run the AST tiers).  All tracing happens abstractly via
``jax.stages``: ``fn.trace(*ShapeDtypeStructs)`` → jaxpr;
``.lower()`` → StableHLO text whose ``main`` argument attributes mark
GRANTED donations (``tf.aliasing_output``), while the captured lower-time
warning "Some donated buffers were not usable: ShapedArray(...)" is the
authoritative DROPPED set (it fires exactly on mismatches, never for
eliminated unused args — see ``dropped_donations``);
``.compile()`` (lazy, only when a rule asks) → ``memory_analysis()`` /
``cost_analysis()``.
"""

from __future__ import annotations

import dataclasses
import re
import warnings
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .registry import EntrypointSpec

#: StableHLO main-signature argument attribute marking a GRANTED donation
_ALIAS_ATTR = "tf.aliasing_output"


@dataclasses.dataclass
class ArgLeaf:
    """One flattened input leaf of the traced program."""

    index: int                   # position in the flattened arg list
    argnum: int                  # which top-level argument it came from
    path: str                    # pytree key path, e.g. "params/conv1/kernel"
    shape: Tuple[int, ...]
    dtype: str
    donated: bool = False        # the jit declared it donated
    aliased: bool = False        # the lowered module actually aliases it
    #: False when the lowering eliminated the arg as unused — a donated
    #: eliminated arg is freed, not leaked, so it is NOT a finding
    present: bool = True

    @property
    def nbytes(self) -> int:
        return aval_nbytes(self.dtype, self.shape)


@dataclasses.dataclass
class EqnSite:
    """A jaxpr equation + where it lives (for rules to filter/report)."""

    primitive: str
    params: Dict[str, Any]
    invars: List[Tuple[str, Tuple[int, ...]]]    # (dtype, shape) per invar
    outvars: List[Tuple[str, Tuple[int, ...]]]
    file: str                    # repo-relative posix path ("" if unknown)
    line: int
    in_scan: bool                # inside a scan/while body (the hot loop)
    depth: int


class TracedEntrypoint:
    """Trace + lower one EntrypointSpec and expose its IR views."""

    def __init__(self, spec: EntrypointSpec, root,
                 prebuilt=None) -> None:
        import jax

        self.spec = spec
        self.root = root
        # ``prebuilt`` is an (fn, args) pair from EntrypointBuildCache —
        # one run_lint mixing the perf and mesh tiers builds each
        # factory once and hands the result to both
        fn, args = prebuilt if prebuilt is not None else spec.build()
        if not hasattr(fn, "trace"):
            fn = jax.jit(fn)
        self._fn = fn
        self._args = args
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            traced = fn.trace(*args)
            self._lowered = traced.lower()
        self.jaxpr = traced.jaxpr
        self.lowered_text = self._lowered.as_text()
        #: lower-time warnings, notably the dropped-donation one
        self.warnings = [str(w.message) for w in caught]
        self._compiled = None
        self._sites: Optional[List[EqnSite]] = None
        self._arg_leaves: Optional[List[ArgLeaf]] = None

    # -- compile-backed views (lazy: compiling is the expensive part) -------
    def compiled(self):
        if self._compiled is None:
            self._compiled = self._lowered.compile()
        return self._compiled

    def memory_analysis(self):
        try:
            return self.compiled().memory_analysis()
        except Exception:       # backends without the stats stay graceful
            return None

    def cost_analysis(self):
        try:
            ca = self.compiled().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            return ca
        except Exception:
            return None

    # -- donation table ------------------------------------------------------
    def arg_leaves(self) -> List[ArgLeaf]:
        """Flattened input leaves annotated with declared-donated (from the
        registry spec) and actually-aliased (from the lowered module)."""
        if self._arg_leaves is not None:
            return self._arg_leaves
        import jax

        donated = set(self.spec.donate_argnums or ())
        leaves: List[ArgLeaf] = []
        idx = 0
        for argnum, arg in enumerate(self._args):
            flat = jax.tree_util.tree_flatten_with_path(arg)[0]
            for keypath, leaf in flat:
                path = "/".join(_key_str(k) for k in keypath)
                leaves.append(ArgLeaf(
                    index=idx, argnum=argnum, path=path,
                    shape=tuple(getattr(leaf, "shape", ())),
                    dtype=str(getattr(leaf, "dtype", "?")),
                    donated=argnum in donated))
                idx += 1
        self._align_with_module(leaves)
        self._arg_leaves = leaves
        return leaves

    def _align_with_module(self, leaves: List[ArgLeaf]) -> None:
        """Mark each leaf aliased/present by aligning the ``main``
        signature's args against the flattened spec leaves.

        The lowering ELIMINATES unused args (keep_unused=False default),
        so HLO positions are a subsequence of the flat leaf order; a
        greedy in-order match by tensor type recovers the mapping.  NB
        the mapping is AMBIGUOUS when an eliminated leaf shares a tensor
        type with a later kept one — rules needing certainty must use
        ``alias_attr_count``/``hlo_arg_type_counts`` (exact, parse-only)
        or the lower-time warning set instead of these per-leaf flags."""
        li = 0
        for type_str, aliased in self._hlo_args():
            while li < len(leaves) and \
                    _mlir_type(leaves[li].dtype, leaves[li].shape) \
                    != type_str:
                leaves[li].present = False      # eliminated as unused
                li += 1
            if li >= len(leaves):
                break
            leaves[li].aliased = aliased
            li += 1
        for leaf in leaves[li:]:
            leaf.present = False

    def _hlo_args(self) -> List[Tuple[str, bool]]:
        """(tensor type, has tf.aliasing_output) per ``main`` arg, in
        order — parsed once from the lowered module text."""
        if getattr(self, "_hlo_args_cache", None) is None:
            m = re.search(r"func\.func (?:public )?@main\((.*?)\)\s*->",
                          self.lowered_text, re.S)
            self._hlo_args_cache = [] if not m else [
                (am.group(1), _ALIAS_ATTR in (am.group(2) or ""))
                for am in re.finditer(
                    r"%arg\d+:\s*tensor<([^>]*)>\s*(\{[^}]*\})?",
                    m.group(1))]
        return self._hlo_args_cache

    def alias_attr_count(self) -> int:
        """How many ``main`` args the lowered module actually aliases —
        exact (no leaf alignment involved)."""
        return sum(1 for _, aliased in self._hlo_args() if aliased)

    def hlo_arg_type_counts(self) -> Dict[str, int]:
        """Tensor-type multiset of the kept ``main`` args; comparing it
        against the spec leaves' type multiset tells whether any leaf of
        a given type was eliminated (count mismatch = ambiguity)."""
        counts: Dict[str, int] = {}
        for type_str, _ in self._hlo_args():
            counts[type_str] = counts.get(type_str, 0) + 1
        return counts

    def dropped_donations(self) -> List[Tuple[str, Tuple[int, ...]]]:
        """(dtype, shape) of every donated buffer the lowering REFUSED to
        alias, parsed from jax's authoritative lower-time warning ("Some
        donated buffers were not usable: ShapedArray(...)").  This is the
        primary dropped-donation signal: it fires exactly for mismatches
        — an unused donated arg is eliminated and freed WITHOUT a warning
        — so it is immune to the positional ambiguity of aligning HLO
        args against flat leaves when identical tensor types repeat."""
        out: List[Tuple[str, Tuple[int, ...]]] = []
        for w in self.warnings:
            if "donated buffers were not usable" not in w.lower():
                continue
            for m in re.finditer(r"ShapedArray\((\w+)\[([0-9,\s]*)\]", w):
                shape = tuple(int(s) for s in m.group(2).split(",")
                              if s.strip())
                out.append((m.group(1), shape))
        return out

    # -- jaxpr walk ----------------------------------------------------------
    def eqn_sites(self) -> List[EqnSite]:
        if self._sites is None:
            self._sites = list(self._walk(self.jaxpr.jaxpr, False, 0))
        return self._sites

    def _walk(self, jaxpr, in_scan: bool, depth: int) -> Iterator[EqnSite]:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            file, line = self._source_of(eqn)
            yield EqnSite(
                primitive=prim,
                params=dict(eqn.params),
                invars=[(str(v.aval.dtype), tuple(v.aval.shape))
                        for v in eqn.invars if hasattr(v, "aval")],
                outvars=[(str(v.aval.dtype), tuple(v.aval.shape))
                         for v in eqn.outvars if hasattr(v, "aval")],
                file=file, line=line, in_scan=in_scan, depth=depth)
            sub_scan = in_scan or prim in ("scan", "while")
            for v in eqn.params.values():
                for item in (v if isinstance(v, (list, tuple)) else (v,)):
                    inner = getattr(item, "jaxpr", None)
                    if inner is not None:
                        yield from self._walk(inner, sub_scan, depth + 1)

    def _source_of(self, eqn) -> Tuple[str, int]:
        """Innermost user frame of an eqn, repo-relative ("" when the frame
        falls outside the lint root, e.g. site-packages flax)."""
        try:
            from jax._src import source_info_util

            frame = source_info_util.user_frame(eqn.source_info)
            if frame is None:
                return "", 0
            fname = frame.file_name
            line = int(frame.start_line)
        except Exception:
            return "", 0
        try:
            from pathlib import Path

            rel = Path(fname).resolve().relative_to(
                Path(self.root).resolve())
            return rel.as_posix(), line
        except Exception:
            return "", 0

    def source_line(self, file: str, line: int) -> str:
        """The raw source text at file:line (for explicitness checks)."""
        try:
            from pathlib import Path

            lines = (Path(self.root) / file).read_text(
                encoding="utf-8").splitlines()
            return lines[line - 1] if 0 < line <= len(lines) else ""
        except Exception:
            return ""


#: numpy dtype name → MLIR element type (tensor<...> rendering)
_MLIR_DTYPES = {
    "float32": "f32", "float64": "f64", "float16": "f16",
    "bfloat16": "bf16", "int8": "i8", "int16": "i16", "int32": "i32",
    "int64": "i64", "uint8": "ui8", "uint16": "ui16", "uint32": "ui32",
    "uint64": "ui64", "bool": "i1", "complex64": "complex<f32>",
}


def _mlir_type(dtype: str, shape: Tuple[int, ...]) -> str:
    el = _MLIR_DTYPES.get(dtype, dtype)
    return "x".join([str(int(s)) for s in shape] + [el])


#: public alias — rules compare leaf avals against hlo_arg_type_counts()
aval_mlir_type = _mlir_type


def _key_str(k) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k).strip("[].'\"")


def nelems(shape: Tuple[int, ...]) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def aval_nbytes(dtype: str, shape: Tuple[int, ...]) -> int:
    """Bytes of one (dtype, shape) aval — shared by ArgLeaf and the
    donation rule so the unknown-dtype fallback lives in one place."""
    import numpy as np

    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        itemsize = 4
    return nelems(shape) * itemsize
