"""Per-entrypoint collective budgets (SHARD004's committed ratchet).

``benchmarks/collective_budgets.json`` commits, per
``<entrypoint>@<variant>``, the count and byte volume of the budgeted
collective ops (``utils.hlo_costs.BUDGET_OPS``) in the CPU-partitioned
module.  The mesh pass compares what it just compiled against the file:
over budget → finding; missing entry → finding telling the author to
commit one.  Regenerate after a DELIBERATE change with::

    python -m fedml_tpu.analysis.mesh.budgets

which rewrites the file from the live registry (the diff is the review
artifact — a collective-structure change can never land silently).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional

BUDGET_FILE = "benchmarks/collective_budgets.json"

_DOC = ("per-entrypoint collective budget over the CPU-partitioned "
        "module (8 forced host devices): count + byte volume of "
        "all-reduce/all-gather/reduce-scatter/all-to-all per "
        "<entrypoint>@<mesh variant>.  SHARD004 ratchets against this "
        "file; regenerate deliberately with "
        "`python -m fedml_tpu.analysis.mesh.budgets`.")


def budget_path(root) -> Path:
    return Path(root) / BUDGET_FILE


def load_budgets(root) -> Optional[Dict[str, Any]]:
    """The committed budget entries, or None when the file is missing."""
    p = budget_path(root)
    if not p.is_file():
        return None
    data = json.loads(p.read_text(encoding="utf-8"))
    return data.get("entries", {})


def write_budgets(root, stats_by_key: Dict[str, Dict[str, Any]]) -> Path:
    p = budget_path(root)
    p.parent.mkdir(parents=True, exist_ok=True)
    payload = {"_doc": _DOC,
               "entries": {k: stats_by_key[k]
                           for k in sorted(stats_by_key)}}
    p.write_text(json.dumps(payload, indent=2, sort_keys=False) + "\n",
                 encoding="utf-8")
    return p


def collect_registry_stats(root, registry=None,
                           names=None) -> Dict[str, Dict[str, Any]]:
    """Compile every registered mesh variant and return
    ``{budget_key: collective_stats}`` — the generator behind both the
    committed budget file and the ``fedml perf programs`` collectives
    columns."""
    from ..perf.registry import EntrypointBuildCache, load_default_entrypoints
    from . import _pin_mesh_cpu_platform
    from .lowering import MeshLoweredEntrypoint

    _pin_mesh_cpu_platform(8)
    reg = registry if registry is not None else load_default_entrypoints()
    cache = EntrypointBuildCache()
    out: Dict[str, Dict[str, Any]] = {}
    for spec in reg.entries():
        if names is not None and spec.name not in names:
            continue
        for variant in spec.mesh_variants or ():
            lowered = MeshLoweredEntrypoint(spec, variant, Path(root),
                                            cache=cache)
            out[variant.budget_key(spec.name)] = lowered.collective_stats()
    return out


def main() -> int:
    from ..engine import default_root

    root = default_root()
    stats = collect_registry_stats(root)
    p = write_budgets(root, stats)
    print(f"wrote {p} ({len(stats)} budget entries)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
