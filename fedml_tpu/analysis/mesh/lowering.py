"""SPMD lowering + partitioned-HLO views for one (entrypoint, variant).

The single-device perf tier mostly *lowers*; this tier must **compile**:
the collectives XLA's SPMD partitioner inserts exist only in the
optimized HLO (``jit(...).trace(...).lower().compile().as_text()``), not
in the sharding-annotated StableHLO.  ``MeshLoweredEntrypoint`` builds
the variant's named mesh over the forced-CPU device grid, attaches the
declared in-shardings to the abstract args, compiles, and parses the
partitioned module into the facts the SHARD rules read:

* every collective instruction — op, payload bytes (shared conventions
  with ``utils/hlo_costs.py``), expanded replica groups (explicit and
  iota ``[G,S]<=[N]`` forms, including the transposed variant), the
  computation it lives in, and whether that computation is reachable
  from a ``while`` body (the round loop);
* which ENTRY collectives are rooted at a ``parameter`` or feed ROOT
  through pass-through ops only (boundary resharding, SHARD002);
* the lower-time dropped-donation warnings under the mesh lowering
  (SHARD006's authoritative signal).

jax is imported lazily — the module parses text with stdlib ``re`` and
numpy only, so the rule catalog stays importable without a backend.
"""

from __future__ import annotations

import dataclasses
import re
import warnings
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from ...utils.hlo_costs import (
    BUDGET_OPS,
    _COLLECTIVE_OPS,
    _shape_bytes,
    collective_totals,
)
from ..perf.registry import EntrypointSpec
from .variants import INHERIT, MeshVariant

#: ops a value passes through unchanged for boundary attribution —
#: a collective reachable from a parameter (or reaching ROOT) through
#: ONLY these is a boundary reshard, not a mid-program exchange
_PASS_THROUGH = {
    "copy", "bitcast", "reshape", "transpose", "convert", "tuple",
    "get-tuple-element", "optimization-barrier",
}

_HEADER_RE = re.compile(r"^(ENTRY\s+)?%([\w.\-]+)\s*\(.*\{$")
_INSTR_RE = re.compile(r"^(ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
_OP_CALL_RE = re.compile(r"(?<![\w.%\-])([a-z][a-z0-9\-]*)\(")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
_CALLED_COMP_RE = re.compile(
    r"(?:body|condition|to_apply|calls)=\{?%([\w.\-]+)")
_EXPLICIT_GROUPS_RE = re.compile(r"replica_groups=\{(\{[\d,]+\}(?:,\{[\d,]+\})*)\}")
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_PARAM_NUM_RE = re.compile(r"parameter\((\d+)\)")


@dataclasses.dataclass
class HloInstr:
    name: str
    op: str
    result_type: str             # text before the op call
    operands: List[str]          # %names referenced in the operand list
    attrs: str                   # text after the operand list
    is_root: bool
    computation: str
    line: str


@dataclasses.dataclass
class CollectiveInstr:
    """One collective in the partitioned module, fully attributed."""

    op: str                      # base op ("all-reduce", …)
    nbytes: int                  # result payload (async -start halved)
    groups: List[List[int]]      # expanded replica groups (device ids)
    computation: str
    in_loop: bool                # computation reachable from a while body
    name: str                    # HLO instruction name
    #: ENTRY-only boundary attribution (False elsewhere)
    roots_param: bool = False
    param_indices: Tuple[int, ...] = ()
    feeds_root: bool = False

    @property
    def group_size(self) -> int:
        return max((len(g) for g in self.groups), default=0)

    def hosts_spanned(self, devices_per_host: int) -> int:
        dph = max(int(devices_per_host), 1)
        return max((len({d // dph for d in g}) for g in self.groups),
                   default=1)


def expand_replica_groups(line: str) -> List[List[int]]:
    """Expand a ``replica_groups=`` attribute into device-id lists.

    Handles the explicit ``{{0,1},{2,3}}`` form and the iota
    ``[G,S]<=[N0,N1,...]`` form with optional ``T(perm)`` — semantics of
    ``HloReplicaGroupList``: iota over prod(N) reshaped to the ``<=``
    dims, transposed by perm, reshaped to [G,S]; row i is group i."""
    m = _EXPLICIT_GROUPS_RE.search(line)
    if m:
        return [[int(d) for d in grp.strip("{}").split(",") if d]
                for grp in m.group(1).split("},{")]
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        import numpy as np

        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        arr = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            perm = [int(p) for p in m.group(4).split(",")]
            arr = np.transpose(arr, perm)
        return [list(map(int, row)) for row in arr.reshape(g, s)]
    return []


class HloModule:
    """Minimal text parse of one HLO module: computations → instructions,
    the ENTRY name, and while-body reachability."""

    def __init__(self, hlo_text: str) -> None:
        self.text = hlo_text
        self.computations: Dict[str, Dict[str, HloInstr]] = {}
        self.entry: str = ""
        cur: Optional[str] = None
        for raw in hlo_text.splitlines():
            s = raw.strip()
            h = _HEADER_RE.match(s)
            if h and " -> " in s:
                cur = h.group(2)
                self.computations[cur] = {}
                if h.group(1):
                    self.entry = cur
                continue
            if s == "}":
                cur = None
                continue
            if cur is None:
                continue
            im = _INSTR_RE.match(s)
            if not im:
                continue
            rest = im.group(3)
            oc = _OP_CALL_RE.search(rest)
            if not oc:
                continue
            op = oc.group(1)
            # operand list = balanced parens from the op call
            depth, i = 0, oc.end() - 1
            end = len(rest)
            for i in range(oc.end() - 1, len(rest)):
                if rest[i] == "(":
                    depth += 1
                elif rest[i] == ")":
                    depth -= 1
                    if depth == 0:
                        end = i
                        break
            operand_text = rest[oc.end():end]
            instr = HloInstr(
                name=im.group(2), op=op,
                result_type=rest[:oc.start()].strip(),
                operands=_OPERAND_NAME_RE.findall(operand_text),
                attrs=rest[end + 1:], is_root=bool(im.group(1)),
                computation=cur, line=s)
            self.computations[cur][instr.name] = instr

    def loop_computations(self) -> Set[str]:
        """Computation names reachable from any ``while`` body/condition
        (transitively through body/condition/to_apply/calls edges)."""
        seeds: Set[str] = set()
        for comp in self.computations.values():
            for instr in comp.values():
                if instr.op == "while":
                    seeds.update(_CALLED_COMP_RE.findall(instr.line))
        reach, frontier = set(), list(seeds)
        while frontier:
            name = frontier.pop()
            if name in reach or name not in self.computations:
                continue
            reach.add(name)
            for instr in self.computations[name].values():
                frontier.extend(_CALLED_COMP_RE.findall(instr.line))
        return reach

    def collectives(self) -> List[CollectiveInstr]:
        loops = self.loop_computations()
        out: List[CollectiveInstr] = []
        for cname, comp in self.computations.items():
            for instr in comp.values():
                base = instr.op
                if base.endswith("-done"):
                    continue        # -start carries the payload
                is_start = base.endswith("-start")
                if is_start:
                    base = base[:-len("-start")]
                if base not in _COLLECTIVE_OPS:
                    continue
                nbytes = _shape_bytes(instr.result_type)
                if is_start:
                    # async result tuple aliases the operands — halve,
                    # matching utils/hlo_costs.parse_collectives
                    nbytes //= 2
                out.append(CollectiveInstr(
                    op=base, nbytes=nbytes,
                    groups=expand_replica_groups(instr.line),
                    computation=cname,
                    in_loop=cname in loops, name=instr.name))
        self._attribute_boundaries(out)
        return out

    def _attribute_boundaries(self, colls: List[CollectiveInstr]) -> None:
        """ENTRY-only: mark collectives rooted at parameters / feeding
        ROOT through pass-through ops (boundary resharding, SHARD002)."""
        entry = self.computations.get(self.entry)
        if not entry:
            return
        by_name = {c.name: c for c in colls if c.computation == self.entry}

        def _walk_back(start: HloInstr) -> Tuple[bool, Tuple[int, ...]]:
            seen, stack, params = set(), list(start.operands), []
            while stack:
                n = stack.pop()
                if n in seen:
                    continue
                seen.add(n)
                instr = entry.get(n)
                if instr is None:
                    continue
                if instr.op == "parameter":
                    m = _PARAM_NUM_RE.search(instr.line)
                    params.append(int(m.group(1)) if m else -1)
                elif instr.op in _PASS_THROUGH:
                    stack.extend(instr.operands)
            return bool(params), tuple(sorted(params))

        for c in by_name.values():
            instr = entry.get(c.name)
            if instr is not None:
                c.roots_param, c.param_indices = _walk_back(instr)
        # ROOT side: BFS back from ROOT through pass-through ops; any
        # collective reached produces the final value layout directly
        root = next((i for i in entry.values() if i.is_root), None)
        if root is None:
            return
        seen: Set[str] = set()
        stack = [root.name]
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            instr = entry.get(n)
            if instr is None:
                continue
            if n in by_name:
                by_name[n].feeds_root = True
                continue
            if instr.op in _PASS_THROUGH or instr is root:
                stack.extend(instr.operands)


# ---------------------------------------------------------------------------
# spec resolution + lowering
# ---------------------------------------------------------------------------
_DONATION_WARNING = "donated buffers were not usable"


def _resolve_arg_shardings(mesh, arg, entry):
    """One ``in_specs`` entry → a sharding pytree matching ``arg``'s
    leaves (see ``variants`` module doc for the entry forms)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if callable(entry):
        return entry(mesh, arg)
    if isinstance(entry, str):
        from ...parallel.sharding import make_param_shardings

        return make_param_shardings(arg, mesh, entry)
    sharding = (NamedSharding(mesh, P()) if entry is None
                else NamedSharding(mesh, P(*entry)))
    return jax.tree_util.tree_map(lambda _: sharding, arg)


def _resolve_out_shardings(mesh, out_specs):
    from jax.sharding import NamedSharding, PartitionSpec as P

    if callable(out_specs):
        return out_specs(mesh)
    if out_specs is None:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(*out_specs))


@dataclasses.dataclass
class MeshArgLeaf:
    argnum: int
    path: str
    shape: Tuple[int, ...]
    dtype: str
    nbytes: int
    sharding: Any                # resolved NamedSharding
    donated: bool


class MeshLoweredEntrypoint:
    """Compile one (spec, variant) pair SPMD-partitioned and expose the
    partitioned-HLO views the SHARD rules read."""

    def __init__(self, spec: EntrypointSpec, variant: MeshVariant,
                 root, cache=None) -> None:
        import jax
        import numpy as np

        self.spec = spec
        self.variant = variant
        self.root = root
        devices = jax.devices()
        if len(devices) < variant.n_devices:
            raise RuntimeError(
                f"mesh variant {variant.name!r} needs "
                f"{variant.n_devices} devices, have {len(devices)} — "
                f"set XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{variant.n_devices} before jax initializes")
        if variant.fn_factory is not None:
            fn, args = variant.fn_factory()
            if not (isinstance(args, tuple)):
                args = (args,)
        elif cache is not None:
            fn, args = cache.build(spec)
        else:
            fn, args = spec.build()
        from jax.sharding import Mesh

        sizes = [int(s) for s in variant.mesh_axes.values()]
        self.mesh = Mesh(
            np.asarray(devices[:variant.n_devices]).reshape(sizes),
            tuple(variant.mesh_axes))
        in_specs = variant.in_specs or (None,) * len(args)
        if len(in_specs) != len(args):
            raise ValueError(
                f"variant {variant.name!r}: {len(in_specs)} in_specs "
                f"entries for {len(args)} args")
        donate = (spec.donate_argnums
                  if variant.donate_argnums == INHERIT
                  else variant.donate_argnums)
        self.donate_argnums = tuple(donate or ())
        self.arg_leaves: List[MeshArgLeaf] = []
        shard_args = []
        for argnum, (arg, entry) in enumerate(zip(args, in_specs)):
            sh_tree = _resolve_arg_shardings(self.mesh, arg, entry)
            shard_args.append(jax.tree_util.tree_map(
                lambda s, sh: jax.ShapeDtypeStruct(
                    s.shape, s.dtype, sharding=sh), arg, sh_tree))
            flat, _ = jax.tree_util.tree_flatten_with_path(arg)
            sh_flat = jax.tree_util.tree_leaves(sh_tree)
            for (kp, leaf), sh in zip(flat, sh_flat):
                path = "/".join(_key_str(k) for k in kp)
                self.arg_leaves.append(MeshArgLeaf(
                    argnum=argnum, path=path,
                    shape=tuple(leaf.shape), dtype=str(leaf.dtype),
                    nbytes=int(np.prod(leaf.shape, dtype=np.int64))
                    * np.dtype(leaf.dtype).itemsize,
                    sharding=sh,
                    donated=argnum in self.donate_argnums))
        self.out_shardings = _resolve_out_shardings(
            self.mesh, variant.out_specs)
        base = fn
        if hasattr(fn, "trace") and getattr(fn, "__wrapped__", None):
            # re-jit the underlying callable: the OUTER jit owns
            # donation/out_shardings under SPMD lowering (a nested jit's
            # donation is ignored once inlined)
            base = fn.__wrapped__
        jitted = jax.jit(base, out_shardings=self.out_shardings,
                         donate_argnums=self.donate_argnums)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with self.mesh:
                lowered = jitted.trace(*shard_args).lower()
                compiled = lowered.compile()
        self.lower_warnings = [str(w.message) for w in caught]
        self.hlo_text = compiled.as_text()
        self.module = HloModule(self.hlo_text)
        self._collectives: Optional[List[CollectiveInstr]] = None

    def collectives(self) -> List[CollectiveInstr]:
        if self._collectives is None:
            self._collectives = self.module.collectives()
        return self._collectives

    def collective_stats(self) -> Dict[str, Any]:
        """Budgeted-op totals over the partitioned module — the number
        SHARD004 ratchets and ``fedml perf programs`` surfaces."""
        return collective_totals(self.hlo_text, BUDGET_OPS)

    def dropped_donations(self) -> List[str]:
        """Per-device ShapedArray reprs from the lower-time
        dropped-donation warning (empty → every donation aliased)."""
        out: List[str] = []
        for msg in self.lower_warnings:
            if _DONATION_WARNING in msg:
                out.extend(re.findall(r"ShapedArray\(([^)]*)\)", msg))
        return out


def _key_str(k) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)
