"""Mesh-variant declarations for registered jit entrypoints.

A :class:`MeshVariant` tells the mesh-lint tier how to lower one
registered hot program SPMD-partitioned: which named mesh to build over
the (CPU-forced) device grid, how every positional argument is sharded
going in, what the outputs promise coming out, and which deviations are
*declared design* rather than findings.  Declarations are plain data —
no jax at import time — and resolve to real ``NamedSharding``s only when
the pass runs (``lowering.MeshLoweredEntrypoint``).

Per-argument ``in_specs`` entry forms (one entry per positional arg):

* ``None`` — fully replicated (``P()``) on every leaf
* a tuple of axis names / ``None`` — ``P(*entry)`` on every leaf
  (homogeneous args: arrays or stacks whose leading dims agree)
* a strategy string (``"dp" | "fsdp" | "tp" | "tp_fsdp"``) — resolved
  through ``parallel.sharding.make_param_shardings`` (parameter trees)
* a callable ``(mesh, arg_sds_tree) -> sharding pytree`` — full control

``out_specs`` takes ``None`` (replicated), a spec tuple, or a callable
``(mesh) -> out_shardings`` handed to ``jax.jit`` verbatim.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

#: sentinel: the variant donates whatever the EntrypointSpec declares
INHERIT = "inherit"

#: ``reshard_ok`` marker exempting every input-rooted boundary collective
OK_IN = "in"
#: ``reshard_ok`` marker exempting every ROOT-feeding boundary collective
OK_OUT = "out"


@dataclasses.dataclass
class MeshVariant:
    """One SPMD lowering of an entrypoint (see module doc for the spec
    entry forms).  ``name`` scopes the budget key ``<entry>@<name>`` in
    ``benchmarks/collective_budgets.json``."""

    name: str
    #: axis name → size, in mesh order (e.g. {"clients": 8}); the pass
    #: builds the mesh over the first prod(sizes) CPU devices
    mesh_axes: Dict[str, int]
    in_specs: Optional[Tuple[Any, ...]] = None
    out_specs: Any = None
    #: argnums the mesh lowering donates; INHERIT → the spec's set
    donate_argnums: Any = INHERIT
    #: host-span model for SHARD005: device i lives on host i // this
    #: (8 forced CPU devices with 4/host models a 2-host DCN slice)
    devices_per_host: int = 4
    #: argnums whose FULL replication is the declared design (SHARD003
    #: exemption) — pair with ``note`` saying why
    replicate_ok: Tuple[int, ...] = ()
    #: boundary-resharding exemptions (SHARD002): argnum ints, OK_IN,
    #: or OK_OUT — again a declared-design contract, not a suppression
    reshard_ok: Tuple[Any, ...] = ()
    #: "large array" floor (bytes) for SHARD003/SHARD005 — the mini
    #: registry programs are tiny, so variants tune this to their scale
    min_bytes: int = 1 << 16
    #: justification recorded next to replicate_ok / reshard_ok
    note: str = ""
    #: optional build override: () -> (fn, args) — used when the mesh
    #: lowering needs a DIFFERENT program instance than the single-device
    #: perf trace (e.g. Parrot's mesh backend bakes sharding constraints
    #: into the jit at construction time)
    fn_factory: Optional[Callable[[], Any]] = None

    def __post_init__(self) -> None:
        if not self.mesh_axes:
            raise ValueError(f"mesh variant {self.name!r}: empty mesh_axes")
        for ax, size in self.mesh_axes.items():
            if int(size) < 1:
                raise ValueError(
                    f"mesh variant {self.name!r}: axis {ax!r} size {size} "
                    f"must be a positive int (no -1 here — the lint mesh "
                    f"is explicit so budgets stay comparable)")

    @property
    def n_devices(self) -> int:
        n = 1
        for size in self.mesh_axes.values():
            n *= int(size)
        return n

    def budget_key(self, entry_name: str) -> str:
        return f"{entry_name}@{self.name}"

    def host_of(self, device_id: int) -> int:
        return int(device_id) // max(int(self.devices_per_host), 1)
