"""Mesh-lint tier: SPMD collective-flow analysis over mesh-lowered
entrypoints.

Fourth analysis tier next to the AST plane, the whole-program plane and
the single-device perf tier: ``fedml lint --mesh`` resolves every
``register_jit_entrypoint`` entry that declares mesh variants
(``MeshVariant``: mesh shape + axis names + in/out shardings), lowers it
SPMD-partitioned on CPU under a forced 8-device host platform, and runs
the SHARD002-SHARD006 rules over the compiled (partitioned) HLO — the
only artifact that carries the collectives XLA's partitioner inserted.
Findings share the noqa fingerprints, the ``.fedml-lint-baseline.json``
ratchet, the text/JSON output and the exit codes of the other tiers.

jax imports stay inside the pass; when no backend is initialized yet the
pass pins ``JAX_PLATFORMS=cpu`` and forces the 8-device host platform so
``fedml lint --mesh`` works from a bare shell.  When a backend is
already live with fewer devices than a variant's mesh needs, that
variant becomes a SHARD000 error (coverage must not silently shrink).
"""

from __future__ import annotations

import os
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..findings import SEV_ERROR, Finding
from .rules import make_mesh_rules, mesh_rule_ids
from .variants import INHERIT, OK_IN, OK_OUT, MeshVariant

__all__ = [
    "MeshVariant", "INHERIT", "OK_IN", "OK_OUT", "run_mesh_pass",
    "make_mesh_rules", "mesh_rule_ids", "collective_report",
]

#: the forced host-platform device count every mesh variant lowers under
FORCED_DEVICE_COUNT = 8

_FORCE_FLAG = "--xla_force_host_platform_device_count"


def _pin_mesh_cpu_platform(n_devices: int = FORCED_DEVICE_COUNT) -> None:
    """Like the perf tier's CPU pin, plus the forced host device count —
    both only help when no backend is initialized yet (XLA reads
    XLA_FLAGS at backend init)."""
    backend_live = False
    if "jax" in sys.modules:
        try:
            from jax._src import xla_bridge

            backend_live = xla_bridge.backends_are_initialized()
        except Exception:
            backend_live = True
    if backend_live:
        return
    if not os.environ.get("JAX_PLATFORMS"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "jax" in sys.modules:
            try:
                sys.modules["jax"].config.update("jax_platforms", "cpu")
            except Exception:
                pass
    flags = os.environ.get("XLA_FLAGS", "")
    if _FORCE_FLAG not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} {_FORCE_FLAG}={n_devices}").strip()


def run_mesh_pass(root: Path,
                  registry=None,
                  rule_ids: Optional[Sequence[str]] = None,
                  cache=None) -> Tuple[List[Finding], List[str]]:
    """Lower every registered mesh variant SPMD-partitioned and run the
    requested SHARD rules.  Returns (findings, notes).  A build/lower/
    compile failure becomes a SHARD000 *error* finding — a broken mesh
    registration must fail the gate, not silently shrink coverage."""
    _pin_mesh_cpu_platform()
    from ..perf import _rel_or_default
    from ..perf.registry import EntrypointBuildCache, load_default_entrypoints
    from .lowering import MeshLoweredEntrypoint

    reg = registry if registry is not None else load_default_entrypoints()
    wanted = ({r.strip().upper() for r in rule_ids} if rule_ids else None)
    rules = [r for r in make_mesh_rules()
             if wanted is None or r.id.upper() in wanted]
    if cache is None:
        cache = EntrypointBuildCache()
    findings: List[Finding] = []
    notes: List[str] = []
    n_variants = 0
    for spec in reg.entries():
        variants = spec.mesh_variants or ()
        if not variants:
            continue
        path = _rel_or_default(spec, root)
        spec.path = path
        for variant in variants:
            n_variants += 1
            try:
                lowered = MeshLoweredEntrypoint(spec, variant, root,
                                                cache=cache)
            except Exception as exc:  # noqa: BLE001 — becomes a finding
                msg = (f"{exc.__class__.__name__}: "
                       f"{str(exc).splitlines()[0][:160]}"
                       if str(exc) else exc.__class__.__name__)
                findings.append(Finding(
                    "SHARD000", SEV_ERROR, path,
                    int(spec.meta.get("src_line", 1) or 1), 0,
                    f"mesh variant '{variant.budget_key(spec.name)}' "
                    f"failed to lower/compile — {msg}"))
                notes.append(f"mesh pass: variant "
                             f"'{variant.budget_key(spec.name)}' failed "
                             f"({msg})")
                continue
            for rule in rules:
                findings.extend(rule.check_lowered(lowered))
    if n_variants == 0:
        notes.append("mesh pass: no registered mesh variants")
    return findings, notes


def collective_report(root, registry=None,
                      names: Optional[Sequence[str]] = None
                      ) -> Dict[str, Dict[str, Any]]:
    """Per-entrypoint collective count/bytes per mesh variant —
    ``{entry: {variant: collective_stats}}`` — for the ``fedml perf
    programs`` collectives columns.  Same compile, same parser, same
    totals as the SHARD004 budget ratchet."""
    from .budgets import collect_registry_stats

    stats = collect_registry_stats(root, registry=registry,
                                   names=set(names) if names else None)
    out: Dict[str, Dict[str, Any]] = {}
    for key, s in stats.items():
        entry, _, variant = key.rpartition("@")
        out.setdefault(entry, {})[variant] = s
    return out
