"""SHARD002-SHARD006 — collective-flow lints over mesh-lowered
entrypoints (docs/STATIC_ANALYSIS.md "Mesh tier" has the catalog).

Each rule reads a ``MeshLoweredEntrypoint`` (partitioned HLO + resolved
arg/out shardings + lower-time warnings) and yields findings whose
messages are LINE-FREE and shape-keyed, like the PERF family, so the
shared fingerprint/baseline machinery stays stable under source churn.
Findings anchor at the registration call site — a
``# fedml: noqa[SHARD00x]`` next to ``register_jit_entrypoint``
suppresses, and the declared-design escape hatches (``replicate_ok`` /
``reshard_ok`` on the variant, with a ``note``) are the preferred,
reviewable alternative.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from ..findings import SEV_ERROR, SEV_WARNING, Finding
from .lowering import MeshLoweredEntrypoint
from .variants import OK_IN, OK_OUT

_MESH_REGISTRY: List[type] = []


class MeshRule:
    """Base: one rule instance sees every (entrypoint, variant) once."""

    id: str = ""
    severity: str = SEV_WARNING
    title: str = ""

    def check_lowered(self, lowered: MeshLoweredEntrypoint
                      ) -> Iterable[Finding]:
        return ()


def register_mesh(cls):
    _MESH_REGISTRY.append(cls)
    return cls


def make_mesh_rules() -> List[MeshRule]:
    return [cls() for cls in _MESH_REGISTRY]


def mesh_rule_ids() -> List[str]:
    return [cls.id for cls in _MESH_REGISTRY]


def _site(lowered: MeshLoweredEntrypoint) -> Tuple[str, int]:
    spec = lowered.spec
    return (spec.path or "fedml_tpu/analysis/perf/entrypoints.py",
            int(spec.meta.get("src_line", 1) or 1))


def _key(lowered: MeshLoweredEntrypoint) -> str:
    return lowered.variant.budget_key(lowered.spec.name)


def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return f"{n}B"


def _param_argnums(lowered: MeshLoweredEntrypoint,
                   param_indices) -> set:
    """Map partitioned-HLO parameter indices back to top-level argnums
    via flattened-leaf offsets.  When XLA eliminated unused args the
    counts disagree — return every argnum (conservative: declared
    ``reshard_ok`` argnums still exempt, attribution text degrades)."""
    leaves = lowered.arg_leaves
    entry = lowered.module.computations.get(lowered.module.entry, {})
    n_params = sum(1 for i in entry.values() if i.op == "parameter")
    if n_params != len(leaves):
        return {leaf.argnum for leaf in leaves}
    return {leaves[i].argnum for i in param_indices
            if 0 <= i < len(leaves)}


@register_mesh
class BoundaryReshardRule(MeshRule):
    """SHARD002 — a collective rooted at a program input (or producing
    the ROOT value) through pass-through ops only: the partitioner is
    resharding a boundary value, so the declared in/out sharding
    disagrees with what the program actually consumes/produces.  Either
    fix the declared spec (the caller pays this collective EVERY call)
    or declare the reshard deliberate via ``reshard_ok`` + note."""

    id = "SHARD002"
    severity = SEV_WARNING
    title = "boundary resharding not implied by declared shardings"

    #: only data-MOVEMENT collectives are reshards: an all-reduce or
    #: reduce-scatter at the boundary is the program's own reduction
    #: (in-sharded → out-replicated implies combining, e.g. the weighted
    #: mean over a sharded client axis), never a layout fixup
    RESHARD_OPS = frozenset({"all-gather", "all-to-all",
                             "collective-permute", "collective-broadcast"})

    def check_lowered(self, lowered):
        v = lowered.variant
        path, line = _site(lowered)
        ok_argnums = {a for a in v.reshard_ok if isinstance(a, int)}
        ok_in = OK_IN in v.reshard_ok
        ok_out = OK_OUT in v.reshard_ok
        for c in lowered.collectives():
            if c.op not in self.RESHARD_OPS:
                continue
            if c.roots_param and not ok_in:
                argnums = _param_argnums(lowered, c.param_indices)
                if argnums and argnums <= ok_argnums:
                    continue
                yield Finding(
                    self.id, self.severity, path, line, 0,
                    f"[{_key(lowered)}] {c.op} ({_fmt_bytes(c.nbytes)}) "
                    f"reshards program input arg"
                    f"{sorted(argnums) if argnums else '?'} right at the "
                    f"boundary — the declared in_sharding disagrees with "
                    f"what the program consumes; fix the spec or declare "
                    f"reshard_ok with a note")
            elif c.feeds_root and not c.roots_param and not ok_out:
                yield Finding(
                    self.id, self.severity, path, line, 0,
                    f"[{_key(lowered)}] {c.op} ({_fmt_bytes(c.nbytes)}) "
                    f"produces the program output — the declared "
                    f"out_sharding forces a reshard of the computed "
                    f"value; fix the out spec or declare reshard_ok "
                    f"with a note")


@register_mesh
class IdleAxisReplicationRule(MeshRule):
    """SHARD003 — a large input held fully replicated while a mesh axis
    that could divide it sits idle: every device stores the whole array
    (client-axis state, eval batches...).  Shard it, or declare the
    replication deliberate via ``replicate_ok`` + note."""

    id = "SHARD003"
    severity = SEV_WARNING
    title = "large array replicated while a dividing mesh axis is idle"

    def check_lowered(self, lowered):
        v = lowered.variant
        path, line = _site(lowered)
        axes = {a: int(s) for a, s in v.mesh_axes.items() if int(s) > 1}
        if not axes:
            return
        for leaf in lowered.arg_leaves:
            if leaf.argnum in v.replicate_ok:
                continue
            if leaf.nbytes < v.min_bytes:
                continue
            if not getattr(leaf.sharding, "is_fully_replicated", False):
                continue
            dividing = sorted(
                a for a, s in axes.items()
                if any(d >= s and d % s == 0 for d in leaf.shape))
            if not dividing:
                continue
            where = f"arg{leaf.argnum}" + (f":{leaf.path}" if leaf.path
                                           else "")
            yield Finding(
                self.id, self.severity, path, line, 0,
                f"[{_key(lowered)}] {where} "
                f"{leaf.dtype}[{','.join(map(str, leaf.shape))}] "
                f"({_fmt_bytes(leaf.nbytes)}) is fully replicated while "
                f"mesh axis {'/'.join(dividing)} could divide it — every "
                f"device stores the whole array; shard it or declare "
                f"replicate_ok with a note")


@register_mesh
class CollectiveBudgetRule(MeshRule):
    """SHARD004 — the compiled module's collective count/bytes versus
    the committed ``benchmarks/collective_budgets.json``.  Over budget
    or missing entry → finding; regenerate deliberately with
    ``python -m fedml_tpu.analysis.mesh.budgets`` (the diff is the
    review artifact)."""

    id = "SHARD004"
    severity = SEV_WARNING
    title = "per-entrypoint collective budget ratchet"

    def check_lowered(self, lowered):
        from .budgets import BUDGET_FILE, load_budgets

        path, line = _site(lowered)
        key = _key(lowered)
        actual = lowered.collective_stats()
        entries = load_budgets(lowered.root)
        budget = (entries or {}).get(key)
        if budget is None:
            yield Finding(
                self.id, self.severity, path, line, 0,
                f"[{key}] no committed collective budget (actual: "
                f"{actual['total_ops']} ops, "
                f"{_fmt_bytes(actual['total_bytes'])}) — run "
                f"`python -m fedml_tpu.analysis.mesh.budgets` and commit "
                f"{BUDGET_FILE}")
            return
        over_ops = actual["total_ops"] > int(budget.get("total_ops", 0))
        over_bytes = (actual["total_bytes"]
                      > int(budget.get("total_bytes", 0)))
        if over_ops or over_bytes:
            yield Finding(
                self.id, self.severity, path, line, 0,
                f"[{key}] collectives exceed the committed budget: "
                f"{actual['total_ops']} ops / "
                f"{_fmt_bytes(actual['total_bytes'])} vs budgeted "
                f"{budget.get('total_ops', 0)} ops / "
                f"{_fmt_bytes(int(budget.get('total_bytes', 0)))} — fix "
                f"the sharding regression or regenerate {BUDGET_FILE} "
                f"deliberately")


@register_mesh
class CrossHostLoopGatherRule(MeshRule):
    """SHARD005 — replica groups classified cross-host vs intra-host
    under the variant's ``devices_per_host`` model; a LARGE cross-host
    all-gather inside a round loop (while-body computation) is an error:
    it moves the gathered payload over DCN every iteration, the exact
    traffic the sharded design exists to avoid."""

    id = "SHARD005"
    severity = SEV_ERROR
    title = "large cross-host all-gather inside a round loop"

    def check_lowered(self, lowered):
        v = lowered.variant
        path, line = _site(lowered)
        for c in lowered.collectives():
            if c.op != "all-gather" or not c.in_loop:
                continue
            if c.nbytes < v.min_bytes:
                continue
            hosts = c.hosts_spanned(v.devices_per_host)
            if hosts <= 1:
                continue
            yield Finding(
                self.id, self.severity, path, line, 0,
                f"[{_key(lowered)}] cross-host all-gather "
                f"({_fmt_bytes(c.nbytes)}, {hosts} hosts of "
                f"{v.devices_per_host} devices, group size "
                f"{c.group_size}) inside the round loop — gathered "
                f"state crosses DCN every iteration; keep it sharded "
                f"or move the gather out of the loop")


@register_mesh
class DonationShardingMismatchRule(MeshRule):
    """SHARD006 — a donated input whose output sharding differs: the
    mesh lowering drops the alias (XLA cannot alias buffers with
    different per-device shapes), forcing exactly the copy donation was
    meant to avoid.  The single-device perf trace (PERF001) cannot see
    this — the drop only exists under SPMD lowering."""

    id = "SHARD006"
    severity = SEV_WARNING
    title = "donation lost to sharding mismatch"

    def check_lowered(self, lowered):
        dropped = lowered.dropped_donations()
        if not dropped:
            return
        path, line = _site(lowered)
        out_sh = lowered.out_shardings
        for leaf in lowered.arg_leaves:
            if not leaf.donated:
                continue
            try:
                same = leaf.sharding.is_equivalent_to(
                    out_sh, len(leaf.shape))
            except Exception:
                same = leaf.sharding == out_sh
            if same:
                # dropped for a non-sharding reason (dtype/shape) —
                # PERF001 owns that on the single-device trace
                continue
            shard_shape = tuple(leaf.sharding.shard_shape(leaf.shape))
            sdtype = _short_dtype(leaf.dtype)
            if not any(_matches(d, sdtype, shard_shape) for d in dropped):
                continue
            where = f"arg{leaf.argnum}" + (f":{leaf.path}" if leaf.path
                                           else "")
            yield Finding(
                self.id, self.severity, path, line, 0,
                f"[{_key(lowered)}] donated {where} "
                f"{leaf.dtype}[{','.join(map(str, leaf.shape))}] lost "
                f"its donation under SPMD lowering — in-sharding "
                f"{_spec_str(leaf.sharding)} vs out-sharding "
                f"{_spec_str(out_sh)} have different per-device "
                f"layouts, so XLA keeps the copy; align the declared "
                f"shardings (or stop donating)")


_SHORT_DTYPES = {"float32": "float32", "bfloat16": "bfloat16",
                 "float16": "float16"}


def _short_dtype(dtype: str) -> str:
    return _SHORT_DTYPES.get(dtype, dtype)


def _matches(dropped_repr: str, dtype: str,
             shard_shape: Tuple[int, ...]) -> bool:
    """The warning carries per-DEVICE avals, e.g. ``float32[8,16]``."""
    want = f"{dtype}[{','.join(map(str, shard_shape))}]"
    return want in dropped_repr.replace(" ", "")


def _spec_str(sharding) -> str:
    spec = getattr(sharding, "spec", None)
    return str(spec) if spec is not None else str(sharding)
