"""PROTO002 + FLOW001 — cross-file protocol rules over the package index.

**PROTO002 (orphan wire traffic).**  Aggregated by WIRE VALUE across every
manager class and comm backend — plus pure-sender helper classes and
top-level driver functions, whose traffic counts even though they register
nothing: a ``Message(TYPE, …)`` construction whose type no manager anywhere
registers a handler for is dropped on arrival; a registered handler whose
type no code path ever sends waits forever.  Conservatism: an unresolvable
registration suppresses orphan-SEND verdicts (the dynamic handler could
accept anything) and an unbindable parametric send suppresses
orphan-HANDLER verdicts (the dynamic send could emit anything) — only
provable one-sided traffic is flagged.

**FLOW001 (protocol liveness).**  The manager fleet is modelled as a
message-passing FSM: registered handlers are the transitions, ``Message``
constructions the emissions, and the init states are the emissions
reachable from each manager's entry methods (``run``/``start``/…) through
intra-class ``self.*`` references (plain references count, so timer
callbacks are reachable).  A fixpoint walk activates a handler when any
reachable site emits its wire value, and activating a handler makes ITS
emissions reachable.  Two liveness defects fall out:

* a handler that stays inactive at fixpoint even though send sites for its
  type exist — every send is itself unreachable from the init handshake,
  so the protocol stalls before that state;
* a ``*FINISH*`` wire value whose handler exists but whose every emission
  is unreachable — rounds can run but never terminate.

Known approximations (documented in docs/STATIC_ANALYSIS.md): reachability
is per-class-closure (no cross-class data flow beyond the message graph),
conditions on emissions are ignored (any branch counts as sendable), and
all manager classes share one graph (a value aliased across two protocol
families links them — the same wire-value aggregation PROTO001 uses).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set, Tuple

from ..findings import SEV_ERROR, Finding
from ..rules import ProgramRule, register_program
from .index import INIT_METHODS, ClassInfo, PackageIndex, class_closure


@register_program
class Proto002OrphanWire(ProgramRule):
    id = "PROTO002"
    severity = SEV_ERROR
    title = "wire value sent with no registered handler (or vice versa)"

    def check_program(self, index: PackageIndex) -> Iterable[Finding]:
        t = index.aggregate_traffic()
        sends, handlers = t.sends, t.handlers
        out: List[Finding] = []
        if not t.dynamic_handlers:
            for value, sites in sorted(sends.items()):
                if value in handlers:
                    continue
                for owner, path, method, lineno in sites:
                    where = owner if owner.endswith("()") \
                        else f"{owner}.{method}"
                    out.append(Finding(
                        self.id, self.severity, path, lineno, 0,
                        f"{where} sends {value!r} but no "
                        f"manager registers a handler for it — the message "
                        f"is dropped on arrival"))
        if not t.dynamic_sends:
            for value, sites in sorted(handlers.items()):
                if value in sends:
                    continue
                for owner, path, handler, lineno in sites:
                    where = owner if owner.endswith("()") \
                        else f"{owner}.{handler}"
                    out.append(Finding(
                        self.id, self.severity, path, lineno, 0,
                        f"{where} handles {value!r} but no "
                        f"code path ever sends it — the handler is dead "
                        f"and any state waiting on it stalls"))
        return out


@register_program
class Flow001ProtocolLiveness(ProgramRule):
    id = "FLOW001"
    severity = SEV_ERROR
    title = "protocol state unreachable from the init handshake"

    def check_program(self, index: PackageIndex) -> Iterable[Finding]:
        managers = index.managers
        # handler table: value → [(class, handler method)]
        handler_table: Dict[str, List[Tuple[ClassInfo, str]]] = {}
        for cls in managers:
            for r in cls.registrations:
                if r.value is not None:
                    handler_table.setdefault(r.value, []).append(
                        (cls, r.handler))
        # all raw send sites by value (reachable or not)
        send_sites: Dict[str, int] = {}
        for cls in managers:
            for e in cls.emissions:
                send_sites[e.value] = send_sites.get(e.value, 0) + 1

        # fixpoint: active methods per class + the set of sendable values
        active: Set[Tuple[str, str]] = set()   # (class name, method)
        sent: Set[str] = set()
        # code outside the manager classes has no modelled entry point —
        # assume it runs: its sends are init-reachable, and any symbolic
        # send there could emit anything, which voids liveness verdicts
        dynamic_reachable = False
        for _owner, _path, mi, dyn in index.outside_senders():
            for e in mi.emissions:
                sent.add(e.value)
                send_sites[e.value] = send_sites.get(e.value, 0) + 1
            if dyn:
                dynamic_reachable = True

        def activate(cls: ClassInfo, roots: Iterable[str]) -> bool:
            changed = False
            for name in class_closure(cls, roots):
                key = (cls.name, name)
                if key in active:
                    continue
                active.add(key)
                changed = True
                for e in cls.methods[name].emissions:
                    if e.value not in sent:
                        sent.add(e.value)
            return changed

        for cls in managers:
            activate(cls, INIT_METHODS)
        changed = True
        while changed:
            changed = False
            for value in list(sent):
                for cls, handler in handler_table.get(value, ()):
                    if (cls.name, handler) not in active:
                        changed |= activate(cls, [handler])
            # handlers with unresolvable types could fire on anything —
            # treat them as reachable so downstream states stay live
            for cls in managers:
                for r in cls.registrations:
                    if r.value is None and (cls.name, r.handler) not in active:
                        changed |= activate(cls, [r.handler])

        # a symbolic Message(<param>/<unresolvable>) site inside an ACTIVE
        # method could emit any value — every "unreachable" verdict would
        # be a guess (an unbound site in a method that never activates is
        # itself unreachable and harmless)
        for cls in managers:
            for name, mi in cls.methods.items():
                if (cls.name, name) in active and (
                        mi.unresolved_emissions or mi.unbound_param_sites):
                    dynamic_reachable = True
        if dynamic_reachable:
            return ()

        out: List[Finding] = []
        for cls in managers:
            for r in cls.registrations:
                # the verdict keys on the WIRE VALUE being reachably sent,
                # not on handler activation — a handler inherited from a
                # base class never appears in cls.methods, so activation
                # would misreport it even when its message flows fine
                if r.value is None or r.value in sent:
                    continue
                if not send_sites.get(r.value):
                    continue  # nothing sends it at all → PROTO002's verdict
                if "FINISH" in r.value:
                    msg = (f"{cls.name} waits for {r.value!r} to terminate, "
                           f"but every send of it is unreachable from the "
                           f"init handshake — rounds can never finish")
                else:
                    msg = (f"{cls.name}.{r.handler} waits on {r.value!r}, "
                           f"but every send of it is itself unreachable "
                           f"from the init handshake — the protocol stalls "
                           f"before this state")
                out.append(Finding(self.id, self.severity, cls.path,
                                   r.lineno, 0, msg))
        return out
