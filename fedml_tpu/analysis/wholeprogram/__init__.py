"""Whole-program analysis: package index + cross-file rules + graph export.

Run via ``fedml lint --whole-program`` (rules PROTO002/FLOW001/SHARD001/
RES001, sharing the per-file engine's noqa/fingerprint/baseline machinery)
or ``fedml lint --graph dot|json`` (the send/handle graph the rules reason
over).  See docs/STATIC_ANALYSIS.md for the catalog and the FSM model's
known approximations.
"""

from .graph import build_graph, filter_graph, to_dot, to_json
from .index import PackageIndex, build_index

__all__ = ["PackageIndex", "build_index", "build_graph", "filter_graph",
           "to_dot", "to_json", "index_package"]


def index_package(root=None, paths=None) -> PackageIndex:
    """Parse the package and build a PackageIndex directly (the --graph
    entry point).  Unparsable files are skipped, not fatal — but they are
    recorded on the index so absence-based consumers (the graph's orphan
    lists) can go conservative instead of claiming healthy traffic is
    orphaned."""
    from ..engine import default_root, parse_contexts

    contexts, errors = parse_contexts(root or default_root(), paths)
    index = build_index(contexts)
    index.parse_errors = [rel for rel, _exc in errors]
    return index
