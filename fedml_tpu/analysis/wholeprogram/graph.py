"""Send/handle graph export — ``fedml lint --graph dot|json``.

The graph the protocol rules reason over, made visible: one node per
manager class (labelled with its module and server/client/peer role), one
edge per (sender class → handler class) pair carrying the wire value.
Orphan traffic (sends with no handler, handlers with no sender) is listed
separately so the DOT rendering doubles as a PROTO002 debugging aid.
"""

from __future__ import annotations

import json
from typing import Dict, List, Set, Tuple

from .index import PackageIndex

_ROLE_SHAPE = {"server": "box", "client": "ellipse", "peer": "hexagon"}


def build_graph(index: PackageIndex) -> Dict:
    # the SAME aggregation PROTO002 consumes — the drawing cannot drift
    # from the rule's verdicts
    t = index.aggregate_traffic()
    nodes: List[Dict] = []
    seen: Set[str] = set()
    role_of = {c.name: c.role for m in index.modules.values()
               for c in m.classes}
    for cls in index.managers:
        if cls.name not in seen:
            seen.add(cls.name)
            nodes.append({"name": cls.name, "module": cls.path,
                          "role": cls.role})
    for table in (t.sends, t.handlers):
        for sites in table.values():
            for owner, path, _member, _lineno in sites:
                if owner not in seen:
                    seen.add(owner)
                    nodes.append({"name": owner, "module": path,
                                  "role": role_of.get(owner, "peer")})
    sends = {v: {s[0] for s in sites} for v, sites in t.sends.items()}
    handles = {v: {s[0] for s in sites} for v, sites in t.handlers.items()}
    handler_names: Dict[Tuple[str, str], str] = {
        (v, s[0]): s[2] for v, sites in t.handlers.items() for s in sites}
    edges: List[Dict] = []
    for value in sorted(set(sends) & set(handles)):
        for src in sorted(sends[value]):
            for dst in sorted(handles[value]):
                edges.append({"value": value, "from": src, "to": dst,
                              "handler": handler_names[(value, dst)]})
    # orphan lists mirror PROTO002's conservatism exactly: one dynamic
    # registration could accept anything (no orphan-send verdict), one
    # dynamic send could emit anything (no orphan-handler verdict), and an
    # unparsable file hides ALL its traffic (no orphan verdicts at all) —
    # the drawing must never show red traffic the rule will not flag
    notes = []
    if index.parse_errors:
        notes.append(f"{len(index.parse_errors)} file(s) could not be "
                     f"parsed — orphan detection disabled")
    suppress_orphans = bool(index.parse_errors)
    return {
        "version": 1,
        "tool": "fedml-lint-graph",
        "nodes": sorted(nodes, key=lambda n: n["name"]),
        "edges": edges,
        "notes": notes,
        "orphan_sends": ([] if t.dynamic_handlers or suppress_orphans
                         else sorted(set(sends) - set(handles))),
        "orphan_handlers": ([] if t.dynamic_sends or suppress_orphans
                            else sorted(set(handles) - set(sends))),
    }


def filter_graph(graph: Dict, path_prefixes) -> Dict:
    """Narrow a WHOLE-PACKAGE graph to the nodes defined under the given
    paths plus their direct counterparts — the graph must always be built
    from the full index (a subset index would misresolve every contract),
    then filtered for display.  Orphan lists stay global: they mirror
    PROTO002, which is a whole-program verdict."""
    # normalize ("./x", "x/") so the match can't silently miss everything
    from pathlib import PurePosixPath

    prefixes = [PurePosixPath(str(p)).as_posix() for p in path_prefixes]

    def in_subset(module: str) -> bool:
        return any(module == p or module.startswith(p + "/")
                   for p in prefixes)

    primary = {n["name"] for n in graph["nodes"] if in_subset(n["module"])}
    edges = [e for e in graph["edges"]
             if e["from"] in primary or e["to"] in primary]
    keep = primary | {e["from"] for e in edges} | {e["to"] for e in edges}
    return dict(graph,
                nodes=[n for n in graph["nodes"] if n["name"] in keep],
                edges=edges)


def _q(s: str) -> str:
    return '"' + s.replace('"', r'\"') + '"'


def to_dot(graph: Dict) -> str:
    lines = ["digraph send_handle {", "  rankdir=LR;",
             "  node [fontsize=10]; edge [fontsize=9];"]
    for note in graph.get("notes", ()):
        lines.append(f"  // {note}")
    for n in graph["nodes"]:
        shape = _ROLE_SHAPE.get(n["role"], "ellipse")
        label = f"{n['name']}\\n{n['module']}"
        lines.append(f"  {_q(n['name'])} [shape={shape}, "
                     f"label={_q(label)}];")
    for e in graph["edges"]:
        lines.append(f"  {_q(e['from'])} -> {_q(e['to'])} "
                     f"[label={_q(e['value'])}];")
    # orphan traffic renders red against a sink/source placeholder so a
    # glance at the drawing shows exactly what PROTO002 will flag
    if graph["orphan_sends"] or graph["orphan_handlers"]:
        lines.append('  "(none)" [shape=plaintext, fontcolor=red];')
    for v in graph["orphan_sends"]:
        lines.append(f'  {_q(v)} -> "(none)" '
                     f'[color=red, label="no handler"];')
    for v in graph["orphan_handlers"]:
        lines.append(f'  "(none)" -> {_q(v)} '
                     f'[color=red, label="no sender"];')
    lines.append("}")
    return "\n".join(lines)


def to_json(graph: Dict) -> str:
    return json.dumps(graph, indent=2)
