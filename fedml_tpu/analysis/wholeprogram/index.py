"""Package-wide index for the whole-program lint pass.

Built ONCE per run from the already-parsed per-file ASTs, then queried by
every cross-file rule (PROTO002/FLOW001/SHARD001/RES001) and by the
``fedml lint --graph`` exporter.  It records, per module: import aliases,
module-level string constants; per class: the constant table, the methods,
and — for comm-manager classes — the protocol surface:

* **registrations** — ``register_message_receive_handler(TYPE, self.h)``
  call sites, with TYPE resolved to its wire value through the
  ``message_define`` constant classes (or module constants, or literals);
* **emissions** — ``Message(TYPE, …)`` constructions, resolved the same
  way.  A TYPE that is a local variable is resolved through the method's
  assignments (both arms of a conditional count); a TYPE that is a method
  PARAMETER is left symbolic and bound at each intra-class call site that
  passes a resolvable constant (the ``self._send_round_start(MSG_TYPE_X)``
  idiom);
* **self-references** — every ``self.<method>`` mention, call or not, so
  callbacks handed to ``threading.Timer(…, self._on_timeout)`` count as
  reachable in the liveness FSM;
* **raises** — ``raise`` statements outside any ``try``, for the
  resource-lifecycle rule's receive-loop-exit check.

Like the per-file engine, the index never imports the code under analysis —
stdlib ``ast`` only, so the whole-program pass stays fast and jax-free.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import astutil

#: methods treated as protocol entry points: emissions reachable from these
#: form the init handshake the FLOW001 FSM starts from.
INIT_METHODS = ("run", "run_flow", "run_async", "start", "__init__")

REGISTER_METHOD = "register_message_receive_handler"


@dataclasses.dataclass
class Emission:
    value: str
    lineno: int
    method: str            # method the Message(...) construction sits in


@dataclasses.dataclass
class Registration:
    value: Optional[str]   # None → type expression was not resolvable
    handler: str           # name of the bound self.<handler> method
    lineno: int
    method: str


@dataclasses.dataclass
class MethodInfo:
    name: str
    lineno: int
    node: ast.AST
    params: List[str] = dataclasses.field(default_factory=list)
    self_refs: Set[str] = dataclasses.field(default_factory=set)
    emissions: List[Emission] = dataclasses.field(default_factory=list)
    #: (param name, lineno) of Message(<param>, ...) constructions awaiting
    #: binding from call sites
    param_emissions: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)
    #: the subset no call site could bind — THIS method can send types the
    #: analysis cannot name (a call site that passes an unresolvable arg
    #: next to a resolvable one is treated as bound: approximation)
    unbound_param_sites: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)
    registrations: List[Registration] = dataclasses.field(
        default_factory=list)
    unresolved_emissions: int = 0
    raises_outside_try: List[int] = dataclasses.field(default_factory=list)
    self_calls: List[ast.Call] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassInfo:
    name: str
    path: str
    lineno: int
    bases: List[str]
    consts: Dict[str, str] = dataclasses.field(default_factory=dict)
    methods: Dict[str, MethodInfo] = dataclasses.field(default_factory=dict)
    #: Message(<param>) sites no call site could bind — the class can send
    #: types the analysis cannot name, so orphan-handler verdicts that
    #: depend on "nothing sends X" must be withheld
    unbound_param_emissions: int = 0

    @property
    def registrations(self) -> List[Registration]:
        return [r for m in self.methods.values() for r in m.registrations]

    @property
    def emissions(self) -> List[Emission]:
        return [e for m in self.methods.values() for e in m.emissions]

    @property
    def is_manager(self) -> bool:
        """A protocol participant: registers at least one typed handler."""
        return bool(self.registrations)

    @property
    def role(self) -> str:
        n = self.name.lower()
        if "server" in n or "aggregat" in n:
            return "server"
        if "client" in n or "edge" in n:
            return "client"
        return "peer"

    def calls_finish(self) -> bool:
        for m in self.methods.values():
            for node in ast.walk(m.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "finish"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"):
                    return True
        return False


#: aggregation site: (owner label — class name or "fn()", path, member,
#: lineno)
Site = Tuple[str, str, str, int]


@dataclasses.dataclass
class Traffic:
    sends: Dict[str, List[Site]] = dataclasses.field(default_factory=dict)
    handlers: Dict[str, List[Site]] = dataclasses.field(
        default_factory=dict)
    dynamic_sends: int = 0
    dynamic_handlers: int = 0


@dataclasses.dataclass
class ModuleInfo:
    path: str
    tree: ast.AST
    aliases: Dict[str, str]
    constants: Dict[str, str] = dataclasses.field(default_factory=dict)
    classes: List[ClassInfo] = dataclasses.field(default_factory=list)
    #: top-level functions — drivers/helpers that may send or register
    #: protocol traffic outside any manager class
    functions: List[MethodInfo] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PackageIndex:
    modules: Dict[str, ModuleInfo] = dataclasses.field(default_factory=dict)
    #: the engine's FileContext list, for rules that re-walk raw ASTs
    contexts: List = dataclasses.field(default_factory=list)
    #: relpaths the builder's caller could not parse — consumers that make
    #: absence-based claims (orphan lists) must go conservative when set
    parse_errors: List[str] = dataclasses.field(default_factory=list)
    #: class name → {CONST: wire value}, merged across modules (two classes
    #: aliasing one string is a legal shared contract, same as PROTO001)
    class_consts: Dict[str, Dict[str, str]] = dataclasses.field(
        default_factory=dict)
    #: module-level NAME → set of values seen package-wide (for resolving
    #: bare-name message types imported from another module)
    global_consts: Dict[str, Set[str]] = dataclasses.field(
        default_factory=dict)

    @property
    def managers(self) -> List[ClassInfo]:
        return [c for m in self.modules.values() for c in m.classes
                if c.is_manager]

    def outside_senders(self) -> List[Tuple[str, str, "MethodInfo", int]]:
        """Emission-bearing code OUTSIDE the manager classes — pure-sender
        classes and top-level driver functions.  Their traffic must count,
        or handlers fed by them would be falsely reported dead.  Returns
        (owner label, path, method-info, dynamic-site count) — the count
        is the number of sends the analysis cannot name: for class methods
        the call-site binding already ran, so only UNBOUND sites are
        dynamic; free functions are never bound, so every parametric site
        is."""
        out: List[Tuple[str, str, MethodInfo, int]] = []
        for m in self.modules.values():
            for cls in m.classes:
                if cls.is_manager:
                    continue
                for mi in cls.methods.values():
                    if mi.emissions or mi.param_emissions \
                            or mi.unresolved_emissions:
                        dyn = len(mi.unbound_param_sites) \
                            + mi.unresolved_emissions
                        out.append((cls.name, m.path, mi, dyn))
            for fn in m.functions:
                if fn.emissions or fn.param_emissions \
                        or fn.unresolved_emissions:
                    dyn = len(fn.param_emissions) + fn.unresolved_emissions
                    out.append((f"{fn.name}()", m.path, fn, dyn))
        return out

    def outside_registrations(self) -> List[Tuple[str, "Registration"]]:
        """Handler registrations in top-level functions (a driver wiring a
        manager) — they count toward "someone handles this value"."""
        return [(m.path, r) for m in self.modules.values()
                for fn in m.functions for r in fn.registrations]

    def aggregate_traffic(self) -> "Traffic":
        """ONE canonical send/handler aggregation, shared by PROTO002 and
        the graph exporter so the drawing can never disagree with the
        rule: every emission and registration across managers, pure-sender
        classes and top-level drivers, plus the dynamic-site counts that
        gate absence-based verdicts."""
        t = Traffic()
        for cls in self.managers:
            t.dynamic_sends += cls.unbound_param_emissions
            for m in cls.methods.values():
                t.dynamic_sends += m.unresolved_emissions
            for e in cls.emissions:
                t.sends.setdefault(e.value, []).append(
                    (cls.name, cls.path, e.method, e.lineno))
            for r in cls.registrations:
                if r.value is None:
                    t.dynamic_handlers += 1
                else:
                    t.handlers.setdefault(r.value, []).append(
                        (cls.name, cls.path, r.handler, r.lineno))
        for owner, path, mi, dyn in self.outside_senders():
            t.dynamic_sends += dyn
            for e in mi.emissions:
                t.sends.setdefault(e.value, []).append(
                    (owner, path, e.method, e.lineno))
        for path, r in self.outside_registrations():
            if r.value is None:
                t.dynamic_handlers += 1
            else:
                t.handlers.setdefault(r.value, []).append(
                    (r.method + "()", path, r.handler, r.lineno))
        return t

    def comm_bases(self) -> List[ClassInfo]:
        """Classes that look like the comm-manager runtime base: they define
        BOTH the handler registry setter and the dispatch entry point."""
        return [c for m in self.modules.values() for c in m.classes
                if REGISTER_METHOD in c.methods
                and "receive_message" in c.methods]

    def dispatch_guarded(self) -> Optional[bool]:
        """True/False: does every comm base wrap handler dispatch in a
        try that reaches finish()/stop_receive_message() on error?
        None when the scanned package has no comm base at all."""
        bases = self.comm_bases()
        if not bases:
            return None
        return all(_receive_message_guarded(c.methods["receive_message"])
                   for c in bases)


def class_closure(cls: ClassInfo, roots) -> Set[str]:
    """Transitive ``self.*`` reference closure over a class's methods —
    the reachability model shared by FLOW001 and RES001 (handler bindings
    are already excluded at index-build time)."""
    seen: Set[str] = set()
    stack = [r for r in roots if r in cls.methods]
    while stack:
        name = stack.pop()
        if name in seen:
            continue
        seen.add(name)
        for ref in cls.methods[name].self_refs:
            if ref in cls.methods and ref not in seen:
                stack.append(ref)
    return seen


def _receive_message_guarded(method: MethodInfo) -> bool:
    for node in ast.walk(method.node):
        if not isinstance(node, ast.Try):
            continue
        cleanup = list(node.finalbody)
        for h in node.handlers:
            cleanup.extend(h.body)
        for stmt in cleanup:
            for sub in ast.walk(stmt):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in ("finish",
                                              "stop_receive_message")):
                    return True
    return False


# -- wire-value resolution ----------------------------------------------------

def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def resolve_type_expr(node: ast.AST, index: PackageIndex, module: ModuleInfo,
                      method_node: Optional[ast.AST] = None,
                      params: Sequence[str] = (),
                      _depth: int = 0) -> Tuple[Set[str], Set[str]]:
    """Resolve a message-type expression → (wire values, unbound params).

    Handles string literals, ``Cls.CONST`` references, bare module-constant
    names (local module first, then a package-wide unique name), local
    variables assigned within the method (every arm of an ``a if c else b``
    counts), and function parameters (returned symbolically for call-site
    binding).  Anything else resolves to nothing — callers decide how
    conservative to be about unresolved sites.
    """
    if _depth > 6:
        return set(), set()
    v = _const_str(node)
    if v is not None:
        return {v}, set()
    if isinstance(node, ast.IfExp):
        bv, bp = resolve_type_expr(node.body, index, module, method_node,
                                   params, _depth + 1)
        ov, op = resolve_type_expr(node.orelse, index, module, method_node,
                                   params, _depth + 1)
        return bv | ov, bp | op
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        table = index.class_consts.get(node.value.id, {})
        val = table.get(node.attr)
        return ({val} if val is not None else set()), set()
    if isinstance(node, ast.Name):
        name = node.id
        values: Set[str] = set()
        if method_node is not None:
            for stmt in ast.walk(method_node):
                if (isinstance(stmt, ast.Assign)
                        and any(isinstance(t, ast.Name) and t.id == name
                                for t in stmt.targets)):
                    sv, _ = resolve_type_expr(stmt.value, index, module,
                                              method_node, (), _depth + 1)
                    values |= sv
        if values:
            return values, set()
        if name in params:
            return set(), {name}
        if name in module.constants:
            return {module.constants[name]}, set()
        glob = index.global_consts.get(name, set())
        if len(glob) == 1:
            return set(glob), set()
    return set(), set()


# -- builders ----------------------------------------------------------------

def _collect_class_consts(cls: ast.ClassDef) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for stmt in cls.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            v = _const_str(stmt.value)
            if v is not None and stmt.targets[0].id.isupper():
                out[stmt.targets[0].id] = v
    return out


def _collect_module_consts(tree: ast.AST) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for stmt in getattr(tree, "body", []):
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id.isupper()):
            v = _const_str(stmt.value)
            if v is not None:
                out[stmt.targets[0].id] = v
    return out


def _register_handler_arg(call: ast.Call) -> Optional[ast.AST]:
    """The handler expression of a register_message_receive_handler call —
    positional or keyword-bound (``handler=self.h``)."""
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "handler":
            return kw.value
    return None


def _register_type_arg(call: ast.Call) -> Optional[ast.AST]:
    """The message-type expression — positional or ``msg_type=`` keyword."""
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "msg_type":
            return kw.value
    return None


def _message_type_arg(call: ast.Call) -> Optional[ast.AST]:
    """The type expression of a Message construction — positional or
    keyword (``Message(type=X, …)`` is legal against the runtime ctor)."""
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg in ("type", "msg_type", "mtype"):
            return kw.value
    return None


def _is_message_ctor(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id == "Message"
    if isinstance(fn, ast.Attribute):
        return fn.attr == "Message"
    return False


def _raise_outside_try(node: ast.Raise, parents, method_node) -> bool:
    for a in astutil.ancestors(node, parents):
        if isinstance(a, ast.Try):
            return False
        if a is method_node:
            break
    return True


def _build_method(fn: ast.AST, index: PackageIndex, module: ModuleInfo,
                  parents) -> MethodInfo:
    params = [a.arg for a in fn.args.args if a.arg != "self"]
    info = MethodInfo(fn.name, fn.lineno, fn, params=params)
    # a handler BOUND via register_message_receive_handler(TYPE, self.h)
    # must not count as a self-reference: it runs when its message arrives,
    # not when the registering method does — counting it would fold every
    # handler into the init closure and blind the liveness FSM
    binding_ids = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == REGISTER_METHOD):
            h = _register_handler_arg(node)
            if h is not None:
                binding_ids.add(id(h))
    for node in ast.walk(fn):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and id(node) not in binding_ids):
            info.self_refs.add(node.attr)
        if isinstance(node, ast.Raise) and _raise_outside_try(
                node, parents, fn):
            info.raises_outside_try.append(node.lineno)
        if not isinstance(node, ast.Call):
            continue
        if (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            info.self_calls.append(node)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == REGISTER_METHOD
                and (node.args or node.keywords)):
            targ = _register_type_arg(node)
            values, _ = (resolve_type_expr(targ, index, module, fn, ())
                         if targ is not None else (set(), set()))
            handler = ""
            h = _register_handler_arg(node)
            if isinstance(h, ast.Attribute):
                handler = h.attr
            elif isinstance(h, ast.Name):
                handler = h.id
            if values:
                for v in sorted(values):
                    info.registrations.append(
                        Registration(v, handler, node.lineno, fn.name))
            else:
                info.registrations.append(
                    Registration(None, handler, node.lineno, fn.name))
        elif _is_message_ctor(node) and (node.args or node.keywords):
            # bare Message() is the transports' payload-reconstruction
            # idiom, not a protocol send — anything else must resolve or
            # count as a dynamic send
            targ = _message_type_arg(node)
            if targ is None:
                info.unresolved_emissions += 1
                continue
            values, unbound = resolve_type_expr(targ, index, module,
                                                fn, params)
            for v in sorted(values):
                info.emissions.append(Emission(v, node.lineno, fn.name))
            for p in sorted(unbound):
                info.param_emissions.append((p, node.lineno))
            if not values and not unbound:
                info.unresolved_emissions += 1
    return info


def _bind_param_emissions(cls: ClassInfo, index: PackageIndex,
                          module: ModuleInfo) -> int:
    """Bind ``Message(<param>, …)`` emissions to the constants passed at
    intra-class call sites; returns the number of params left unbound."""
    unbound = 0
    # a callee with several Message(<param>) sites for the same param must
    # not multiply the bound emissions — one per (caller, value, call site)
    bound_seen: set = set()
    for callee in cls.methods.values():
        if not callee.param_emissions:
            continue
        for pname, lineno in callee.param_emissions:
            try:
                pidx = callee.params.index(pname)
            except ValueError:
                unbound += 1
                continue
            bound_here = False
            for caller in cls.methods.values():
                for call in caller.self_calls:
                    if call.func.attr != callee.name:
                        continue
                    arg: Optional[ast.AST] = None
                    if pidx < len(call.args):
                        arg = call.args[pidx]
                    else:
                        for kw in call.keywords:
                            if kw.arg == pname:
                                arg = kw.value
                    if arg is None:
                        continue
                    values, _ = resolve_type_expr(
                        arg, index, module, caller.node, caller.params)
                    for v in sorted(values):
                        key = (caller.name, v, call.lineno)
                        if key not in bound_seen:
                            bound_seen.add(key)
                            caller.emissions.append(
                                Emission(v, call.lineno, caller.name))
                        bound_here = True
            if not bound_here:
                callee.unbound_param_sites.append((pname, lineno))
                unbound += 1
    return unbound


def build_index(contexts) -> PackageIndex:
    """``contexts`` — the engine's FileContext list (path/tree/lines)."""
    index = PackageIndex(contexts=list(contexts))
    # pass 1: constant tables (needed before any type expression resolves)
    for ctx in contexts:
        module = ModuleInfo(ctx.path, ctx.tree, ctx.aliases,
                            _collect_module_consts(ctx.tree))
        for name, value in module.constants.items():
            index.global_consts.setdefault(name, set()).add(value)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                consts = _collect_class_consts(node)
                if consts:
                    index.class_consts.setdefault(node.name, {}).update(
                        consts)
        index.modules[ctx.path] = module
    # pass 2: classes, methods, top-level functions, protocol surface
    for ctx in contexts:
        module = index.modules[ctx.path]
        parents = ctx.parents
        for node in ctx.tree.body if hasattr(ctx.tree, "body") else []:
            if isinstance(node, astutil.FUNC_NODES):
                # a free function cannot be call-site-bound, so any
                # Message(<param>) site in it stays symbolic (dynamic)
                module.functions.append(
                    _build_method(node, index, module, parents))
            if not isinstance(node, ast.ClassDef):
                continue
            cls = ClassInfo(node.name, ctx.path, node.lineno,
                            [astutil.dotted_name(b, ctx.aliases)
                             for b in node.bases],
                            consts=index.class_consts.get(node.name, {}))
            for stmt in node.body:
                if isinstance(stmt, astutil.FUNC_NODES):
                    cls.methods[stmt.name] = _build_method(
                        stmt, index, module, parents)
            cls.unbound_param_emissions = _bind_param_emissions(
                cls, index, module)
            module.classes.append(cls)
    return index
