"""SHARD001 + RES001 — sharding-spec and resource-lifecycle program rules.

**SHARD001 (PartitionSpec/mesh consistency).**  Mesh axis declarations are
collected PACKAGE-WIDE (``AXIS_*`` constants, literal ``Mesh(...,
axis_names=…)`` tuples, ``build_mesh({...})`` dict keys); spec usage is
checked in the sharded subsystems (``parallel/``, ``train/llm/``,
``ml/engine/``) where a typo'd axis only explodes at trace time on real
hardware:

* a string literal (or a name resolving to one) inside ``PartitionSpec``/
  ``P(...)`` that names no declared mesh axis;
* ``shard_map(..., in_specs=…)`` whose literal spec tuple's arity differs
  from the wrapped function's positional arity;
* ``jax.jit(..., donate_argnums=…, in_shardings=…)`` donating an argument
  index past the end of the ``in_shardings`` tuple.

**RES001 (resource lifecycle).**

* a ``threading.Thread`` that is neither daemonized nor joined anywhere in
  its module outlives shutdown and leaks;
* a comm-manager class that registers handlers but never calls
  ``finish()`` — its receive loop cannot exit;
* a ``raise`` (outside any ``try``) in handler-reachable code when the
  comm base's ``receive_message`` dispatch is NOT guarded by a
  try/finish — the exception strands every peer blocked on this node.
  With the guarded dispatch in ``FedMLCommManager.receive_message`` the
  check stays quiet; remove the guard and every raising handler lights up.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .. import astutil
from ..findings import SEV_ERROR, SEV_WARNING, Finding
from ..rules import ProgramRule, register_program
from .index import PackageIndex, class_closure

SHARD_SCOPES = ("parallel/", "train/llm/", "ml/engine/")


def _in_shard_scope(path: str) -> bool:
    return any(s in path for s in SHARD_SCOPES)


def _literal_strs(node: ast.AST) -> List[str]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _dotted(node: ast.Call, ctx) -> str:
    return astutil.call_name(node, ctx.aliases)


def collect_declared_axes(index: PackageIndex, contexts) -> Set[str]:
    axes: Set[str] = set()
    for module in index.modules.values():
        for name, value in module.constants.items():
            if name.startswith("AXIS_"):
                axes.add(value)
    for ctx in contexts:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node, ctx)
            tail = name.rsplit(".", 1)[-1]
            if tail == "Mesh":
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes.update(_literal_strs(kw.value))
                if len(node.args) >= 2:
                    axes.update(_literal_strs(node.args[1]))
            elif tail in ("build_mesh", "build_hybrid_mesh"):
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if isinstance(arg, ast.Dict):
                        axes.update(k.value for k in arg.keys
                                    if isinstance(k, ast.Constant)
                                    and isinstance(k.value, str))
    return axes


def _resolve_str_name(name: str, ctx, enclosing: Optional[ast.AST],
                      global_consts: Dict[str, Set[str]]) -> Optional[str]:
    """Best-effort: a bare name → the string it denotes, else None."""
    if enclosing is not None:
        args = enclosing.args
        pos = args.args
        defaults = args.defaults
        for a, d in zip(pos[len(pos) - len(defaults):], defaults):
            if a.arg == name and isinstance(d, ast.Constant) \
                    and isinstance(d.value, str):
                return d.value
        for stmt in ast.walk(enclosing):
            if (isinstance(stmt, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == name
                            for t in stmt.targets)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                return stmt.value.value
    vals = global_consts.get(name, set())
    if len(vals) == 1:
        return next(iter(vals))
    return None


@register_program
class Shard001SpecMeshConsistency(ProgramRule):
    id = "SHARD001"
    severity = SEV_ERROR
    title = "PartitionSpec/mesh contract violation in the sharded layers"

    def check_program(self, index: PackageIndex) -> Iterable[Finding]:
        contexts = getattr(index, "contexts", [])
        axes = collect_declared_axes(index, contexts)
        out: List[Finding] = []
        for ctx in contexts:
            if not _in_shard_scope(ctx.path):
                continue
            out.extend(self._check_specs(ctx, axes, index))
            out.extend(self._check_shard_map_arity(ctx))
            out.extend(self._check_donate(ctx))
        return out

    # -- undeclared axis names in P(...) -------------------------------------
    def _check_specs(self, ctx, axes: Set[str],
                     index: PackageIndex) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not _dotted(node, ctx).endswith("PartitionSpec"):
                continue
            enclosing = astutil.enclosing_function(node, ctx.parents)
            for arg in node.args:
                name: Optional[str] = None
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    name = arg.value
                elif isinstance(arg, ast.Name):
                    name = _resolve_str_name(arg.id, ctx, enclosing,
                                             index.global_consts)
                if name is not None and name not in axes:
                    # NB: the declared-axes set must stay OUT of the
                    # message — it feeds the baseline fingerprint, and an
                    # unrelated module declaring a new axis would churn it
                    yield Finding(
                        self.id, self.severity, ctx.path, node.lineno, 0,
                        f"PartitionSpec names mesh axis {name!r}, but no "
                        f"mesh in the package declares it — this fails at "
                        f"trace time on hardware (run `fedml lint --graph "
                        f"json` or grep AXIS_*/Mesh(axis_names=...) for "
                        f"the declared set)")

    # -- shard_map in_specs arity --------------------------------------------
    @staticmethod
    def _spec_len(node: ast.AST, ctx,
                  enclosing: Optional[ast.AST]) -> Optional[int]:
        if isinstance(node, (ast.Tuple, ast.List)):
            return len(node.elts)
        if isinstance(node, ast.Call):
            # a bare P(...) is a legal pytree PREFIX that broadcasts over
            # every positional arg — no arity can be concluded from it
            return None
        if isinstance(node, ast.Name):
            scopes: List[ast.AST] = []
            if enclosing is not None:
                scopes.append(enclosing)
            scopes.append(ctx.tree)
            lens: Set[int] = set()
            for scope in scopes:
                for stmt in ast.walk(scope):
                    if (isinstance(stmt, ast.Assign)
                            and any(isinstance(t, ast.Name)
                                    and t.id == node.id
                                    for t in stmt.targets)
                            and isinstance(stmt.value,
                                           (ast.Tuple, ast.List))):
                        lens.add(len(stmt.value.elts))
                if lens:
                    break
            if len(lens) == 1:
                return lens.pop()
        return None

    @staticmethod
    def _fn_arity(fn: ast.AST) -> Optional[int]:
        a = fn.args
        if a.vararg is not None or a.kwonlyargs:
            return None
        pos = list(a.posonlyargs) + list(a.args)
        return len([x for x in pos if x.arg != "self"])

    def _check_shard_map_arity(self, ctx) -> Iterable[Finding]:
        module_fns = {n.name: n for n in ast.walk(ctx.tree)
                      if isinstance(n, astutil.FUNC_NODES)}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, astutil.FUNC_NODES):
                continue
            for dec in node.decorator_list:
                if not (isinstance(dec, ast.Call) and dec.args):
                    continue
                dname = _dotted(dec, ctx)
                inner = astutil.dotted_name(dec.args[0], ctx.aliases)
                if not (dname.rsplit(".", 1)[-1] == "partial"
                        and inner.endswith("shard_map")):
                    continue
                yield from self._arity_check(dec, node, ctx)
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and _dotted(node, ctx).endswith("shard_map")
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in module_fns):
                yield from self._arity_check(
                    node, module_fns[node.args[0].id], ctx)

    def _arity_check(self, call: ast.Call, fn: ast.AST,
                     ctx) -> Iterable[Finding]:
        in_specs = next((kw.value for kw in call.keywords
                         if kw.arg == "in_specs"), None)
        if in_specs is None:
            return
        enclosing = astutil.enclosing_function(call, ctx.parents)
        n_specs = self._spec_len(in_specs, ctx, enclosing)
        arity = self._fn_arity(fn)
        if n_specs is not None and arity is not None and n_specs != arity:
            yield Finding(
                self.id, self.severity, ctx.path, call.lineno, 0,
                f"shard_map in_specs has {n_specs} entries but "
                f"{fn.name}() takes {arity} positional arguments — "
                f"the spec/argument zip fails at trace time")

    # -- donate_argnums past in_shardings ------------------------------------
    def _check_donate(self, ctx) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _dotted(node, ctx).rsplit(".", 1)[-1]
            if tail not in ("jit", "pjit"):
                continue
            donate = next((kw.value for kw in node.keywords
                           if kw.arg == "donate_argnums"), None)
            shardings = next((kw.value for kw in node.keywords
                              if kw.arg == "in_shardings"), None)
            if donate is None or not isinstance(shardings,
                                                (ast.Tuple, ast.List)):
                continue
            idxs = ([donate] if isinstance(donate, ast.Constant)
                    else list(donate.elts)
                    if isinstance(donate, (ast.Tuple, ast.List)) else [])
            for idx in idxs:
                if (isinstance(idx, ast.Constant)
                        and isinstance(idx.value, int)
                        and idx.value >= len(shardings.elts)):
                    yield Finding(
                        self.id, self.severity, ctx.path, node.lineno, 0,
                        f"donate_argnums={idx.value} is past the end of "
                        f"the {len(shardings.elts)}-entry in_shardings — "
                        f"the donated buffer has no sharding spec")


@register_program
class Res001ResourceLifecycle(ProgramRule):
    id = "RES001"
    severity = SEV_WARNING
    title = "leaked thread / receive loop that cannot exit"

    def check_program(self, index: PackageIndex) -> Iterable[Finding]:
        contexts = getattr(index, "contexts", [])
        out: List[Finding] = []
        for ctx in contexts:
            out.extend(self._check_threads(ctx))
        out.extend(self._check_managers(index))
        return out

    # -- thread lifecycle ----------------------------------------------------
    @staticmethod
    def _terminal(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    def _check_threads(self, ctx) -> Iterable[Finding]:
        daemonized: Set[str] = set()
        joined: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and node.targets[0].attr == "daemon"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True):
                t = self._terminal(node.targets[0].value)
                if t:
                    daemonized.add(t)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"):
                t = self._terminal(node.func.value)
                if t:
                    joined.add(t)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and _dotted(node, ctx).endswith("threading.Thread")):
                continue
            daemon_kw = next((kw.value for kw in node.keywords
                              if kw.arg == "daemon"), None)
            if daemon_kw is not None:
                if not (isinstance(daemon_kw, ast.Constant)
                        and daemon_kw.value is False):
                    continue  # daemon=True, or dynamic — give it the benefit
            parent = ctx.parents.get(node)
            target: Optional[str] = None
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
                target = self._terminal(parent.targets[0])
            elif isinstance(parent, (ast.Tuple, ast.List)):
                # comprehension/list element: can't track the binding; the
                # collection is usually iterated for join — skip
                continue
            elif isinstance(parent, ast.ListComp):
                continue
            if target is not None and (target in daemonized
                                       or target in joined):
                continue
            yield Finding(
                self.id, self.severity, ctx.path, node.lineno, 0,
                "threading.Thread is neither daemonized nor joined "
                "anywhere in this module — it outlives shutdown and "
                "leaks at interpreter exit")

    # -- comm-manager lifecycle ----------------------------------------------
    def _check_managers(self, index: PackageIndex) -> Iterable[Finding]:
        guarded = index.dispatch_guarded()
        for cls in index.managers:
            if not cls.calls_finish():
                yield Finding(
                    self.id, self.severity, cls.path, cls.lineno, 0,
                    f"{cls.name} registers message handlers but never "
                    f"calls finish() — its receive loop cannot exit and "
                    f"the node leaks its transport")
            if guarded is not False:
                # True → the base's dispatch provably cleans up; None → no
                # comm base in scan scope (a --paths subset), where flagging
                # would be a guess — only a PROVABLY unguarded base fires
                continue
            handler_roots = {r.handler for r in cls.registrations}
            reachable = class_closure(cls, handler_roots)
            for mname in sorted(reachable):
                m = cls.methods.get(mname)
                if m is None:
                    continue
                for lineno in m.raises_outside_try:
                    yield Finding(
                        self.id, self.severity, cls.path, lineno, 0,
                        f"{cls.name}.{mname} can raise out of a message "
                        f"handler and the comm base's receive_message "
                        f"dispatch is not guarded — the receive loop dies "
                        f"without finish() and peers stall forever")

