"""Lint engine: file collection, parsing, rule dispatch, noqa suppression.

The engine is deliberately dependency-free (stdlib ``ast`` only) so the
linter runs in CI images that don't carry jax — it reads source, it never
imports the code under analysis.
"""

from __future__ import annotations

import ast
import dataclasses
import re
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import astutil
from .findings import SEV_ERROR, Finding

#: inline suppression: ``# fedml: noqa[JAX001]`` (one or more comma-separated
#: rule ids, justification text after an em-dash or any trailing prose) or a
#: bare ``# fedml: noqa`` that silences every rule on the line.
NOQA_RE = re.compile(r"#\s*fedml:\s*noqa(?:\[([A-Za-z0-9_,\s-]+)\])?", re.I)


@dataclasses.dataclass
class FileContext:
    path: str                    # posix relpath from the lint root
    source: str
    tree: ast.AST
    lines: List[str]
    _parents: Optional[Dict[ast.AST, ast.AST]] = None
    _aliases: Optional[Dict[str, str]] = None

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = astutil.build_parent_map(self.tree)
        return self._parents

    @property
    def aliases(self) -> Dict[str, str]:
        if self._aliases is None:
            self._aliases = astutil.import_aliases(self.tree)
        return self._aliases


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    files_scanned: int
    suppressed: int
    duration_s: float
    #: surfaced caveats — e.g. "cross-file rules skipped because a file
    #: does not parse"; run_cli echoes these so a clean exit is never
    #: silently weaker than requested
    notes: List[str] = dataclasses.field(default_factory=list)


def default_root() -> Path:
    """Checkout root: the directory containing the fedml_tpu package."""
    return Path(__file__).resolve().parent.parent.parent


def collect_files(root: Path,
                  paths: Optional[Sequence[str]] = None) -> List[Path]:
    """Python files under ``root`` — default scope is the fedml_tpu package;
    ``paths`` (files or directories, relative to root) narrows the scan."""
    targets = [root / p for p in paths] if paths else [root / "fedml_tpu"]
    out: Set[Path] = set()
    for t in targets:
        if t.is_file() and t.suffix == ".py":
            out.add(t)
        elif t.is_dir():
            out.update(p for p in t.rglob("*.py")
                       if "__pycache__" not in p.parts)
        else:
            # a typo'd --paths must not silently scan nothing and pass —
            # that would disable the gate with exit 0
            raise FileNotFoundError(
                f"lint target {t} is not a .py file or directory")
    return sorted(out)


def parse_contexts(root: Path, paths: Optional[Sequence[str]] = None,
                   skip: Optional[Set[str]] = None
                   ) -> Tuple[List[FileContext], List[Tuple[str, Exception]]]:
    """Collect + parse into FileContexts; unparsable files come back as
    (relpath, exception) pairs for the caller to surface (run_lint turns
    them into LINT001 findings; the index builder skips them).  ``skip``
    short-circuits relpaths already parsed elsewhere."""
    contexts: List[FileContext] = []
    errors: List[Tuple[str, Exception]] = []
    for fp in collect_files(root, paths):
        rel = fp.relative_to(root).as_posix()
        if skip and rel in skip:
            continue
        try:
            source = fp.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            errors.append((rel, exc))
            continue
        contexts.append(FileContext(rel, source, tree, source.splitlines()))
    return contexts, errors


def _noqa_rules_for_line(line: str) -> Optional[Set[str]]:
    """None → no suppression; empty set → suppress all; else rule ids."""
    m = NOQA_RE.search(line)
    if not m:
        return None
    if not m.group(1):
        return set()
    return {r.strip().upper() for r in m.group(1).split(",") if r.strip()}


def _apply_noqa(findings: List[Finding],
                ctx: FileContext) -> Tuple[List[Finding], int]:
    kept, suppressed = [], 0
    for f in findings:
        line = ctx.lines[f.line - 1] if 0 < f.line <= len(ctx.lines) else ""
        rules = _noqa_rules_for_line(line)
        if rules is not None and (not rules or f.rule_id.upper() in rules):
            suppressed += 1
        else:
            kept.append(f)
    return kept, suppressed


def run_lint(root: Optional[Path] = None,
             paths: Optional[Sequence[str]] = None,
             rule_ids: Optional[Sequence[str]] = None,
             whole_program: bool = False,
             perf: bool = False,
             mesh: bool = False,
             conc: bool = False,
             taint: bool = False,
             perf_registry=None) -> LintResult:
    from .conc import conc_rule_ids
    from .mesh.rules import mesh_rule_ids
    from .perf.rules import perf_rule_ids
    from .rules import make_program_rules, make_rules
    from .taint import taint_rule_ids

    t0 = time.monotonic()
    root = Path(root) if root else default_root()
    wanted = {r.strip().upper() for r in rule_ids} if rule_ids else None
    all_rules = make_rules()
    all_prog_rules = make_program_rules()
    prog_ids = {r.id.upper() for r in all_prog_rules}
    # PERF000/SHARD000 are the passes' own build-failure findings,
    # suppressible and baselineable like any rule id
    perf_ids = {r.upper() for r in perf_rule_ids()} | {"PERF000"}
    mesh_ids = {r.upper() for r in mesh_rule_ids()} | {"SHARD000"}
    conc_ids = {r.upper() for r in conc_rule_ids()} | {"CONC000"}
    taint_ids = {r.upper() for r in taint_rule_ids()} | {"PRIV000"}
    if wanted is not None:
        known = ({r.id.upper() for r in all_rules} | prog_ids | perf_ids
                 | mesh_ids | conc_ids | taint_ids)
        unknown = sorted(wanted - known)
        if unknown:
            raise ValueError(f"unknown rule id(s) {unknown}; "
                             f"known: {sorted(known)}")
        # asking for a whole-program/perf/mesh rule by id implies that
        # pass; conversely --perf/--mesh with a rule filter that selects
        # NO rule of that tier would trace every entrypoint for nothing
        # — skip the pass.  (SHARD001 is a whole-program rule; only
        # SHARD000/SHARD002-006 enable the mesh pass.)
        whole_program = whole_program or bool(wanted & prog_ids)
        perf = bool(wanted & perf_ids)
        mesh = bool(wanted & mesh_ids)
        conc = bool(wanted & conc_ids)
        taint = bool(wanted & taint_ids)
    rules = [r for r in all_rules
             if wanted is None or r.id.upper() in wanted]
    prog_rules = ([r for r in all_prog_rules
                   if wanted is None or r.id.upper() in wanted]
                  if whole_program else [])
    findings: List[Finding] = []
    suppressed = 0
    contexts, parse_errors = parse_contexts(root, paths)
    n_files = len(contexts) + len(parse_errors)
    for rel, exc in parse_errors:
        findings.append(Finding(
            "LINT001", SEV_ERROR, rel,
            getattr(exc, "lineno", 1) or 1, 0,
            f"file cannot be parsed: {exc.__class__.__name__}"))
    for ctx in contexts:
        file_findings: List[Finding] = []
        for rule in rules:
            file_findings.extend(rule.check_file(ctx))
        kept, n_sup = _apply_noqa(file_findings, ctx)
        findings.extend(kept)
        suppressed += n_sup
    # project-level rules (cross-file: protocol drift) emit after the scan
    ctx_by_path = {c.path: c for c in contexts}

    def _emit_project(project_findings: List[Finding]) -> None:
        nonlocal suppressed
        by_file: Dict[str, List[Finding]] = {}
        for f in project_findings:
            by_file.setdefault(f.path, []).append(f)
        for path, fl in by_file.items():
            if path in ctx_by_path:
                kept, n_sup = _apply_noqa(fl, ctx_by_path[path])
                findings.extend(kept)
                suppressed += n_sup
            else:
                findings.extend(fl)

    for rule in rules:
        _emit_project(list(rule.finish()))
    notes: List[str] = []
    if prog_rules:
        from .wholeprogram import build_index

        # cross-file verdicts are only sound when EVERY file parses: an
        # invisible counterpart (its handlers/sends unindexed) would turn
        # healthy traffic into orphans/stalls.  Skip — never guess — and
        # say so; on a full scan the LINT001 finding fails the run anyway.
        skip_reason = None
        subset = None
        rest: List[FileContext] = []
        if parse_errors:
            skip_reason = (
                f"cross-file rules skipped: {len(parse_errors)} file(s) "
                f"cannot be parsed (see LINT001) — cross-file verdicts "
                f"would be guesses")
        elif paths:
            # subset scans still index the WHOLE package (a subset index
            # would misreport the counterpart role's traffic) and emit
            # findings only for the requested files — clang-tidy
            # header-filter semantics, so pre-commit runs stay quiet.
            subset = {c.path for c in contexts}
            rest, rest_errors = parse_contexts(root, None, skip=subset)
            if rest_errors:
                skip_reason = (
                    f"cross-file rules skipped: {len(rest_errors)} "
                    f"file(s) outside --paths cannot be parsed — run a "
                    f"full `fedml lint --whole-program` for the verdicts")
        if skip_reason is not None:
            notes.append(skip_reason)
        else:
            index = build_index(contexts + rest)
            for rule in prog_rules:
                prog_findings = list(rule.check_program(index))
                if subset is not None:
                    prog_findings = [f for f in prog_findings
                                     if f.path in subset]
                _emit_project(prog_findings)
    build_cache = None
    if perf or mesh:
        # one shared factory-build cache: a run mixing the perf and mesh
        # tiers (e.g. --rules PERF001,SHARD004) builds each registered
        # entrypoint once instead of once per tier
        from .perf import EntrypointBuildCache

        build_cache = EntrypointBuildCache()
    if perf:
        from .perf import run_perf_pass

        perf_findings, perf_notes = run_perf_pass(
            root, registry=perf_registry, rule_ids=rule_ids,
            cache=build_cache)
        if paths:
            subset_paths = {c.path for c in contexts}
            perf_findings = [f for f in perf_findings
                             if f.path in subset_paths]
        _emit_project(perf_findings)
        notes.extend(perf_notes)
    if mesh:
        from .mesh import run_mesh_pass

        mesh_findings, mesh_notes = run_mesh_pass(
            root, registry=perf_registry, rule_ids=rule_ids,
            cache=build_cache)
        if paths:
            subset_paths = {c.path for c in contexts}
            mesh_findings = [f for f in mesh_findings
                             if f.path in subset_paths]
        _emit_project(mesh_findings)
        notes.extend(mesh_notes)
    if conc:
        from .conc import run_conc_pass

        conc_findings, conc_notes = run_conc_pass(root, rule_ids=rule_ids)
        if paths:
            subset_paths = {c.path for c in contexts}
            conc_findings = [f for f in conc_findings
                             if f.path in subset_paths]
        _emit_project(conc_findings)
        notes.extend(conc_notes)
    if taint:
        from .taint import run_taint_pass

        taint_findings, taint_notes = run_taint_pass(
            root, rule_ids=rule_ids)
        if paths:
            subset_paths = {c.path for c in contexts}
            taint_findings = [f for f in taint_findings
                              if f.path in subset_paths]
        _emit_project(taint_findings)
        notes.extend(taint_notes)
    findings.sort(key=Finding.sort_key)
    return LintResult(findings, n_files, suppressed,
                      time.monotonic() - t0, notes)
