"""Lint engine: file collection, parsing, rule dispatch, noqa suppression.

The engine is deliberately dependency-free (stdlib ``ast`` only) so the
linter runs in CI images that don't carry jax — it reads source, it never
imports the code under analysis.
"""

from __future__ import annotations

import ast
import dataclasses
import re
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import astutil
from .findings import SEV_ERROR, Finding

#: inline suppression: ``# fedml: noqa[JAX001]`` (one or more comma-separated
#: rule ids, justification text after an em-dash or any trailing prose) or a
#: bare ``# fedml: noqa`` that silences every rule on the line.
NOQA_RE = re.compile(r"#\s*fedml:\s*noqa(?:\[([A-Za-z0-9_,\s-]+)\])?", re.I)


@dataclasses.dataclass
class FileContext:
    path: str                    # posix relpath from the lint root
    source: str
    tree: ast.AST
    lines: List[str]
    _parents: Optional[Dict[ast.AST, ast.AST]] = None
    _aliases: Optional[Dict[str, str]] = None

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = astutil.build_parent_map(self.tree)
        return self._parents

    @property
    def aliases(self) -> Dict[str, str]:
        if self._aliases is None:
            self._aliases = astutil.import_aliases(self.tree)
        return self._aliases


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    files_scanned: int
    suppressed: int
    duration_s: float


def default_root() -> Path:
    """Checkout root: the directory containing the fedml_tpu package."""
    return Path(__file__).resolve().parent.parent.parent


def collect_files(root: Path,
                  paths: Optional[Sequence[str]] = None) -> List[Path]:
    """Python files under ``root`` — default scope is the fedml_tpu package;
    ``paths`` (files or directories, relative to root) narrows the scan."""
    targets = [root / p for p in paths] if paths else [root / "fedml_tpu"]
    out: Set[Path] = set()
    for t in targets:
        if t.is_file() and t.suffix == ".py":
            out.add(t)
        elif t.is_dir():
            out.update(p for p in t.rglob("*.py")
                       if "__pycache__" not in p.parts)
        else:
            # a typo'd --paths must not silently scan nothing and pass —
            # that would disable the gate with exit 0
            raise FileNotFoundError(
                f"lint target {t} is not a .py file or directory")
    return sorted(out)


def _noqa_rules_for_line(line: str) -> Optional[Set[str]]:
    """None → no suppression; empty set → suppress all; else rule ids."""
    m = NOQA_RE.search(line)
    if not m:
        return None
    if not m.group(1):
        return set()
    return {r.strip().upper() for r in m.group(1).split(",") if r.strip()}


def _apply_noqa(findings: List[Finding],
                ctx: FileContext) -> Tuple[List[Finding], int]:
    kept, suppressed = [], 0
    for f in findings:
        line = ctx.lines[f.line - 1] if 0 < f.line <= len(ctx.lines) else ""
        rules = _noqa_rules_for_line(line)
        if rules is not None and (not rules or f.rule_id.upper() in rules):
            suppressed += 1
        else:
            kept.append(f)
    return kept, suppressed


def run_lint(root: Optional[Path] = None,
             paths: Optional[Sequence[str]] = None,
             rule_ids: Optional[Sequence[str]] = None) -> LintResult:
    from .rules import make_rules

    t0 = time.monotonic()
    root = Path(root) if root else default_root()
    wanted = {r.strip().upper() for r in rule_ids} if rule_ids else None
    all_rules = make_rules()
    if wanted is not None:
        known = {r.id.upper() for r in all_rules}
        unknown = sorted(wanted - known)
        if unknown:
            raise ValueError(f"unknown rule id(s) {unknown}; "
                             f"known: {sorted(known)}")
    rules = [r for r in all_rules
             if wanted is None or r.id.upper() in wanted]
    findings: List[Finding] = []
    suppressed = 0
    files = collect_files(root, paths)
    contexts: List[FileContext] = []
    for fp in files:
        rel = fp.relative_to(root).as_posix()
        try:
            source = fp.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            findings.append(Finding(
                "LINT001", SEV_ERROR, rel,
                getattr(exc, "lineno", 1) or 1, 0,
                f"file cannot be parsed: {exc.__class__.__name__}"))
            continue
        ctx = FileContext(rel, source, tree, source.splitlines())
        contexts.append(ctx)
        file_findings: List[Finding] = []
        for rule in rules:
            file_findings.extend(rule.check_file(ctx))
        kept, n_sup = _apply_noqa(file_findings, ctx)
        findings.extend(kept)
        suppressed += n_sup
    # project-level rules (cross-file: protocol drift) emit after the scan
    ctx_by_path = {c.path: c for c in contexts}
    for rule in rules:
        project_findings = list(rule.finish())
        by_file: Dict[str, List[Finding]] = {}
        for f in project_findings:
            by_file.setdefault(f.path, []).append(f)
        for path, fl in by_file.items():
            if path in ctx_by_path:
                kept, n_sup = _apply_noqa(fl, ctx_by_path[path])
                findings.extend(kept)
                suppressed += n_sup
            else:
                findings.extend(fl)
    findings.sort(key=Finding.sort_key)
    return LintResult(findings, len(files), suppressed,
                      time.monotonic() - t0)
