"""Rule registry.  A per-file rule sees every file once (``check_file``) and
may emit more findings after the whole scan (``finish``, for cross-file rules
like PROTO001).  A PROGRAM rule (``ProgramRule``) instead queries the
package index built by ``analysis.wholeprogram`` and only runs under
``fedml lint --whole-program``.  ``make_rules``/``make_program_rules`` build
FRESH instances per run — rules are allowed to accumulate state."""

from __future__ import annotations

from typing import Iterable, List, Type

from ..findings import Finding

_REGISTRY: List[Type["Rule"]] = []
_PROGRAM_REGISTRY: List[Type["ProgramRule"]] = []


class Rule:
    id: str = ""
    severity: str = "warning"
    title: str = ""
    whole_program = False

    def check_file(self, ctx) -> Iterable[Finding]:
        return ()

    def finish(self) -> Iterable[Finding]:
        return ()


class ProgramRule(Rule):
    """Cross-file rule over the whole-program PackageIndex."""

    whole_program = True

    def check_program(self, index) -> Iterable[Finding]:
        return ()


def register(cls: Type[Rule]) -> Type[Rule]:
    _REGISTRY.append(cls)
    return cls


def register_program(cls: Type[ProgramRule]) -> Type[ProgramRule]:
    _PROGRAM_REGISTRY.append(cls)
    return cls


def make_rules() -> List[Rule]:
    # importing the rule modules populates the registry
    from . import conc_rules, jax_rules, proto_rules  # noqa: F401

    return [cls() for cls in _REGISTRY]


def make_program_rules() -> List[ProgramRule]:
    from ..wholeprogram import protocol_rules, structure_rules  # noqa: F401

    return [cls() for cls in _PROGRAM_REGISTRY]


def rule_catalog() -> List[dict]:
    from ..perf.rules import make_perf_rules

    return ([{"id": r.id, "severity": r.severity, "title": r.title,
              "whole_program": False} for r in make_rules()]
            + [{"id": r.id, "severity": r.severity, "title": r.title,
                "whole_program": True} for r in make_program_rules()]
            + [{"id": r.id, "severity": r.severity, "title": r.title,
                "whole_program": False, "perf": True}
               for r in make_perf_rules()])
