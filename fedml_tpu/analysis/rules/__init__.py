"""Rule registry.  A per-file rule sees every file once (``check_file``) and
may emit more findings after the whole scan (``finish``, for cross-file rules
like PROTO001).  A PROGRAM rule (``ProgramRule``) instead queries the
package index built by ``analysis.wholeprogram`` and only runs under
``fedml lint --whole-program``.  ``make_rules``/``make_program_rules`` build
FRESH instances per run — rules are allowed to accumulate state."""

from __future__ import annotations

from typing import Iterable, List, Type

from ..findings import Finding

_REGISTRY: List[Type["Rule"]] = []
_PROGRAM_REGISTRY: List[Type["ProgramRule"]] = []


class Rule:
    id: str = ""
    severity: str = "warning"
    title: str = ""
    whole_program = False

    def check_file(self, ctx) -> Iterable[Finding]:
        return ()

    def finish(self) -> Iterable[Finding]:
        return ()


class ProgramRule(Rule):
    """Cross-file rule over the whole-program PackageIndex."""

    whole_program = True

    def check_program(self, index) -> Iterable[Finding]:
        return ()


def register(cls: Type[Rule]) -> Type[Rule]:
    _REGISTRY.append(cls)
    return cls


def register_program(cls: Type[ProgramRule]) -> Type[ProgramRule]:
    _PROGRAM_REGISTRY.append(cls)
    return cls


def make_rules() -> List[Rule]:
    # importing the rule modules populates the registry
    from . import conc_rules, jax_rules, proto_rules  # noqa: F401

    return [cls() for cls in _REGISTRY]


def make_program_rules() -> List[ProgramRule]:
    from ..wholeprogram import protocol_rules, structure_rules  # noqa: F401

    return [cls() for cls in _PROGRAM_REGISTRY]


def rule_catalog() -> List[dict]:
    """Every rule of every tier (``fedml lint --list-rules`` renders
    this).  ``tier`` ∈ file|program|perf|mesh|conc|taint; the
    pass-failure channels (PERF000/SHARD000/CONC000/PRIV000) are listed
    with their tier."""
    from ..conc import conc_catalog
    from ..mesh.rules import make_mesh_rules
    from ..perf.rules import make_perf_rules
    from ..taint import taint_catalog

    cat = ([{"id": r.id, "severity": r.severity, "title": r.title,
             "whole_program": False, "tier": "file"}
            for r in make_rules()]
           + [{"id": r.id, "severity": r.severity, "title": r.title,
               "whole_program": True, "tier": "program"}
              for r in make_program_rules()]
           + [{"id": r.id, "severity": r.severity, "title": r.title,
               "whole_program": False, "perf": True, "tier": "perf"}
              for r in make_perf_rules()]
           + [{"id": "PERF000", "severity": "error",
               "title": "perf pass could not trace an entrypoint",
               "whole_program": False, "perf": True, "tier": "perf"}]
           + [{"id": r.id, "severity": r.severity, "title": r.title,
               "whole_program": False, "mesh": True, "tier": "mesh"}
              for r in make_mesh_rules()]
           + [{"id": "SHARD000", "severity": "error",
               "title": "mesh pass could not lower an entrypoint",
               "whole_program": False, "mesh": True, "tier": "mesh"}]
           + [{"id": c["id"], "severity": c["severity"],
               "title": c["title"], "whole_program": True,
               "conc": True, "tier": "conc", "reads": c["reads"]}
              for c in conc_catalog()]
           + [{"id": c["id"], "severity": c["severity"],
               "title": c["title"], "whole_program": True,
               "taint": True, "tier": "taint", "reads": c["reads"]}
              for c in taint_catalog()])
    return cat
