"""Rule registry.  A rule sees every file once (``check_file``) and may emit
more findings after the whole scan (``finish``, for cross-file rules like
PROTO001).  ``make_rules`` builds FRESH instances per run — rules are allowed
to accumulate state across files."""

from __future__ import annotations

from typing import Iterable, List, Type

from ..findings import Finding

_REGISTRY: List[Type["Rule"]] = []


class Rule:
    id: str = ""
    severity: str = "warning"
    title: str = ""

    def check_file(self, ctx) -> Iterable[Finding]:
        return ()

    def finish(self) -> Iterable[Finding]:
        return ()


def register(cls: Type[Rule]) -> Type[Rule]:
    _REGISTRY.append(cls)
    return cls


def make_rules() -> List[Rule]:
    # importing the rule modules populates the registry
    from . import conc_rules, jax_rules, proto_rules  # noqa: F401

    return [cls() for cls in _REGISTRY]


def rule_catalog() -> List[dict]:
    return [{"id": r.id, "severity": r.severity, "title": r.title}
            for r in make_rules()]
