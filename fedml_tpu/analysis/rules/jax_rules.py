"""JAX discipline rules.

JAX001 — jit/pjit wrapped inside a loop or round-scoped function: every
         call re-traces and re-compiles, the classic silent multi-hour
         degradation (wrap once at init, call many times).
JAX002 — a PRNG key consumed by ≥2 calls (or across loop iterations)
         without an intervening split/fold_in: correlated randomness.
JAX003 — host-device sync (.item()/float()/np.asarray/block_until_ready)
         inside a loop on a trainer/engine hot path: stalls the dispatch
         pipeline every iteration.
JAX004 — static_argnums positions fed non-hashable literals, and donated
         buffers referenced after the donating call.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .. import astutil
from ..findings import SEV_ERROR, SEV_WARNING, Finding
from . import Rule, register

JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
KEY_SOURCES = {"jax.random.PRNGKey", "jax.random.key"}
KEY_DERIVERS = {"jax.random.split", "jax.random.fold_in"}


def _resolved(call: ast.Call, ctx) -> str:
    return astutil.call_name(call, ctx.aliases)


def _is_jit(name: str) -> bool:
    return name in JIT_NAMES or name.endswith(".pjit.pjit")


def _scopes(tree: ast.AST):
    """Yield (scope_node, body) for the module and every function."""
    yield tree, tree.body
    for node in ast.walk(tree):
        if isinstance(node, astutil.FUNC_NODES):
            yield node, node.body


def _walk_scope(body, loop_stack: Tuple[int, ...] = (),
                branch_stack: Tuple[Tuple[int, int], ...] = ()):
    """Yield (stmt, loop_stack, branch_stack) for one scope, entering loop
    bodies but NOT nested function/class/lambda scopes.  ``branch_stack``
    carries (if_or_try_id, branch_index) so callers can tell that two
    statements live on mutually exclusive paths."""
    for stmt in body:
        yield stmt, loop_stack, branch_stack
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            inner = loop_stack + (id(stmt),)
            yield from _walk_scope(stmt.body, inner, branch_stack)
            yield from _walk_scope(stmt.orelse, loop_stack, branch_stack)
        elif isinstance(stmt, ast.If):
            yield from _walk_scope(stmt.body, loop_stack,
                                   branch_stack + ((id(stmt), 0),))
            yield from _walk_scope(stmt.orelse, loop_stack,
                                   branch_stack + ((id(stmt), 1),))
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            yield from _walk_scope(stmt.body, loop_stack, branch_stack)
        elif isinstance(stmt, ast.Try):
            # try-body vs handlers count as exclusive: the handler path is
            # a RETRY of the body, and re-consuming the same key there is a
            # deliberate replay, not correlated randomness
            for part in (stmt.body, stmt.orelse):
                yield from _walk_scope(part, loop_stack,
                                       branch_stack + ((id(stmt), 0),))
            for i, h in enumerate(stmt.handlers):
                yield from _walk_scope(h.body, loop_stack,
                                       branch_stack + ((id(stmt), 1 + i),))
            yield from _walk_scope(stmt.finalbody, loop_stack, branch_stack)


def _stmt_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """Expressions OWNED by this statement: compound statements contribute
    only their header (iter/test/with-items) — their bodies are walked as
    separate statements by ``_walk_scope``, so scanning the whole subtree
    here would double-count every call."""
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.While, ast.If)):
        return [stmt.test]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, (ast.Try, ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [stmt]


def _calls_in(node: ast.AST) -> Iterable[ast.Call]:
    """Calls inside one statement's own expressions, not nested defs."""
    for expr in _stmt_exprs(node) if isinstance(node, ast.stmt) else [node]:
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and n is not expr:
                continue  # different scope — do not descend
            if isinstance(n, ast.Call):
                yield n
            stack.extend(ast.iter_child_nodes(n))


@register
class Jax001RecompileInLoop(Rule):
    id = "JAX001"
    severity = SEV_WARNING
    title = "jit/pjit wrapped inside a loop or per-round function"

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and _is_jit(_resolved(node, ctx))):
                continue
            loop = astutil.enclosing_loop(node, ctx.parents)
            if loop is not None:
                out.append(Finding(
                    self.id, self.severity, ctx.path, node.lineno,
                    node.col_offset,
                    "jit/pjit called inside a loop — every iteration "
                    "re-traces and re-compiles; wrap once outside"))
                continue
            fn = astutil.enclosing_function(node, ctx.parents)
            # builder/factory functions (build_*, make_*, create_*) wrap
            # once by design — only flag handler-style per-round functions
            if fn is not None and "round" in fn.name.lower() \
                    and not fn.name.lstrip("_").startswith(
                        ("build", "make", "create", "init")):
                out.append(Finding(
                    self.id, self.severity, ctx.path, node.lineno,
                    node.col_offset,
                    f"jit/pjit wrapped inside per-round function "
                    f"'{fn.name}' — recompiles every round; hoist to init"))
        return out


@register
class Jax002KeyReuse(Rule):
    id = "JAX002"
    severity = SEV_ERROR
    title = "PRNG key reused without split/fold_in"

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        for _scope, body in _scopes(ctx.tree):
            out.extend(self._check_scope(body, ctx))
        return out

    # -- event model: defs (PRNGKey / split results) + consuming uses -------
    def _events(self, body, ctx):
        events = []  # (lineno, col, kind, name, loop_stack, branch_stack)
        for stmt, loops, branches in _walk_scope(body):
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                value = stmt.value
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                names: List[str] = []
                for t in targets:
                    if isinstance(t, ast.Name):
                        names.append(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        names.extend(e.id for e in t.elts
                                     if isinstance(e, ast.Name))
                if value is not None and names:
                    kinds = {_resolved(c, ctx) for c in _calls_in(value)}
                    if kinds & KEY_DERIVERS:
                        for n in names:
                            events.append((stmt.lineno, stmt.col_offset,
                                           "def_split", n, loops, branches))
                        continue
                    if kinds & KEY_SOURCES:
                        for n in names:
                            events.append((stmt.lineno, stmt.col_offset,
                                           "def_key", n, loops, branches))
                        continue
            for call in _calls_in(stmt):
                name = _resolved(call, ctx)
                if name in KEY_DERIVERS or name in KEY_SOURCES:
                    continue
                args = list(call.args) + [kw.value for kw in call.keywords]
                for a in args:
                    if isinstance(a, ast.Name):
                        events.append((a.lineno, a.col_offset, "consume",
                                       a.id, loops, branches))
        events.sort(key=lambda e: (e[0], e[1]))
        return events

    @staticmethod
    def _exclusive(p1, p2) -> bool:
        """True when two branch paths can never both execute."""
        forks1 = dict(p1)
        return any(sid in forks1 and forks1[sid] != idx for sid, idx in p2)

    def _check_scope(self, body, ctx) -> Iterable[Finding]:
        events = self._events(body, ctx)
        resplit_loops: Dict[str, Set[int]] = {}
        for _, _, kind, name, loops, _branches in events:
            if kind == "def_split":
                resplit_loops.setdefault(name, set()).update(loops)
        keys: Dict[str, Dict] = {}
        out: List[Finding] = []
        flagged_loops: Set[Tuple[str, int]] = set()
        for lineno, col, kind, name, loops, branches in events:
            if kind in ("def_key", "def_split"):
                keys[name] = {"consumed": [], "def_loops": set(loops)}
                continue
            info = keys.get(name)
            if info is None:
                continue
            # consumptions on mutually exclusive branches don't compound
            if not info.get("flagged") \
                    and any(not self._exclusive(branches, prev)
                            for prev in info["consumed"]):
                info["flagged"] = True
                out.append(Finding(
                    self.id, self.severity, ctx.path, lineno, col,
                    f"PRNG key '{name}' consumed by more than one call "
                    f"without an intervening jax.random.split — "
                    f"correlated randomness"))
            info["consumed"].append(branches)
            for loop_id in loops:
                if (loop_id not in info["def_loops"]
                        and loop_id not in resplit_loops.get(name, ())
                        and (name, loop_id) not in flagged_loops):
                    flagged_loops.add((name, loop_id))
                    out.append(Finding(
                        self.id, self.severity, ctx.path, lineno, col,
                        f"PRNG key '{name}' defined outside the loop is "
                        f"consumed every iteration without being split — "
                        f"identical randomness each pass"))
        return out


#: trainer/engine hot paths where a per-iteration host sync stalls the
#: device dispatch pipeline.  One-shot modules (weight_import, mesh
#: construction) stay out — a sync at init time is not a hazard.
HOT_PATH_PREFIXES = ("fedml_tpu/ml/trainer/",)
HOT_PATH_FILES = ("fedml_tpu/serving/llm_engine.py",
                  "fedml_tpu/train/llm/trainer.py")

SYNC_FREE_FUNCS = {"float", "int", "bool"}
SYNC_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get",
              "jax.block_until_ready"}


@register
class Jax003HostSyncInHotLoop(Rule):
    id = "JAX003"
    severity = SEV_WARNING
    title = "host-device sync inside a hot-path loop"

    def _applies(self, path: str) -> bool:
        return path.startswith(HOT_PATH_PREFIXES) or path in HOT_PATH_FILES

    def check_file(self, ctx) -> Iterable[Finding]:
        if not self._applies(ctx.path):
            return ()
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            what = self._sync_kind(node, ctx)
            if what is None:
                continue
            if astutil.enclosing_loop(node, ctx.parents) is None:
                continue
            out.append(Finding(
                self.id, self.severity, ctx.path, node.lineno,
                node.col_offset,
                f"{what} inside a hot-path loop forces a host-device "
                f"sync every iteration — hoist it after the loop "
                f"(device_get once) or record via the metrics plane"))
        return out

    def _sync_kind(self, call: ast.Call, ctx) -> Optional[str]:
        name = _resolved(call, ctx)
        if name in SYNC_CALLS:
            return f"{name}()"
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in ("item", "block_until_ready") \
                and not call.args:
            return f".{call.func.attr}()"
        if name in SYNC_FREE_FUNCS and call.args \
                and not isinstance(call.args[0], ast.Constant):
            return f"{name}() on a device value"
        return None


@register
class Jax004StaticDonateHazards(Rule):
    id = "JAX004"
    severity = SEV_ERROR
    title = "non-hashable static arg / donated buffer reused"

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        for _scope, body in _scopes(ctx.tree):
            out.extend(self._check_scope(body, ctx))
        return out

    @staticmethod
    def _int_positions(node) -> List[int]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)):
            return [e.value for e in node.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)]
        return []

    def _check_scope(self, body, ctx) -> Iterable[Finding]:
        jitted: Dict[str, Dict[str, List[int]]] = {}
        donated: List[Tuple[str, int, str]] = []  # (var, call line, fn name)
        out: List[Finding] = []
        for stmt, _loops, _branches in _walk_scope(body):
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Call) \
                    and _is_jit(_resolved(stmt.value, ctx)) \
                    and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                cfg = {"static": [], "donate": []}
                for kw in stmt.value.keywords:
                    if kw.arg == "static_argnums":
                        cfg["static"] = self._int_positions(kw.value)
                    elif kw.arg == "donate_argnums":
                        cfg["donate"] = self._int_positions(kw.value)
                if cfg["static"] or cfg["donate"]:
                    jitted[stmt.targets[0].id] = cfg
                continue
            rebound: Set[str] = set()
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        rebound.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        rebound.update(e.id for e in t.elts
                                       if isinstance(e, ast.Name))
            for call in _calls_in(stmt):
                if isinstance(call.func, ast.Name) \
                        and call.func.id in jitted:
                    cfg = jitted[call.func.id]
                    for pos in cfg["static"]:
                        if pos < len(call.args) and isinstance(
                                call.args[pos],
                                (ast.List, ast.Dict, ast.Set)):
                            out.append(Finding(
                                self.id, self.severity, ctx.path,
                                call.lineno, call.col_offset,
                                f"argument {pos} of '{call.func.id}' is "
                                f"static_argnums but receives a "
                                f"non-hashable literal — TypeError at "
                                f"trace time"))
                    for pos in cfg["donate"]:
                        if pos < len(call.args) and isinstance(
                                call.args[pos], ast.Name) \
                                and call.args[pos].id not in rebound:
                            donated.append((call.args[pos].id, call.lineno,
                                            call.func.id))
            for expr in _stmt_exprs(stmt):
                for node in ast.walk(expr):
                    if not (isinstance(node, ast.Name)
                            and isinstance(node.ctx, ast.Load)):
                        continue
                    for var, line, fn in donated:
                        if node.id == var and node.lineno > line:
                            out.append(Finding(
                                self.id, self.severity, ctx.path,
                                node.lineno, node.col_offset,
                                f"'{var}' was donated to '{fn}' (donate_"
                                f"argnums) and is used after the call — "
                                f"its buffer is invalid"))
                            donated.remove((var, line, fn))
                            break
        return out
