"""CONC001 — unlocked shared-state mutation in thread-spawning modules.

Scope is the threaded control plane (scheduler/, serving/, and the
cross-silo runner): in a module that starts ``threading.Thread`` /
``threading.Timer``, an IN-PLACE mutation of shared state (``self.x[k]=v``,
``self.items.append(…)``, ``count += 1`` on a module global) that is not
lexically inside a ``with <lock>:`` block is a data-race candidate.  Plain
attribute rebinds are deliberately not flagged (atomic under the GIL and
idiomatic for status flags); container mutation is where corruption lives.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from .. import astutil
from ..findings import SEV_WARNING, Finding
from . import Rule, register

TARGET_PREFIXES = ("fedml_tpu/scheduler/", "fedml_tpu/serving/")
TARGET_FILES = ("fedml_tpu/cross_silo/runner.py",)

THREAD_CTORS = {"threading.Thread", "threading.Timer"}
LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition",
              "threading.Semaphore", "threading.BoundedSemaphore"}
MUTATORS = {"append", "extend", "insert", "add", "update", "pop", "popitem",
            "remove", "discard", "clear", "setdefault", "appendleft"}
MUTABLE_LITERALS = (ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp,
                    ast.SetComp)
MUTABLE_CTORS = {"dict", "list", "set", "collections.defaultdict",
                 "collections.deque", "collections.OrderedDict",
                 "collections.Counter"}


def _applies(path: str) -> bool:
    return path.startswith(TARGET_PREFIXES) or path in TARGET_FILES


@register
class Conc001UnlockedSharedMutation(Rule):
    id = "CONC001"
    severity = SEV_WARNING
    title = "shared state mutated without a lock in a threaded module"

    def check_file(self, ctx) -> Iterable[Finding]:
        if not _applies(ctx.path):
            return ()
        if not any(isinstance(n, ast.Call)
                   and astutil.call_name(n, ctx.aliases) in THREAD_CTORS
                   for n in ast.walk(ctx.tree)):
            return ()
        lock_names = self._lock_names(ctx)
        globals_ = self._module_mutables(ctx)
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            target = self._mutation_target(node, globals_, ctx)
            if target is None:
                continue
            fn = astutil.enclosing_function(node, ctx.parents)
            if fn is None or fn.name in ("__init__", "__new__"):
                continue
            if self._lock_held(node, ctx, lock_names):
                continue
            out.append(Finding(
                self.id, self.severity, ctx.path, node.lineno,
                node.col_offset,
                f"'{target}' is mutated in-place in a module that spawns "
                f"threads, outside any 'with <lock>:' block — wrap the "
                f"mutation in the owning lock or confine it to one thread"))
        return out

    # -- what counts as shared state ----------------------------------------
    def _module_mutables(self, ctx) -> Set[str]:
        names: Set[str] = set()
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                v = stmt.value
                if isinstance(v, MUTABLE_LITERALS) or (
                        isinstance(v, ast.Call)
                        and astutil.call_name(v, ctx.aliases)
                        in MUTABLE_CTORS):
                    names.add(stmt.targets[0].id)
        return names

    def _lock_names(self, ctx) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and astutil.call_name(node.value, ctx.aliases) \
                    in LOCK_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.add(t.attr)
        return names

    # -- mutation detection ---------------------------------------------------
    @staticmethod
    def _shared_base(expr, globals_: Set[str]) -> str:
        """'self.x' / tracked module global behind an expression, or ''."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return f"self.{expr.attr}"
        if isinstance(expr, ast.Name) and expr.id in globals_:
            return expr.id
        return ""

    def _mutation_target(self, node, globals_: Set[str], ctx):
        if isinstance(node, ast.AugAssign):
            base = self._shared_base(node.target, globals_)
            if base:
                return base
            if isinstance(node.target, ast.Subscript):
                return self._shared_base(node.target.value, globals_) or None
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    base = self._shared_base(t.value, globals_)
                    if base:
                        return base
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATORS:
            base = self._shared_base(node.func.value, globals_)
            if base:
                return f"{base}.{node.func.attr}()"
        return None

    def _lock_held(self, node, ctx, lock_names: Set[str]) -> bool:
        for anc in astutil.ancestors(node, ctx.parents):
            if isinstance(anc, astutil.FUNC_NODES):
                return False
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    name = astutil.dotted_name(item.context_expr)
                    if not name and isinstance(item.context_expr, ast.Call):
                        name = astutil.dotted_name(item.context_expr.func)
                    last = name.rsplit(".", 1)[-1] if name else ""
                    lowered = name.lower()
                    if last in lock_names or "lock" in lowered \
                            or "mutex" in lowered or "cond" in lowered:
                        return True
        return False
