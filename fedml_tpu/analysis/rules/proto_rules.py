"""PROTO001 — message-key drift across the federation protocol.

The wire contract lives in ``*message_define*.py`` constant classes
(MSG_TYPE_* / MSG_ARG_KEY_* / ARG_*).  Sender and receiver agree only by
convention, so a key written by the server but never read by any client
(or vice versa) silently drops data.  This rule cross-checks every define
constant against actual call sites, aggregated by WIRE VALUE (two classes
may alias the same string — that's a legal shared contract):

* write sites: ``msg.add_params(KEY, …)`` / ``msg.add(KEY, …)`` and
  ``Message(TYPE, …)`` construction
* read sites: ``msg.get(KEY…)`` and
  ``register_message_receive_handler(TYPE, …)``
* any other reference (stored in a variable, compared, forwarded) counts
  as BOTH — direction unknown, so only pure one-sided drift is flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from .. import astutil
from ..findings import SEV_WARNING, Finding
from . import Rule, register

CONST_PREFIXES = ("MSG_TYPE_", "MSG_ARG_KEY_", "ARG_")
WRITE_METHODS = {"add_params", "add"}
READ_METHODS = {"get"}
REGISTER_FUNCS = {"register_message_receive_handler"}


def _is_define_file(path: str) -> bool:
    return "message_define" in path.rsplit("/", 1)[-1]


@register
class Proto001KeyDrift(Rule):
    id = "PROTO001"
    severity = SEV_WARNING
    title = "protocol constant written but never read (or vice versa)"

    def __init__(self) -> None:
        # (class, const) -> (path, line, value)
        self.defines: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.writes: Set[Tuple[str, str]] = set()   # (class, const)
        self.reads: Set[Tuple[str, str]] = set()
        self.others: Set[Tuple[str, str]] = set()

    def check_file(self, ctx) -> Iterable[Finding]:
        if _is_define_file(ctx.path):
            self._collect_defines(ctx)
        self._collect_usage(ctx)
        return ()

    def _collect_defines(self, ctx) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and stmt.targets[0].id.startswith(CONST_PREFIXES)
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, str)):
                    key = (node.name, stmt.targets[0].id)
                    self.defines[key] = (ctx.path, stmt.lineno,
                                         stmt.value.value)

    @staticmethod
    def _const_ref(node) -> Tuple[str, str]:
        """(class, const) of a ``Cls.CONST`` reference, else ("", "")."""
        if (isinstance(node, ast.Attribute)
                and node.attr.startswith(CONST_PREFIXES)
                and isinstance(node.value, ast.Name)):
            return (node.value.id, node.attr)
        return ("", "")

    def _collect_usage(self, ctx) -> None:
        classified = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            ref = self._const_ref(node.args[0])
            if not ref[0]:
                continue
            fn = node.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if attr in WRITE_METHODS or attr == "Message":
                self.writes.add(ref)
                classified.add(id(node.args[0]))
            elif attr in READ_METHODS or attr in REGISTER_FUNCS:
                self.reads.add(ref)
                classified.add(id(node.args[0]))
        for node in ast.walk(ctx.tree):
            ref = self._const_ref(node)
            if ref[0] and id(node) not in classified \
                    and not _is_define_file(ctx.path):
                self.others.add(ref)

    def finish(self) -> Iterable[Finding]:
        # aggregate per wire value: a key written via MyMessage.X and read
        # via LSAMessage.Y with the same string is a consistent contract
        written: Set[str] = set()
        read: Set[str] = set()
        both: Set[str] = set()
        value_of = {k: v[2] for k, v in self.defines.items()}
        for ref in self.writes:
            written.add(value_of.get(ref, f"?{ref}"))
        for ref in self.reads:
            read.add(value_of.get(ref, f"?{ref}"))
        for ref in self.others:
            both.add(value_of.get(ref, f"?{ref}"))
        out: List[Finding] = []
        for (cls, const), (path, line, value) in sorted(
                self.defines.items(), key=lambda kv: (kv[1][0], kv[1][1])):
            is_type = const.startswith("MSG_TYPE_")
            w = value in written or value in both
            r = value in read or value in both
            if w and r:
                continue
            role_w = "sent" if is_type else "written by a sender"
            role_r = ("handled by a receiver" if is_type
                      else "read by any receiver")
            if w and not r:
                msg = (f"{cls}.{const} ({value!r}) is {role_w} but never "
                       f"{role_r} — the payload is silently dropped")
            elif r and not w:
                msg = (f"{cls}.{const} ({value!r}) is expected by a "
                       f"receiver but no sender ever emits it")
            else:
                msg = (f"{cls}.{const} ({value!r}) is defined but never "
                       f"used anywhere in the protocol")
            out.append(Finding(self.id, self.severity, path, line, 0, msg))
        return out
