"""Shared AST helpers for the lint rules: parent links, import-alias
resolution to canonical dotted names, and loop/function enclosure queries."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional

LOOP_NODES = (ast.For, ast.AsyncFor, ast.While)
COMPREHENSION_NODES = (ast.ListComp, ast.SetComp, ast.DictComp,
                       ast.GeneratorExp)
FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def build_parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def ancestors(node: ast.AST,
              parents: Dict[ast.AST, ast.AST]) -> Iterator[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Map local names to canonical dotted prefixes.

    ``import jax.numpy as jnp``      -> {"jnp": "jax.numpy"}
    ``from jax import jit``          -> {"jit": "jax.jit"}
    ``from jax import random as jr`` -> {"jr": "jax.random"}
    """
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_name(node: ast.AST,
                aliases: Optional[Dict[str, str]] = None) -> str:
    """Canonical dotted name of a Name/Attribute chain ("" if not one)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return ""
    parts.append(node.id)
    parts.reverse()
    if aliases and parts[0] in aliases:
        parts[0] = aliases[parts[0]]
    return ".".join(parts)


def call_name(node: ast.Call,
              aliases: Optional[Dict[str, str]] = None) -> str:
    return dotted_name(node.func, aliases)


def enclosing_function(node: ast.AST, parents: Dict[ast.AST, ast.AST]
                       ) -> Optional[ast.AST]:
    for a in ancestors(node, parents):
        if isinstance(a, FUNC_NODES):
            return a
    return None


def enclosing_loop(node: ast.AST, parents: Dict[ast.AST, ast.AST],
                   include_comprehensions: bool = True
                   ) -> Optional[ast.AST]:
    """Nearest loop around ``node`` within the same function scope."""
    for a in ancestors(node, parents):
        if isinstance(a, FUNC_NODES):
            return None
        if isinstance(a, LOOP_NODES):
            return a
        if include_comprehensions and isinstance(a, COMPREHENSION_NODES):
            return a
    return None
