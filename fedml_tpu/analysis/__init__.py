"""fedml lint — JAX-aware static analysis for the federated control plane.

Rule families (docs/STATIC_ANALYSIS.md has the full catalog):

* JAX001-JAX004 — recompilation, PRNG-key reuse, host-sync-in-hot-loop and
  static/donate hazards that tests don't catch until a long run degrades
* PROTO001      — sender/receiver drift across message_define contracts
* CONC001       — unlocked shared-state mutation in threaded modules
* whole-program pass (``--whole-program``, ``analysis.wholeprogram``):
  PROTO002 orphan sends/handlers across every manager pair, FLOW001
  protocol liveness over the send/handle FSM, SHARD001 PartitionSpec/mesh
  contracts, RES001 thread + receive-loop lifecycle; ``--graph dot|json``
  exports the send/handle graph
* perf tier (``--perf``, ``analysis.perf``): traces REGISTERED jit
  entrypoints (ShapeDtypeStruct specs, no data) and lints their IR —
  PERF001 donation audit, PERF002 bf16→f32 widening, PERF003
  padding-waste in the size-bucket policy, PERF004 layout-changing
  transposes in scan bodies, PERF005 host callbacks inside jit
* mesh tier (``--mesh``, ``analysis.mesh``): lowers registered
  entrypoints SPMD-partitioned per declared mesh variant (forced
  8-device CPU host platform) and lints the compiled HLO — SHARD002
  boundary resharding, SHARD003 idle-axis replication, SHARD004
  collective budget ratchet, SHARD005 cross-host loop all-gathers,
  SHARD006 donation lost to sharding mismatch
* conc tier (``--conc``, ``analysis.conc``): whole-program concurrency
  analysis of the threaded control plane — CONC002 guarded-field
  lockset inference, CONC003 lock-order DAG ratchet
  (``benchmarks/lock_order.json``), CONC004 blocking-call-under-lock,
  CONC005 condition-variable misuse, CONC006 timeout-less shutdown
  waits; the runtime counterpart (``core.mlops.lock_profiler``) checks
  observed acquisition order against the same committed DAG

Entry points: ``run_lint`` (library), ``run_cli`` (the `fedml lint`
command body; exit codes 0 = clean, 1 = new findings, 2 = internal error).
"""

from __future__ import annotations

import json
import sys
import traceback
from pathlib import Path
from typing import Optional, Sequence

from .baseline import (
    DEFAULT_BASELINE_NAME,
    load_baseline,
    partition,
    write_baseline,
)
from .engine import LintResult, default_root, run_lint
from .findings import Finding, fingerprints
from .rules import rule_catalog

__all__ = ["run_lint", "run_cli", "Finding", "LintResult", "rule_catalog",
           "render_rule_list", "DEFAULT_BASELINE_NAME"]

EXIT_CLEAN = 0
EXIT_NEW_FINDINGS = 1
EXIT_INTERNAL_ERROR = 2

#: tier → (human label, enabling flag, doc anchor) for --list-rules
TIER_DOCS = {
    "file": ("per-file AST", "(default)",
             "docs/STATIC_ANALYSIS.md#rule-catalog"),
    "program": ("whole-program", "--whole-program",
                "docs/STATIC_ANALYSIS.md#whole-program-pass"),
    "perf": ("perf-IR", "--perf",
             "docs/STATIC_ANALYSIS.md#perf-tier"),
    "mesh": ("mesh-HLO", "--mesh",
             "docs/STATIC_ANALYSIS.md#mesh-tier"),
    "conc": ("concurrency", "--conc",
             "docs/STATIC_ANALYSIS.md#concurrency-tier"),
    "taint": ("privacy-taint", "--taint",
              "docs/STATIC_ANALYSIS.md#privacy-taint-tier"),
}


def render_rule_list(fmt: str = "text") -> str:
    """The six-tier rule catalog behind ``fedml lint --list-rules``."""
    cat = rule_catalog()
    if fmt == "json":
        by_tier: dict = {}
        for entry in cat:
            tier = entry.get("tier", "file")
            label, flag, doc = TIER_DOCS[tier]
            by_tier.setdefault(tier, {
                "tier": tier, "label": label, "flag": flag, "doc": doc,
                "rules": []})["rules"].append(
                {k: v for k, v in entry.items() if k != "tier"})
        return json.dumps(
            {"version": 1, "tool": "fedml-lint",
             "tiers": [by_tier[t] for t in TIER_DOCS if t in by_tier]},
            indent=2)
    lines = []
    for tier, (label, flag, doc) in TIER_DOCS.items():
        rules = [e for e in cat if e.get("tier", "file") == tier]
        if not rules:
            continue
        lines.append(f"{label} tier  [{flag}]  — {doc}")
        for e in rules:
            lines.append(f"  {e['id']:<10}{e['severity']:<9}{e['title']}")
        lines.append("")
    return "\n".join(lines).rstrip()


def run_cli(root: Optional[str] = None,
            paths: Optional[Sequence[str]] = None,
            fmt: str = "text",
            baseline: Optional[str] = None,
            update_baseline: bool = False,
            rule_ids: Optional[Sequence[str]] = None,
            whole_program: bool = False,
            perf: bool = False,
            mesh: bool = False,
            conc: bool = False,
            taint: bool = False,
            perf_registry=None,
            graph: Optional[str] = None,
            list_rules: bool = False,
            sarif: Optional[str] = None,
            echo=print) -> int:
    """Body of ``fedml lint``; returns the process exit code."""
    try:
        if list_rules:
            echo(render_rule_list("json" if fmt == "json" else "text"))
            return EXIT_CLEAN
        if graph:
            if graph not in ("dot", "json"):
                echo(f"fedml lint: unknown --graph format {graph!r} "
                     f"(want dot or json)")
                return EXIT_INTERNAL_ERROR
            if update_baseline or rule_ids or fmt != "text":
                # silently ignoring these would e.g. skip a requested
                # baseline rewrite — make the contract explicit
                echo("fedml lint: --graph cannot be combined with "
                     "--update-baseline/--rules/--format (use --graph "
                     "json for machine-readable output)")
                return EXIT_INTERNAL_ERROR
            from .engine import collect_files
            from .wholeprogram import build_graph, filter_graph, \
                index_package, to_dot, to_json
            root_p = Path(root) if root else default_root()
            # the graph is only truthful over the WHOLE package — a subset
            # index would misresolve every contract; --paths narrows what
            # is DISPLAYED, not what is analyzed
            g = build_graph(index_package(root_p))
            if paths:
                # a typo'd --paths must not silently render an empty
                # digraph (same guard as the lint scan) — raises here
                collect_files(root_p, paths)
                g = filter_graph(g, paths)
            echo(to_dot(g) if graph == "dot" else to_json(g))
            return EXIT_CLEAN
        if update_baseline and (paths or rule_ids):
            # a partial scan would REPLACE the whole baseline, deleting
            # every entry outside the scanned subset
            echo("fedml lint: refusing --update-baseline with --paths/"
                 "--rules — the baseline must come from a full scan")
            return EXIT_INTERNAL_ERROR
        if update_baseline:
            # the baseline file is SHARED by the per-file, whole-program,
            # perf, mesh, conc and taint CI gates; rewriting it from a
            # partial scan would drop every baselined entry of the
            # skipped tiers, so always take the fullest scan when
            # rewriting
            whole_program = True
            perf = True
            mesh = True
            conc = True
            taint = True
        root_p = Path(root) if root else default_root()
        result = run_lint(root_p, paths=paths or None, rule_ids=rule_ids,
                          whole_program=whole_program, perf=perf,
                          mesh=mesh, conc=conc, taint=taint,
                          perf_registry=perf_registry)
        baseline_p = (Path(baseline) if baseline
                      else root_p / DEFAULT_BASELINE_NAME)
        if update_baseline:
            # "hint:" notes are advisory (e.g. the conc tier's missing/
            # stale lock-order DAG — its findings are still complete);
            # every other note means a pass was skipped or truncated
            blocking = [n for n in result.notes
                        if not n.startswith("hint:")]
            if blocking:
                # a skipped cross-file pass would rewrite the SHARED
                # baseline without its cross-file entries — refuse rather
                # than silently weaken it
                for note in result.notes:
                    echo(f"fedml lint: note: {note}")
                echo("fedml lint: refusing --update-baseline — the scan "
                     "was incomplete; fix the parse errors first")
                return EXIT_INTERNAL_ERROR
            for note in result.notes:
                echo(f"fedml lint: note: {note}")
            n = write_baseline(baseline_p, result.findings)
            echo(f"fedml lint: baseline written to {baseline_p} "
                 f"({n} findings)")
            return EXIT_CLEAN
        known = load_baseline(baseline_p) if baseline_p.is_file() else {}
        new, old = partition(result.findings, known)
        if sarif:
            from .sarif import write_sarif

            n = write_sarif(Path(sarif), new, old)
            echo(f"fedml lint: SARIF report written to {sarif} "
                 f"({n} results)")
        if fmt == "json":
            echo(json.dumps(_json_report(result, new, old), indent=2))
        else:
            for f, _fp in new:
                echo(f.render())
            for note in result.notes:
                echo(f"fedml lint: note: {note}")
            echo(f"fedml lint: {result.files_scanned} files, "
                 f"{len(new)} new finding(s), {len(old)} baselined, "
                 f"{result.suppressed} suppressed "
                 f"({result.duration_s:.1f}s)")
        return EXIT_NEW_FINDINGS if new else EXIT_CLEAN
    except Exception:
        traceback.print_exc(file=sys.stderr)
        return EXIT_INTERNAL_ERROR


def _json_report(result: LintResult, new, old) -> dict:
    findings = (
        [dict(f.to_dict(), fingerprint=fp, baselined=False)
         for f, fp in new]
        + [dict(f.to_dict(), fingerprint=fp, baselined=True)
           for f, fp in old])
    findings.sort(key=lambda d: (d["path"], d["line"], d["col"]))
    return {
        "version": 1,
        "tool": "fedml-lint",
        "files_scanned": result.files_scanned,
        "duration_s": round(result.duration_s, 3),
        "new_count": len(new),
        "baselined_count": len(old),
        "suppressed_count": result.suppressed,
        "notes": list(result.notes),
        "findings": findings,
    }
