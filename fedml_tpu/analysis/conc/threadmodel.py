"""Thread/lock model for the concurrency tier.

Everything the CONC002-CONC006 rules reason about is derived here, from
the whole-program ``PackageIndex`` plus one extra AST walk per class:

* which attributes are SYNCHRONIZATION objects — locks, conditions,
  events, queues, threads — recognized by constructor
  (``threading.Lock()``, ``queue.Queue()``, …) and by the runtime
  profiler's factories (``named_lock("Cls._lock")``, whose string
  literal then becomes the lock's canonical id in both planes);
* which methods are THREAD ROOTS — handed to
  ``threading.Thread(target=…)`` / ``threading.Timer`` /
  ``executor.submit(…)``, or a comm receive loop
  (``handle_receive_message`` runs on the transport's listener thread)
  — so "shared" can mean *reachable from ≥2 roots*, not merely "the
  module has a lock";
* every ``self.<field>`` access with its lexical ``with <lock>:``
  context, for lockset inference;
* every lock ACQUISITION with its nesting, and the call-mediated
  acquisitions one ``self.m()`` / typed-attribute hop away, for the
  lock-order graph.

Like the rest of the analysis plane this never imports the code under
analysis — stdlib ``ast`` only.

Known, deliberate approximations (documented in
docs/STATIC_ANALYSIS.md): ``lock.acquire()/release()`` pairs outside a
``with`` are not tracked; cross-class edges resolve only through
attributes whose class is visible from a ``self.x = ClassName(…)``
assignment; module-level lock-order edges come from lexical nesting
only.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .. import astutil
from ..wholeprogram.index import ClassInfo, MethodInfo, PackageIndex

#: constructor dotted name → sync kind
SYNC_CTORS: Dict[str, str] = {
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "multiprocessing.Lock": "lock",
    "threading.Condition": "condition",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "threading.Event": "event",
    "multiprocessing.Event": "event",
    "queue.Queue": "queue",
    "queue.SimpleQueue": "queue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
    "threading.Thread": "thread",
    "threading.Timer": "thread",
}

#: the runtime profiler's factories (tail-name match so both
#: ``named_lock(…)`` and ``lock_profiler.named_lock(…)`` resolve)
FACTORY_TAILS: Dict[str, str] = {
    "named_lock": "lock",
    "named_rlock": "lock",
}

#: method names that are thread entry points by convention: the comm
#: managers' receive loop runs on the transport's listener thread
CONVENTION_ROOTS = ("handle_receive_message",)

#: methods whose closure forms the shutdown path (CONC006)
SHUTDOWN_ROOTS = ("stop", "finish", "close", "shutdown", "terminate",
                  "stop_receive_message", "__exit__", "__del__")


def _ctor_kind(call: ast.Call, aliases: Dict[str, str]
               ) -> Tuple[Optional[str], Optional[str]]:
    """(sync kind, explicit name literal) of a constructor call."""
    name = astutil.call_name(call, aliases)
    kind = SYNC_CTORS.get(name)
    if kind is None:
        tail = name.rsplit(".", 1)[-1] if name else ""
        kind = FACTORY_TAILS.get(tail)
        if kind is None:
            return None, None
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return kind, call.args[0].value
    return kind, None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _timeout_given(call: ast.Call) -> bool:
    """Does a ``.join()/.get()/.wait()/.result()`` call bound itself?"""
    if call.args:
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


@dataclasses.dataclass
class FieldAccess:
    field: str
    method: str
    lineno: int
    col: int
    store: bool
    lock: Optional[str]          # innermost held self-lock ATTR, or None


@dataclasses.dataclass
class Acquisition:
    """One ``with self.<attr>:`` (or module-lock) site."""
    lock_id: str
    method: str
    lineno: int
    node: ast.With


@dataclasses.dataclass
class Edge:
    src: str                     # lock id held
    dst: str                     # lock id acquired while src held
    path: str
    lineno: int
    via: str                     # "Cls.method" of the outer acquisition


@dataclasses.dataclass
class ClassConc:
    name: str
    path: str
    info: ClassInfo
    sync: Dict[str, str] = dataclasses.field(default_factory=dict)
    lock_names: Dict[str, str] = dataclasses.field(default_factory=dict)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    thread_roots: Set[str] = dataclasses.field(default_factory=set)
    field_accesses: Dict[str, List[FieldAccess]] = dataclasses.field(
        default_factory=dict)
    acquisitions: List[Acquisition] = dataclasses.field(
        default_factory=list)
    #: method → lock ids acquired anywhere in its body
    method_locks: Dict[str, Set[str]] = dataclasses.field(
        default_factory=dict)
    calls: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)

    # -- sync-attr views -----------------------------------------------------
    def attrs_of(self, kind: str) -> Set[str]:
        return {a for a, k in self.sync.items() if k == kind}

    @property
    def lock_attrs(self) -> Set[str]:
        return self.attrs_of("lock")

    def lock_id(self, attr: str) -> str:
        """Canonical id: the ``named_lock`` literal when one was given,
        else ``ClassName.attr`` (the same string the factory convention
        asks callers to pass, so the planes agree by construction)."""
        return self.lock_names.get(attr) or f"{self.name}.{attr}"

    # -- reachability --------------------------------------------------------
    def closure(self, roots) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.info.methods]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            stack.extend(c for c in self.calls.get(m, ())
                         if c in self.info.methods and c not in seen)
        return seen

    def thread_closure(self) -> Dict[str, Set[str]]:
        return {r: self.closure({r}) for r in sorted(self.thread_roots)}

    def init_only_methods(self) -> Set[str]:
        """Methods reachable ONLY from ``__init__`` — they run before
        any thread this class spawns exists, so their unguarded accesses
        are not races."""
        init = self.closure({"__init__"})
        others = self.closure(set(self.info.methods) - {"__init__"})
        return (init - others) | {"__init__"}

    def shutdown_closure(self) -> Dict[str, str]:
        """method → the shutdown root it is reachable from."""
        out: Dict[str, str] = {}
        for root in self.info.methods:
            if root not in SHUTDOWN_ROOTS \
                    and not root.startswith(("stop_", "shutdown_")):
                continue
            for m in self.closure({root}):
                out.setdefault(m, root)
        return out


@dataclasses.dataclass
class ModuleConc:
    path: str
    basename: str
    locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    lock_names: Dict[str, str] = dataclasses.field(default_factory=dict)

    def lock_id(self, name: str) -> str:
        return self.lock_names.get(name) or f"{self.basename}.{name}"


@dataclasses.dataclass
class ConcModel:
    classes: List[ClassConc] = dataclasses.field(default_factory=list)
    modules: Dict[str, ModuleConc] = dataclasses.field(default_factory=dict)
    contexts_by_path: Dict[str, object] = dataclasses.field(
        default_factory=dict)
    edges: List[Edge] = dataclasses.field(default_factory=list)
    #: class name → ClassConc (for typed-attribute edge resolution)
    by_name: Dict[str, ClassConc] = dataclasses.field(default_factory=dict)


# -- builders -----------------------------------------------------------------

def _scan_sync_attrs(cls: ClassConc, aliases: Dict[str, str],
                     class_names: Set[str]) -> None:
    for mi in cls.info.methods.values():
        for node in ast.walk(mi.node):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            for t in node.targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                kind, literal = _ctor_kind(node.value, aliases)
                if kind is not None:
                    cls.sync[attr] = kind
                    if literal:
                        cls.lock_names[attr] = literal
                    continue
                ctor = astutil.call_name(node.value, aliases)
                tail = ctor.rsplit(".", 1)[-1] if ctor else ""
                if tail in class_names:
                    cls.attr_types[attr] = tail


def _scan_thread_roots(cls: ClassConc, aliases: Dict[str, str]) -> None:
    for mi in cls.info.methods.values():
        for node in ast.walk(mi.node):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node, aliases)
            target: Optional[ast.AST] = None
            if name in ("threading.Thread", "threading.Timer"):
                for kw in node.keywords:
                    if kw.arg in ("target", "function"):
                        target = kw.value
                if target is None and name == "threading.Timer" \
                        and len(node.args) >= 2:
                    target = node.args[1]
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "submit" and node.args:
                target = node.args[0]
            attr = _self_attr(target) if target is not None else None
            if attr is not None and attr in cls.info.methods:
                cls.thread_roots.add(attr)
    for conv in CONVENTION_ROOTS:
        if conv in cls.info.methods:
            cls.thread_roots.add(conv)


def _held_self_lock(node: ast.AST, parents, cls: ClassConc
                    ) -> Optional[str]:
    """Innermost enclosing ``with self.<lock-ish>:`` attr (lock or
    condition — holding a Condition means holding its lock)."""
    for anc in astutil.ancestors(node, parents):
        if isinstance(anc, astutil.FUNC_NODES):
            return None
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                attr = _self_attr(item.context_expr)
                if attr is not None \
                        and cls.sync.get(attr) in ("lock", "condition",
                                                   "semaphore"):
                    return attr
    return None


def _scan_field_accesses(cls: ClassConc, parents) -> None:
    for mname, mi in cls.info.methods.items():
        for node in ast.walk(mi.node):
            attr = _self_attr(node)
            if attr is None or attr in cls.sync \
                    or attr in cls.info.methods:
                continue
            store = isinstance(node.ctx, (ast.Store, ast.Del))
            cls.field_accesses.setdefault(attr, []).append(FieldAccess(
                attr, mname, node.lineno, node.col_offset, store,
                _held_self_lock(node, parents, cls)))


def _with_lock_ids(item_expr: ast.AST, cls: Optional[ClassConc],
                   mod: ModuleConc) -> Optional[str]:
    """Lock id acquired by one with-item, or None if not a known lock."""
    if cls is not None:
        attr = _self_attr(item_expr)
        if attr is not None and cls.sync.get(attr) in ("lock",
                                                       "condition"):
            return cls.lock_id(attr)
    if isinstance(item_expr, ast.Name) and item_expr.id in mod.locks:
        return mod.lock_id(item_expr.id)
    return None


def _scan_acquisitions(cls: ClassConc, mod: ModuleConc) -> None:
    for mname, mi in cls.info.methods.items():
        locks: Set[str] = set()
        for node in ast.walk(mi.node):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                lid = _with_lock_ids(item.context_expr, cls, mod)
                if lid is not None:
                    locks.add(lid)
                    cls.acquisitions.append(
                        Acquisition(lid, mname, node.lineno, node))
        cls.method_locks[mname] = locks


def _closure_locks(cls: ClassConc, method: str) -> Set[str]:
    return {lid for m in cls.closure({method})
            for lid in cls.method_locks.get(m, ())}


def _extract_class_edges(cls: ClassConc, mod: ModuleConc,
                         model: ConcModel) -> List[Edge]:
    """Lock-order edges rooted at this class's acquisitions: lexical
    nesting, same-class call-mediated acquisitions, and one typed-
    attribute hop (``with self._lock: self.store.put(…)`` reaches the
    locks of ``type(self.store)``'s ``put`` closure)."""
    edges: List[Edge] = []
    for acq in cls.acquisitions:
        via = f"{cls.name}.{acq.method}"

        def _emit(dst: str, lineno: int) -> None:
            if dst != acq.lock_id:
                edges.append(Edge(acq.lock_id, dst, cls.path, lineno, via))

        for node in ast.walk(acq.node):
            if node is acq.node:
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = _with_lock_ids(item.context_expr, cls, mod)
                    if lid is not None:
                        _emit(lid, node.lineno)
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            callee = node.func
            attr = _self_attr(callee.value)
            if attr is None:
                # self.<attr>.<m>(): one hop through a typed attribute
                inner = _self_attr(getattr(callee.value, "value", None)) \
                    if isinstance(callee.value, ast.Attribute) else None
                if inner is not None and inner in cls.attr_types:
                    target = model.by_name.get(cls.attr_types[inner])
                    if target is not None \
                            and callee.attr in target.info.methods:
                        for lid in _closure_locks(target, callee.attr):
                            _emit(lid, node.lineno)
                continue
            # self.m(): same-class call-mediated acquisition
            if callee.attr in cls.info.methods:
                for lid in _closure_locks(cls, callee.attr):
                    _emit(lid, node.lineno)
    return edges


def _extract_module_edges(mod: ModuleConc, ctx) -> List[Edge]:
    """Module-level lock nesting (lexical only)."""
    edges: List[Edge] = []
    if not mod.locks:
        return edges
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        outer = None
        for item in node.items:
            outer = _with_lock_ids(item.context_expr, None, mod) or outer
        if outer is None:
            continue
        for sub in ast.walk(node):
            if sub is node or not isinstance(sub, (ast.With,
                                                   ast.AsyncWith)):
                continue
            for item in sub.items:
                lid = _with_lock_ids(item.context_expr, None, mod)
                if lid is not None and lid != outer:
                    fn = astutil.enclosing_function(sub, ctx.parents)
                    via = f"{mod.basename}.{fn.name}" if fn is not None \
                        else mod.basename
                    edges.append(Edge(outer, lid, mod.path, sub.lineno,
                                      via))
    return edges


def build_model(index: PackageIndex, contexts) -> ConcModel:
    model = ConcModel(contexts_by_path={c.path: c for c in contexts})
    class_names = {c.name for m in index.modules.values()
                   for c in m.classes}
    for path, minfo in sorted(index.modules.items()):
        ctx = model.contexts_by_path.get(path)
        if ctx is None:
            continue
        parts = path.rsplit("/", 2)
        basename = parts[-1].removesuffix(".py")
        if basename == "__init__" and len(parts) > 1:
            # "pkg/__init__.py" locks read as 'pkg.<name>', not
            # '__init__.<name>' — every package would collide otherwise
            basename = parts[-2]
        mod = ModuleConc(path, basename)
        for stmt in getattr(ctx.tree, "body", []):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and isinstance(stmt.value, ast.Call):
                kind, literal = _ctor_kind(stmt.value, ctx.aliases)
                if kind in ("lock", "condition"):
                    mod.locks[stmt.targets[0].id] = kind
                    if literal:
                        mod.lock_names[stmt.targets[0].id] = literal
        model.modules[path] = mod
        for cinfo in minfo.classes:
            cls = ClassConc(cinfo.name, path, cinfo)
            cls.calls = {m: {c.func.attr for c in mi.self_calls}
                         for m, mi in cinfo.methods.items()}
            _scan_sync_attrs(cls, ctx.aliases, class_names)
            _scan_thread_roots(cls, ctx.aliases)
            _scan_field_accesses(cls, ctx.parents)
            _scan_acquisitions(cls, mod)
            model.classes.append(cls)
            # first definition wins — duplicate class names across
            # modules are rare and only feed the typed-attr hop
            model.by_name.setdefault(cls.name, cls)
    for cls in model.classes:
        model.edges.extend(
            _extract_class_edges(cls, model.modules[cls.path], model))
    for path, mod in model.modules.items():
        model.edges.extend(
            _extract_module_edges(mod, model.contexts_by_path[path]))
    return model


def dedup_edges(edges: List[Edge]) -> Dict[Tuple[str, str], List[Edge]]:
    out: Dict[Tuple[str, str], List[Edge]] = {}
    for e in edges:
        out.setdefault((e.src, e.dst), []).append(e)
    return out
