"""Concurrency lint tier (``fedml lint --conc``) — the fifth pass.

Whole-program concurrency analysis over the PR-3 package index: thread-
root discovery, per-class lockset inference (CONC002), lock-order graph
extraction with a committed-DAG ratchet (CONC003 /
``benchmarks/lock_order.json``), blocking-call-under-lock (CONC004),
condition-variable misuse (CONC005) and timeout-less shutdown-path
waits (CONC006).  CONC000 is the pass's own failure finding, so conc
coverage can never shrink silently — the same contract as
PERF000/SHARD000.

The pass shares the per-file engine's noqa / fingerprint / baseline /
exit-code machinery: ``run_conc_pass`` only produces findings; the
engine suppresses, partitions and reports them like any other tier.
The runtime counterpart — the opt-in lock profiler whose observed
acquisition edges the chaos soak checks against the SAME committed DAG
— lives in ``core/mlops/lock_profiler.py``.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from ..findings import SEV_ERROR, Finding

#: rule ids this pass can emit (CONC000 is the failure channel)
CONC_RULE_IDS = ("CONC002", "CONC003", "CONC004", "CONC005", "CONC006")


def conc_rule_ids() -> List[str]:
    return list(CONC_RULE_IDS)


def conc_catalog() -> List[dict]:
    from .rules import CATALOG

    return [{"id": rid, "severity": sev, "title": title, "reads": reads}
            for rid, sev, title, reads in CATALOG]


def run_conc_pass(root, rule_ids: Optional[Sequence[str]] = None
                  ) -> Tuple[List[Finding], List[str]]:
    """Run the conc tier over the WHOLE package rooted at ``root``.
    Returns (findings, notes); the engine handles noqa/subset/baseline.
    Never raises — a pass-level failure becomes a CONC000 finding."""
    notes: List[str] = []
    try:
        from ..engine import parse_contexts
        from ..wholeprogram import build_index
        from . import rules as _rules
        from .lockorder import committed_pairs
        from .threadmodel import build_model

        contexts, parse_errors = parse_contexts(Path(root), None)
        if parse_errors:
            # shared-state verdicts over a partial index would be
            # guesses — skip loudly, same policy as the whole-program
            # tier (the full scan's LINT001 fails the run anyway)
            notes.append(
                f"conc pass skipped: {len(parse_errors)} file(s) cannot "
                f"be parsed (see LINT001) — concurrency verdicts would "
                f"be guesses")
            return ([Finding(
                "CONC000", SEV_ERROR, rel,
                getattr(exc, "lineno", 1) or 1, 0,
                "conc pass skipped: file cannot be parsed")
                for rel, exc in parse_errors], notes)
        wanted = ({r.strip().upper() for r in rule_ids}
                  if rule_ids else None)
        model = build_model(build_index(contexts), contexts)
        findings: List[Finding] = []
        if wanted is None or "CONC002" in wanted:
            findings.extend(_rules.conc002(model))
        if wanted is None or "CONC003" in wanted:
            f3, n3 = _rules.conc003(model, committed_pairs(root))
            findings.extend(f3)
            notes.extend(n3)
        if wanted is None or "CONC004" in wanted:
            findings.extend(_rules.conc004(model))
        if wanted is None or "CONC005" in wanted:
            findings.extend(_rules.conc005(model))
        if wanted is None or "CONC006" in wanted:
            findings.extend(_rules.conc006(model))
        return findings, notes
    except Exception as exc:  # noqa: BLE001 — the pass must never take
        # down the whole lint run; CONC000 carries the failure instead
        notes.append(f"conc pass failed: {exc.__class__.__name__}: {exc}")
        return ([Finding(
            "CONC000", SEV_ERROR, "fedml_tpu", 1, 0,
            f"conc pass failed: {exc.__class__.__name__} — concurrency "
            f"coverage is OFF until this is fixed")], notes)
