"""CONC002-CONC006 — whole-program concurrency rules over the thread/lock
model (``threadmodel.build_model``).  Each rule is a pure function
``(model, …) -> findings``; the pass driver in ``conc/__init__`` applies
the rule-id filter and the engine applies noqa/baseline on top.

Messages are line-free (the fingerprint contract: a finding must survive
unrelated-line churn) and name the fix, not just the smell.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .. import astutil
from ..findings import SEV_ERROR, SEV_WARNING, Finding
from .threadmodel import ClassConc, ConcModel, dedup_edges

#: id, severity, title, one-line "what it reads" for --list-rules
CATALOG = [
    ("CONC000", SEV_ERROR, "concurrency pass could not run",
     "pass-level failure finding so conc coverage can never shrink "
     "silently"),
    ("CONC002", SEV_WARNING,
     "field guarded by a lock is also accessed without it",
     "per-class lockset inference over thread-reachable field accesses"),
    ("CONC003", SEV_WARNING,
     "lock-order edge is new or participates in a cycle",
     "acquisition-order graph from nested 'with' blocks, ratcheted "
     "against benchmarks/lock_order.json (cycles are errors)"),
    ("CONC004", SEV_WARNING, "blocking call while holding a lock",
     "file/sqlite/socket I/O, checkpoint saves, device syncs and "
     "unbounded waits lexically inside 'with <lock>:'"),
    ("CONC005", SEV_ERROR, "condition-variable misuse",
     "cond.wait() outside a while-predicate loop; notify without "
     "holding the condition"),
    ("CONC006", SEV_WARNING, "timeout-less blocking wait on a shutdown "
     "path",
     "join()/get()/wait()/result() without a timeout reachable from "
     "stop()/finish()/close()"),
]


# -- CONC002: lockset inference ----------------------------------------------

def conc002(model: ConcModel) -> List[Finding]:
    out: List[Finding] = []
    for cls in model.classes:
        lockish = {a for a in cls.sync
                   if cls.sync[a] in ("lock", "condition")}
        if not lockish or not cls.thread_roots:
            continue
        closures = cls.thread_closure()
        union_thread: Set[str] = set()
        for c in closures.values():
            union_thread |= c
        callers: Dict[str, Set[str]] = {}
        for m, callees in cls.calls.items():
            for c in callees:
                callers.setdefault(c, set()).add(m)
        exclusively_thread = {
            m for m in union_thread
            if callers.get(m, set()) <= union_thread}
        init_only = cls.init_only_methods()

        def _labels(method: str) -> Set[str]:
            labels = {f"thread:{r}" for r, c in closures.items()
                      if method in c}
            if method not in exclusively_thread:
                labels.add("main")
            return labels

        for field, accesses in sorted(cls.field_accesses.items()):
            # a field never STORED outside construction cannot race —
            # concurrent reads of init-time state are safe (self.rank,
            # config knobs); only runtime writes make a lock meaningful
            if not any(a.store and a.method not in init_only
                       for a in accesses):
                continue
            guarded = [a for a in accesses if a.lock in lockish]
            if len(guarded) < 2:
                continue
            by_lock: Dict[str, int] = {}
            for a in guarded:
                by_lock[a.lock] = by_lock.get(a.lock, 0) + 1
            dom = max(sorted(by_lock), key=lambda k: by_lock[k])
            if by_lock[dom] < 2:
                continue
            roots: Set[str] = set()
            for a in accesses:
                if a.method not in init_only:
                    roots |= _labels(a.method)
            if len(roots) < 2:
                continue
            unguarded = sorted(
                (a for a in accesses
                 if a.lock is None and a.method not in init_only),
                key=lambda a: (a.lineno, a.col))
            if not unguarded:
                continue
            first = unguarded[0]
            where = sorted({a.method for a in unguarded})
            out.append(Finding(
                "CONC002", SEV_WARNING, cls.path, first.lineno,
                first.col,
                f"'self.{field}' of {cls.name} is guarded by "
                f"'{cls.lock_id(dom)}' at {by_lock[dom]} site(s) but "
                f"accessed without it in {', '.join(where)} — the field "
                f"is reachable from {len(roots)} thread roots; take the "
                f"lock at every access or confine the field to one "
                f"thread"))
    return out


# -- CONC003: lock-order graph + ratchet -------------------------------------

def _cycles(edge_pairs: Set[Tuple[str, str]]) -> List[List[str]]:
    """Strongly-connected components with ≥2 nodes (or a self-loop) in
    the acquisition-order digraph — each is a potential deadlock."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edge_pairs:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(v: str) -> None:
        # iterative Tarjan (the graph is tiny, but recursion depth must
        # not depend on lock-chain length)
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1 or (node, node) in edge_pairs:
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def conc003(model: ConcModel,
            committed: Optional[Set[Tuple[str, str]]]
            ) -> Tuple[List[Finding], List[str]]:
    out: List[Finding] = []
    notes: List[str] = []
    edges = dedup_edges(model.edges)
    pairs = set(edges)
    cyclic_nodes: Set[str] = set()
    for comp in _cycles(pairs):
        cyclic_nodes |= set(comp)
        for (src, dst), sites in sorted(edges.items()):
            if src in comp and dst in comp:
                s = sites[0]
                out.append(Finding(
                    "CONC003", SEV_ERROR, s.path, s.lineno, 0,
                    f"lock-order cycle through "
                    f"{{{', '.join(comp)}}}: '{src}' is held while "
                    f"acquiring '{dst}' — two threads taking these "
                    f"locks in opposite order deadlock; impose one "
                    f"global order"))
    for (src, dst), sites in sorted(edges.items()):
        if src in cyclic_nodes and dst in cyclic_nodes:
            continue
        if committed is None or (src, dst) not in committed:
            s = sites[0]
            out.append(Finding(
                "CONC003", SEV_WARNING, s.path, s.lineno, 0,
                f"new lock-order edge '{src}' -> '{dst}' is not in the "
                f"committed DAG — review the nesting for deadlock "
                f"safety, then commit it with "
                f"`python -m fedml_tpu.analysis.conc.lockorder`"))
    if committed is None:
        # "hint:" notes are advisory — every edge still reports as a
        # finding, so the scan is complete and --update-baseline may
        # proceed (unlike a skipped pass, which must refuse)
        notes.append(
            "hint: conc: no committed lock-order DAG (benchmarks/"
            "lock_order.json) — every edge reports as new; generate it "
            "with `python -m fedml_tpu.analysis.conc.lockorder`")
    else:
        stale = sorted(committed - pairs)
        if stale:
            notes.append(
                f"hint: conc: {len(stale)} committed lock-order edge(s) no "
                f"longer observed ({', '.join(f'{a} -> {b}' for a, b in stale[:4])}"
                f"{', …' if len(stale) > 4 else ''}) — regenerate "
                f"benchmarks/lock_order.json to tighten the ratchet")
    return out, notes


# -- CONC004: blocking call under a lock -------------------------------------

#: attribute tails that block REGARDLESS of arguments
_ALWAYS_BLOCKING_TAILS = {"block_until_ready", "sendall", "makefile",
                         "wait_until_finished"}
#: attribute tails that block when called with NO timeout bound
_TIMEOUT_TAILS = {"join", "result", "get", "wait"}
#: sqlite-ish bases (the attr/name the call hangs off)
_DB_BASES = ("conn", "db", "cur", "cursor", "sql")
#: checkpoint-ish bases for .save/.restore
_CKPT_BASES = ("ckpt", "checkpoint", "mngr", "manager", "orbax", "saver")


def _base_tail(expr: ast.AST) -> str:
    """Last identifier of the expression a method call hangs off."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _blocking_desc(call: ast.Call, aliases: Dict[str, str],
                   cls: Optional[ClassConc]) -> Optional[str]:
    name = astutil.call_name(call, aliases)
    if name == "open":
        return "open() (file I/O)"
    if name == "time.sleep":
        return "time.sleep()"
    if name in ("jax.block_until_ready", "jax.device_get"):
        return f"{name}() (device sync)"
    if not isinstance(call.func, ast.Attribute):
        return None
    tail = call.func.attr
    base = _base_tail(call.func.value).lower()
    if tail in _ALWAYS_BLOCKING_TAILS:
        return f".{tail}()"
    if tail in ("execute", "executemany", "commit") \
            and any(b in base for b in _DB_BASES):
        return f".{tail}() (sqlite I/O)"
    if tail in ("save", "restore") \
            and any(b in base for b in _CKPT_BASES):
        return f".{tail}() (checkpoint I/O)"
    if tail in _TIMEOUT_TAILS and not call.args \
            and not any(kw.arg == "timeout" for kw in call.keywords):
        if tail == "get":
            # dict.get collides — only a QUEUE-typed self attr counts
            attr = None
            if isinstance(call.func.value, ast.Attribute):
                v = call.func.value
                if isinstance(v.value, ast.Name) and v.value.id == "self":
                    attr = v.attr
            if cls is None or attr is None \
                    or cls.sync.get(attr) != "queue":
                return None
            return ".get() without timeout (queue)"
        if tail == "wait" and cls is not None:
            attr = None
            v = call.func.value
            if isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                attr = v.attr
            if attr is not None and cls.sync.get(attr) == "condition":
                return None        # CONC005's territory
        return f".{tail}() without timeout"
    return None


def _io_kind(desc: str) -> Optional[str]:
    if "(sqlite I/O)" in desc:
        return "sqlite"
    if "(file I/O)" in desc:
        return "file"
    return None


def conc004(model: ConcModel) -> List[Finding]:
    # Collect candidates first: (lock_id, section_key, desc, finding).
    # A "section" is one critical region (one `with` / acquisition site);
    # per-lock section stats drive the dedicated-serializer exemption
    # below, and the seen-set collapses regions reached by both the
    # class-acquisition walk and the module-level walk (a method using a
    # MODULE lock is visible to both).
    cands: List[tuple] = []
    sections: Dict[str, set] = {}
    io_sections: Dict[tuple, set] = {}
    seen: set = set()

    def _walk_region(lock_id: str, path: str, region: ast.AST,
                     aliases, cls: Optional[ClassConc]) -> None:
        skey = (path, region.lineno, region.col_offset)
        sections.setdefault(lock_id, set()).add(skey)
        for node in ast.walk(region):
            if not isinstance(node, ast.Call):
                continue
            desc = _blocking_desc(node, aliases, cls)
            if desc is None:
                continue
            kind = _io_kind(desc)
            if kind:
                io_sections.setdefault((lock_id, kind), set()).add(skey)
            key = (lock_id, path, node.lineno, node.col_offset, desc)
            if key in seen:
                continue
            seen.add(key)
            cands.append((lock_id, kind, Finding(
                "CONC004", SEV_WARNING, path, node.lineno,
                node.col_offset,
                f"blocking call {desc} while holding '{lock_id}' — "
                f"every thread contending for the lock stalls behind "
                f"it; move the call outside the critical section or "
                f"bound it with a timeout")))

    for cls in model.classes:
        ctx = model.contexts_by_path[cls.path]
        for acq in cls.acquisitions:
            # a Condition used as a context manager is CONC005 territory
            # (wait/notify UNDER it are the point); plain locks only
            attr_kinds = {cls.sync.get(a) for a in cls.sync
                          if cls.lock_id(a) == acq.lock_id}
            if "condition" in attr_kinds:
                continue
            _walk_region(acq.lock_id, cls.path, acq.node, ctx.aliases, cls)
    # module-level 'with <lock>:' blocks (the ledger/metrics idiom)
    for path, mod in model.modules.items():
        if not mod.locks:
            continue
        ctx = model.contexts_by_path[path]
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lid = None
            for item in node.items:
                if isinstance(item.context_expr, ast.Name) \
                        and item.context_expr.id in mod.locks \
                        and mod.locks[item.context_expr.id] == "lock":
                    lid = mod.lock_id(item.context_expr.id)
            if lid is None:
                continue
            _walk_region(lid, path, node, ctx.aliases, None)
    # Dedicated-serializer exemption: when ≥60% of a lock's critical
    # sections (and at least 3 of them) perform the same kind of I/O,
    # the lock IS that resource's serializer — a sqlite connection or
    # append-only log isn't thread-safe, and the lock exists precisely
    # to order those calls.  Flagging every execute() under a dedicated
    # DB lock would just teach people to scatter noqa; the rule keeps
    # firing for the accidental case (an occasional blocking call under
    # a lock that mostly guards in-memory state).
    exempt: set = set()
    for (lock_id, kind), sect in io_sections.items():
        total = len(sections.get(lock_id, ()))
        if len(sect) >= 3 and total and len(sect) / total >= 0.6:
            exempt.add((lock_id, kind))
    return [f for lock_id, kind, f in cands
            if not (kind and (lock_id, kind) in exempt)]


# -- CONC005: condition-variable misuse --------------------------------------

def conc005(model: ConcModel) -> List[Finding]:
    out: List[Finding] = []
    for cls in model.classes:
        conds = cls.attrs_of("condition")
        if not conds:
            continue
        ctx = model.contexts_by_path[cls.path]
        for mi in cls.info.methods.values():
            for node in ast.walk(mi.node):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute):
                    continue
                v = node.func.value
                attr = None
                if isinstance(v, ast.Attribute) \
                        and isinstance(v.value, ast.Name) \
                        and v.value.id == "self":
                    attr = v.attr
                if attr not in conds:
                    continue
                cv = cls.lock_id(attr)
                if node.func.attr == "wait":
                    in_while = any(
                        isinstance(a, ast.While) for a in
                        _ancestors_in_func(node, ctx.parents))
                    if not in_while:
                        out.append(Finding(
                            "CONC005", SEV_ERROR, cls.path, node.lineno,
                            node.col_offset,
                            f"'{cv}.wait()' outside a while-predicate "
                            f"loop — spurious wakeups and missed "
                            f"notifies return with the predicate still "
                            f"false; use `while not pred: cv.wait()` or "
                            f"cv.wait_for(pred)"))
                elif node.func.attr in ("notify", "notify_all"):
                    holding = any(
                        isinstance(a, (ast.With, ast.AsyncWith))
                        and any(_self_attr_name(i.context_expr) == attr
                                for i in a.items)
                        for a in _ancestors_in_func(node, ctx.parents))
                    if not holding:
                        out.append(Finding(
                            "CONC005", SEV_ERROR, cls.path, node.lineno,
                            node.col_offset,
                            f"'{cv}.{node.func.attr}()' without holding "
                            f"the condition — the waiter can miss the "
                            f"wakeup; wrap in `with {cv.split('.')[-1]}:`"
                            ))
    return out


def _ancestors_in_func(node: ast.AST, parents):
    for a in astutil.ancestors(node, parents):
        if isinstance(a, astutil.FUNC_NODES):
            return
        yield a


def _self_attr_name(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


# -- CONC006: timeout-less blocking wait on a shutdown path ------------------

def conc006(model: ConcModel) -> List[Finding]:
    out: List[Finding] = []
    for cls in model.classes:
        shutdown = cls.shutdown_closure()
        if not shutdown:
            continue
        for mname, root in sorted(shutdown.items()):
            mi = cls.info.methods[mname]
            for node in ast.walk(mi.node):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute):
                    continue
                tail = node.func.attr
                if tail not in ("join", "get", "wait", "result"):
                    continue
                if node.args or any(kw.arg == "timeout"
                                    for kw in node.keywords):
                    continue
                if tail == "get":
                    attr = _self_attr_name(node.func.value)
                    if attr is None or cls.sync.get(attr) != "queue":
                        continue
                out.append(Finding(
                    "CONC006", SEV_WARNING, cls.path, node.lineno,
                    node.col_offset,
                    f"timeout-less '.{tail}()' in {cls.name}.{mname} on "
                    f"the shutdown path (reached from {root}()) — a "
                    f"wedged peer makes stop/finish hang forever; add a "
                    f"timeout or wake the waiter with a sentinel"))
    return out
