"""The committed lock-order DAG (CONC003's ratchet — the SHARD004 idiom).

``benchmarks/lock_order.json`` commits every statically-extracted
acquisition-order edge (lock B acquired while A is held).  The conc
pass compares what it just extracted against the file: a NEW edge is a
finding until a human reviews it for deadlock safety and commits it; a
cycle is always an error regardless of the file.  Regenerate after a
DELIBERATE locking change with::

    python -m fedml_tpu.analysis.conc.lockorder

which rewrites the file from the current source (the diff is the review
artifact — a lock-nesting change can never land silently).  The SAME
edge set is the runtime gate: the chaos soak asserts the edges the lock
profiler OBSERVED (``fedml conc report --check-dag``) are a subset of
this file, so a dynamic path that nests locks in an order the static
pass never saw fails CI instead of deadlocking in production.

Entries are keyed ``"A -> B"`` with a representative site (path only —
line numbers would churn the ratchet on every unrelated edit).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Tuple

ORDER_FILE = "benchmarks/lock_order.json"

_DOC = ("committed lock acquisition-order DAG: every 'held -> acquired' "
        "edge the conc pass extracts from nested 'with <lock>:' blocks "
        "(lexical + call-mediated).  CONC003 ratchets against this file "
        "and flags cycles as potential deadlocks; the runtime lock "
        "profiler's chaos soak asserts observed edges are a subset.  "
        "Regenerate deliberately with "
        "`python -m fedml_tpu.analysis.conc.lockorder`.")


def order_path(root) -> Path:
    return Path(root) / ORDER_FILE


def load_order(root) -> Optional[Dict[str, Any]]:
    """The committed entries, or None when the file is missing."""
    p = order_path(root)
    if not p.is_file():
        return None
    data = json.loads(p.read_text(encoding="utf-8"))
    return data.get("edges", {})


def committed_pairs(root) -> Optional[Set[Tuple[str, str]]]:
    entries = load_order(root)
    if entries is None:
        return None
    out: Set[Tuple[str, str]] = set()
    for key in entries:
        a, sep, b = key.partition(" -> ")
        if sep:
            out.add((a, b))
    return out


def write_order(root, edges: Dict[Tuple[str, str], List[Any]]) -> Path:
    """``edges`` — the conc model's deduped edge map
    ((src, dst) → [Edge, …])."""
    p = order_path(root)
    p.parent.mkdir(parents=True, exist_ok=True)
    entries = {
        f"{src} -> {dst}": {"site": sorted({e.path for e in sites})[0],
                            "via": sorted({e.via for e in sites})}
        for (src, dst), sites in edges.items()}
    payload = {"_doc": _DOC,
               "edges": {k: entries[k] for k in sorted(entries)}}
    p.write_text(json.dumps(payload, indent=2, sort_keys=False) + "\n",
                 encoding="utf-8")
    return p


def collect_edges(root) -> Dict[Tuple[str, str], List[Any]]:
    """Build the model over the whole package and return its deduped
    edge map — the generator behind the committed file."""
    from ..engine import parse_contexts
    from ..wholeprogram import build_index
    from .threadmodel import build_model, dedup_edges

    contexts, errors = parse_contexts(Path(root), None)
    if errors:
        raise RuntimeError(
            f"{len(errors)} file(s) cannot be parsed; fix them first "
            f"(the committed order must come from a full scan)")
    model = build_model(build_index(contexts), contexts)
    return dedup_edges(model.edges)


def main() -> int:
    from ..engine import default_root

    root = default_root()
    edges = collect_edges(root)
    p = write_order(root, edges)
    print(f"wrote {p} ({len(edges)} lock-order edges)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
