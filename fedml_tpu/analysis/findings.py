"""Finding model + stable fingerprints for the lint baseline ratchet.

A fingerprint deliberately excludes the line number: editing an unrelated
part of a file must not churn the committed baseline.  Findings that share
(rule, path, message) are disambiguated by occurrence index in file order,
so two identical violations in one file stay distinct entries.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, Iterable, List, Tuple

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"

_SEV_ORDER = {SEV_ERROR: 0, SEV_WARNING: 1, SEV_INFO: 2}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule_id: str
    severity: str
    path: str          # posix-style path relative to the lint root
    line: int          # 1-based
    col: int           # 0-based, matching ast
    message: str

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule_id, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message}

    def sort_key(self) -> Tuple:
        return (self.path, self.line, self.col,
                _SEV_ORDER.get(self.severity, 9), self.rule_id)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule_id} [{self.severity}] {self.message}")


def fingerprints(findings: Iterable[Finding]) -> List[Tuple[Finding, str]]:
    """Pair each finding with its stable fingerprint (occurrence-indexed)."""
    ordered = sorted(findings, key=Finding.sort_key)
    seen: Dict[Tuple[str, str, str], int] = {}
    out: List[Tuple[Finding, str]] = []
    for f in ordered:
        key = (f.rule_id, f.path, f.message)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        raw = f"{f.rule_id}::{f.path}::{f.message}::{idx}"
        out.append((f, hashlib.sha1(raw.encode()).hexdigest()[:16]))
    return out
