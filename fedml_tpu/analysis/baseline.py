"""Baseline ratchet: CI fails only on findings NOT in the committed
baseline, so the rule set can land on a brownfield codebase and tighten
over time (fix a finding → delete its entry → it can never come back)."""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Tuple

from .findings import Finding, fingerprints

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = ".fedml-lint-baseline.json"


def load_baseline(path: Path) -> Dict[str, dict]:
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"unsupported baseline version "
                         f"{data.get('version')!r} in {path}")
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(path: Path, findings: List[Finding]) -> int:
    entries = [{"fingerprint": fp, "rule": f.rule_id, "path": f.path,
                "message": f.message}
               for f, fp in fingerprints(findings)]
    payload = {"version": BASELINE_VERSION, "tool": "fedml-lint",
               "findings": entries}
    Path(path).write_text(json.dumps(payload, indent=1) + "\n",
                          encoding="utf-8")
    return len(entries)


def partition(findings: List[Finding], baseline: Dict[str, dict]
              ) -> Tuple[List[Tuple[Finding, str]], List[Tuple[Finding, str]]]:
    """Split into (new, baselined) keeping each finding's fingerprint."""
    new, known = [], []
    for f, fp in fingerprints(findings):
        (known if fp in baseline else new).append((f, fp))
    return new, known
