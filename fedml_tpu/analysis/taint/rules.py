"""PRIV rules: map engine hits (tainted value → sink) to findings.

Messages are line-free and name the FIX, not just the smell, so the
fingerprint survives unrelated edits and a finding reads as a work item.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..findings import SEV_ERROR, SEV_WARNING, Finding
from . import catalog as C
from .engine import Hit
from .wirecontract import WILDCARD_TYPE, flatten

CATALOG = [
    ("PRIV000", SEV_ERROR, "privacy-taint pass could not run",
     "pass-level failure finding so taint coverage can never shrink "
     "silently"),
    ("PRIV001", SEV_ERROR,
     "raw client example escapes to an emission sink",
     "interprocedural source→sink dataflow: dataset rows / per-client "
     "batches / label tensors reaching wire, log, metrics, ledger, "
     "trace, HTTP or checkpoint surfaces without a declassifier"),
    ("PRIV002", SEV_WARNING,
     "per-client identifier used as a metrics label value",
     "client-id taint into .labels(...) values — unbounded cardinality; "
     "the run ledger is the sanctioned per-client surface"),
    ("PRIV003", SEV_ERROR,
     "secret material escapes beyond the peer-share channel",
     "PRNG keys/seeds, self-mask seeds and Shamir/LCC shares reaching "
     "any sink except the sanctioned share-channel wire keys"),
    ("PRIV004", SEV_ERROR,
     "SecAgg bypass: unmasked update tree on the wire",
     "params taint reaching Message payloads inside the secagg/"
     "lightsecagg client roles without the mask funnel "
     "(mask_upload / mask_field_vector)"),
    ("PRIV005", SEV_WARNING,
     "tensor-payload repr in a wire-path log call",
     "params/tensor taint into log.* on distributed/cross_silo/serving "
     "paths — log summarize_payload(...) (shape/dtype/nbytes), never "
     "values"),
    ("PRIV006", SEV_WARNING,
     "wire payload key is not in the committed contract",
     "derived per-manager key set ratcheted against "
     "benchmarks/wire_contract.json; unresolvable keys always report"),
]


def _label(sink: str) -> str:
    return C.SINK_LABELS.get(sink, sink)


def _where(h: Hit) -> str:
    return f"{h.func}()" + (f" (via {h.via}())" if h.via else "")


def priv001(hits: List[Hit]) -> List[Finding]:
    out = []
    for h in hits:
        if C.EXAMPLE not in h.kinds:
            continue
        out.append(Finding(
            "PRIV001", SEV_ERROR, h.path, h.line, h.col,
            f"raw client example reaches the {_label(h.sink)} in "
            f"{_where(h)} — raw rows must never leave the client: "
            f"reduce through the local-epoch update "
            f"(trainer.train) or summarize with "
            f"utils.redact.summarize_payload before emission"))
    return out


def priv002(hits: List[Hit]) -> List[Finding]:
    out = []
    for h in hits:
        if h.sink != C.SINK_METRICS_LABEL or C.CLIENT_ID not in h.kinds:
            continue
        out.append(Finding(
            "PRIV002", SEV_WARNING, h.path, h.line, h.col,
            f"per-client identifier used as metrics label value "
            f"'{h.key}' in {_where(h)} — unbounded label cardinality; "
            f"record per-client detail on the run ledger "
            f"(core.mlops.ledger) and key metrics by bounded "
            f"run/silo/rank labels"))
    return out


def priv003(hits: List[Hit]) -> List[Finding]:
    out = []
    for h in hits:
        if C.SECRET not in h.kinds:
            continue
        if h.sink == C.SINK_WIRE and h.key in C.SHARE_CHANNEL_KEYS:
            continue   # the sanctioned Shamir/LCC peer-share channel
        out.append(Finding(
            "PRIV003", SEV_ERROR, h.path, h.line, h.col,
            f"secret material (PRNG seed/key or mask share) reaches "
            f"the {_label(h.sink)} in {_where(h)} — secrets travel "
            f"only on the peer-share wire keys "
            f"({', '.join(sorted(C.SHARE_CHANNEL_KEYS))}); emit a "
            f"digest or drop the value"))
    return out


def priv004(hits: List[Hit]) -> List[Finding]:
    out = []
    for h in hits:
        if h.sink != C.SINK_WIRE or C.PARAMS not in h.kinds:
            continue
        if not any(f in h.path for f in C.SECAGG_PATH_FRAGMENTS):
            continue
        if "client" not in h.owner_class.lower():
            continue   # the server broadcasts the AGGREGATE — sanctioned
        out.append(Finding(
            "PRIV004", SEV_ERROR, h.path, h.line, h.col,
            f"model update tree put on the wire without the SecAgg "
            f"mask funnel in {_where(h)} — an armed client may only "
            f"emit masked vectors; route the update through "
            f"mask_upload / mask_field_vector first"))
    return out


def priv005(hits: List[Hit]) -> List[Finding]:
    out = []
    for h in hits:
        if h.sink != C.SINK_LOG or C.PARAMS not in h.kinds:
            continue
        if C.EXAMPLE in h.kinds:
            continue   # PRIV001 already owns the stronger verdict
        if not h.path.startswith(C.WIRE_PATH_PREFIXES):
            continue
        out.append(Finding(
            "PRIV005", SEV_WARNING, h.path, h.line, h.col,
            f"tensor payload interpolated into a log call in "
            f"{_where(h)} — hot-path round logs ship off-device; log "
            f"utils.redact.summarize_payload(...) "
            f"(shape/dtype/nbytes), never values"))
    return out


def priv006(derived: Dict[str, Any],
            committed: Optional[Dict[str, Any]],
            sites) -> Tuple[List[Finding], List[str]]:
    """Ratchet the derived contract against the committed file.  New
    (owner, type, key) triple → finding; unresolvable key → finding
    always; committed triple no longer derivable → advisory note."""
    out: List[Finding] = []
    notes: List[str] = []
    have = flatten(committed) if committed is not None else set()
    want = flatten(derived)
    new = want - have
    site_index: Dict[Tuple[str, str, str], Tuple[str, int]] = {}
    for label, t, key, path, line in sites:
        site_index.setdefault((label, t, key), (path, line))
        if key == "?":
            out.append(Finding(
                "PRIV006", SEV_WARNING, path, line, 0,
                f"payload key of a {label} message cannot be resolved "
                f"to a wire value — an unreviewable wire surface; use "
                f"a message_define constant or a string literal"))
    for owner, t, key in sorted(new):
        path, line = site_index.get(
            (owner, t, key),
            ("fedml_tpu/core/distributed/communication", 1))
        shown = key if t == WILDCARD_TYPE else f"{key} [{t}]"
        out.append(Finding(
            "PRIV006", SEV_WARNING, path, line, 0,
            f"wire key '{shown}' of {owner} is not in the committed "
            f"contract — review the payload for data-minimization, "
            f"then commit it with "
            f"`python -m fedml_tpu.analysis.taint.wirecontract`"))
    if committed is None:
        notes.append(
            "hint: taint: no committed wire contract (benchmarks/"
            "wire_contract.json) — every key reports as new; generate "
            "it with `python -m fedml_tpu.analysis.taint.wirecontract`")
    else:
        stale = sorted(have - want)
        if stale:
            sample = ", ".join(
                f"{o}:{k}" for o, _t, k in stale[:4])
            notes.append(
                f"hint: taint: {len(stale)} committed wire-contract "
                f"entr{'y is' if len(stale) == 1 else 'ies are'} no "
                f"longer derived from source ({sample}) — regenerate "
                f"benchmarks/wire_contract.json to shrink the surface")
    return out, notes
