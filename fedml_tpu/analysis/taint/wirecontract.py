"""The committed wire contract (PRIV006's ratchet — the lock_order idiom).

``benchmarks/wire_contract.json`` commits, per comm-manager class and
per message type (by WIRE value), the set of payload keys that class is
allowed to put on the wire, plus the ``envelope`` section: keys the
transport planes (reliable delivery, the Message ctor itself) stamp on
EVERY message.  The taint pass derives the same structure from source
and compares: a NEW key is a finding until a human reviews the payload
for data-minimization and commits it; a key the pass cannot resolve is
always a finding (an unreviewable payload surface).  Regenerate after a
DELIBERATE protocol change with::

    python -m fedml_tpu.analysis.taint.wirecontract

which rewrites the file from the current source (the diff is the review
artifact — a new wire field can never land silently).  The SAME file is
the runtime gate: with ``FEDML_TPU_WIRE_AUDIT=1`` the comm-manager base
counts every OBSERVED outbound payload key outside this contract into
``fedml_wire_contract_violations_total`` and
``fedml taint report --check-contract`` fails the soak on any of them.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Tuple

from .. import astutil
from ..wholeprogram.index import resolve_type_expr

CONTRACT_FILE = "benchmarks/wire_contract.json"

#: transport planes whose keys ride on every message regardless of type
ENVELOPE_PATH_PREFIX = "fedml_tpu/core/distributed/communication/"

#: keys the Message constructor itself stamps
CTOR_KEYS = ("msg_type", "sender", "receiver")

_DOC = ("committed wire contract: per comm-manager class and message "
        "type (by wire value), the payload keys it may emit; 'envelope' "
        "keys are stamped by the transport planes on every message.  "
        "PRIV006 ratchets the static derivation against this file; the "
        "runtime wire audit (FEDML_TPU_WIRE_AUDIT=1) counts observed "
        "keys outside it into fedml_wire_contract_violations_total.  "
        "Regenerate deliberately with "
        "`python -m fedml_tpu.analysis.taint.wirecontract`.")

#: an add-site whose message variable cannot be traced to a typed ctor
WILDCARD_TYPE = "*"


def contract_path(root) -> Path:
    return Path(root) / CONTRACT_FILE


def load_contract(root) -> Optional[Dict[str, Any]]:
    """The committed contract, or None when the file is missing."""
    p = contract_path(root)
    if not p.is_file():
        return None
    data = json.loads(p.read_text(encoding="utf-8"))
    return {"envelope": data.get("envelope", []),
            "managers": data.get("managers", {})}


def legal_keys(contract: Dict[str, Any], manager: str,
               msg_type: Optional[str]) -> Set[str]:
    """The key set a runtime observation is checked against.  Unknown
    managers fall back to the union of every manager's keys — the audit
    must not false-positive on a subclass the static pass named
    differently, only on keys NO reviewed surface emits."""
    env = set(contract.get("envelope", ()))
    managers = contract.get("managers", {})
    if manager in managers:
        by_type = managers[manager]
        out = set(env)
        out.update(by_type.get(WILDCARD_TYPE, ()))
        if msg_type is not None:
            out.update(by_type.get(msg_type, ()))
        return out
    out = set(env)
    for by_type in managers.values():
        for keys in by_type.values():
            out.update(keys)
    return out


#: derivation site: (owner label, msg type or "*", key or "?", path, line)
Site = Tuple[str, str, str, str, int]


def _msg_types_for(recv: ast.AST, func_node: ast.AST, index, modinfo,
                   params) -> List[str]:
    """Resolve the receiver message variable to ctor wire type values;
    ``["*"]`` when the variable is a parameter / handler argument or the
    ctor type does not resolve."""
    if not isinstance(recv, ast.Name):
        return [WILDCARD_TYPE]
    name = recv.id
    types: Set[str] = set()
    for stmt in ast.walk(func_node):
        if not (isinstance(stmt, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in stmt.targets)):
            continue
        v = stmt.value
        if not isinstance(v, ast.Call):
            continue
        dn = astutil.dotted_name(v.func) or ""
        if dn.rsplit(".", 1)[-1] != "Message":
            continue
        type_node = None
        if v.args:
            type_node = v.args[0]
        else:
            for kw in v.keywords:
                if kw.arg == "type":
                    type_node = kw.value
        if type_node is not None:
            values, _syms = resolve_type_expr(
                type_node, index, modinfo, method_node=func_node,
                params=params)
            types |= values
    return sorted(types) if types else [WILDCARD_TYPE]


def collect_sites(contexts, index) -> List[Site]:
    """Every ``msg.add_params(key, value)`` / ``msg.add(key, value)``
    site in the package, with owner class, resolved message type(s) and
    resolved key wire value ("?" when unresolvable)."""
    sites: List[Site] = []
    for ctx in contexts:
        modinfo = index.modules.get(ctx.path)
        if modinfo is None:
            continue
        parents = ctx.parents
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and len(node.args) == 2):
                continue
            if node.func.attr != "add_params":
                if node.func.attr != "add":
                    continue
                rdn = astutil.dotted_name(node.func.value) or ""
                from .engine import _msgish
                if not _msgish(rdn.rsplit(".", 1)[-1]):
                    continue
            func_node = astutil.enclosing_function(node, parents)
            if func_node is None:
                continue
            owner = ""
            for anc in astutil.ancestors(node, parents):
                if isinstance(anc, ast.ClassDef):
                    owner = anc.name
                    break
            label = owner or f"{func_node.name}()"
            params = [a.arg for a in func_node.args.args]
            values, _syms = resolve_type_expr(
                node.args[0], index, modinfo, method_node=func_node,
                params=params)
            key = "|".join(sorted(values)) if values else "?"
            if "|" in key:
                key = "?"   # ambiguous resolution is unreviewable too
            for t in _msg_types_for(node.func.value, func_node, index,
                                    modinfo, params):
                sites.append((label, t, key, ctx.path, node.lineno))
    return sites


def derive_contract(contexts, index) -> Dict[str, Any]:
    """The contract structure the ratchet compares and ``main`` writes.
    Unresolvable keys ("?") are EXCLUDED — they are PRIV006 findings,
    never committable."""
    envelope: Set[str] = set(CTOR_KEYS)
    managers: Dict[str, Dict[str, Set[str]]] = {}
    for label, t, key, path, _line in collect_sites(contexts, index):
        if key == "?":
            continue
        if path.startswith(ENVELOPE_PATH_PREFIX):
            envelope.add(key)
        else:
            managers.setdefault(label, {}).setdefault(t, set()).add(key)
    return {
        "envelope": sorted(envelope),
        "managers": {m: {t: sorted(keys)
                         for t, keys in sorted(by_type.items())}
                     for m, by_type in sorted(managers.items())},
    }


def flatten(contract: Dict[str, Any]) -> Set[Tuple[str, str, str]]:
    """(owner, type, key) triples; envelope keys own the pseudo-owner
    ``envelope`` so the ratchet diff is one flat set."""
    out = {("envelope", WILDCARD_TYPE, k)
           for k in contract.get("envelope", ())}
    for m, by_type in contract.get("managers", {}).items():
        for t, keys in by_type.items():
            for k in keys:
                out.add((m, t, k))
    return out


def write_contract(root, contract: Dict[str, Any]) -> Path:
    p = contract_path(root)
    p.parent.mkdir(parents=True, exist_ok=True)
    payload = {"_doc": _DOC,
               "envelope": contract["envelope"],
               "managers": contract["managers"]}
    p.write_text(json.dumps(payload, indent=2, sort_keys=False) + "\n",
                 encoding="utf-8")
    return p


def main() -> int:
    from ..engine import default_root, parse_contexts
    from ..wholeprogram import build_index

    root = default_root()
    contexts, errors = parse_contexts(root, None)
    if errors:
        raise RuntimeError(
            f"{len(errors)} file(s) cannot be parsed; fix them first "
            f"(the committed contract must come from a full scan)")
    index = build_index(contexts)
    contract = derive_contract(contexts, index)
    p = write_contract(root, contract)
    n = len(flatten(contract))
    print(f"wrote {p} ({n} contract entries, "
          f"{len(contract['managers'])} managers)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
