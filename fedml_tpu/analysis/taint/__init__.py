"""Privacy-taint tier (``fedml lint --taint``) — the sixth lint tier.

Statically proves the data-minimization invariant of the federated
contract: raw client examples, per-client identifiers, PRNG/mask
secrets and (on SecAgg paths) unmasked update trees never reach an
emission surface — Message payloads, logs, metrics, the run ledger,
trace spans, HTTP responses, checkpoints — except through the declared
declassifier catalog (local-epoch training, wire codecs, aggregate
reductions, the SecAgg mask funnel).  The same pass derives the wire
contract (``benchmarks/wire_contract.json``) that PRIV006 ratchets and
the runtime wire audit (``core.mlops.wire_audit``) enforces.

Shares the engine's noqa/fingerprint/baseline machinery; a pass-level
failure is a PRIV000 finding, so taint coverage can never shrink
silently.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from ..findings import SEV_ERROR, Finding

TAINT_RULE_IDS = ("PRIV001", "PRIV002", "PRIV003", "PRIV004", "PRIV005",
                  "PRIV006")


def taint_rule_ids() -> List[str]:
    return list(TAINT_RULE_IDS)


def taint_catalog() -> List[dict]:
    from .rules import CATALOG

    return [{"id": rid, "severity": sev, "title": title, "reads": reads}
            for rid, sev, title, reads in CATALOG]


def run_taint_pass(root, rule_ids: Optional[Sequence[str]] = None
                   ) -> Tuple[List[Finding], List[str]]:
    """Run the taint tier over the WHOLE package rooted at ``root``.
    Returns (findings, notes); the engine handles noqa/subset/baseline.
    Never raises — a pass-level failure becomes a PRIV000 finding."""
    notes: List[str] = []
    try:
        from ..engine import parse_contexts
        from ..wholeprogram import build_index
        from . import rules as _rules
        from .engine import build_taint_model
        from .wirecontract import (
            collect_sites,
            derive_contract,
            load_contract,
        )

        contexts, parse_errors = parse_contexts(Path(root), None)
        if parse_errors:
            # dataflow over a partial package would miss flows through
            # the unparsed file — skip loudly, same policy as the
            # whole-program tier (the full scan's LINT001 fails anyway)
            notes.append(
                f"taint pass skipped: {len(parse_errors)} file(s) "
                f"cannot be parsed (see LINT001) — escape verdicts "
                f"would be guesses")
            return ([Finding(
                "PRIV000", SEV_ERROR, rel,
                getattr(exc, "lineno", 1) or 1, 0,
                "taint pass skipped: file cannot be parsed")
                for rel, exc in parse_errors], notes)
        wanted = ({r.strip().upper() for r in rule_ids}
                  if rule_ids else None)
        index = build_index(contexts)
        hits = build_taint_model(contexts, index)
        findings: List[Finding] = []
        if wanted is None or "PRIV001" in wanted:
            findings.extend(_rules.priv001(hits))
        if wanted is None or "PRIV002" in wanted:
            findings.extend(_rules.priv002(hits))
        if wanted is None or "PRIV003" in wanted:
            findings.extend(_rules.priv003(hits))
        if wanted is None or "PRIV004" in wanted:
            findings.extend(_rules.priv004(hits))
        if wanted is None or "PRIV005" in wanted:
            findings.extend(_rules.priv005(hits))
        if wanted is None or "PRIV006" in wanted:
            sites = collect_sites(contexts, index)
            derived = derive_contract(contexts, index)
            f6, n6 = _rules.priv006(derived, load_contract(root), sites)
            findings.extend(f6)
            notes.extend(n6)
        return findings, notes
    except Exception as exc:  # noqa: BLE001 — the pass must never take
        # down the whole lint run; PRIV000 carries the failure instead
        notes.append(f"taint pass failed: {exc.__class__.__name__}: "
                     f"{exc}")
        return ([Finding(
            "PRIV000", SEV_ERROR, "fedml_tpu", 1, 0,
            f"taint pass failed: {exc.__class__.__name__} — privacy "
            f"escape coverage is OFF until this is fixed")], notes)
