"""Interprocedural taint dataflow over the fedml_tpu package.

Per-function forward dataflow (variables → taint-kind sets) with

* name-pattern taint applied at USE time (catalog.NAME_PATTERNS), so a
  tainted NAME stays tainted through helpers the analysis cannot see,
* source calls (``population.rows(...)``, ``philox_generator(...)``),
* declassifier calls as the only cleansing operations,
* container/pytree propagation (dict/list/tuple/f-string/BinOp union),
* class-attribute flow (``self.x`` entries unioned across methods),
* one-level call-through: every function gets a summary (which params
  reach which sinks, what the return value carries); call sites bind
  argument taint against the callee summary.

Emission is a flat list of :class:`Hit` records — the rules module maps
hits to PRIV findings, the engine itself knows nothing about rule ids.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .. import astutil
from ..wholeprogram.index import PackageIndex, resolve_type_expr
from . import catalog as C

#: symbolic taint kind carried by a function parameter until a call site
#: binds it — ``param:batch`` in ``def helper(batch): log.info(batch)``
SYM_PREFIX = "param:"

_LOG_METHODS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception", "critical",
})
_LOG_RECEIVERS = frozenset({"log", "logger", "logging"})

_MSGISH = re.compile(r"msg|message|reply|request|ack")


def _msgish(recv_name: str) -> bool:
    """``.add(k, v)`` counts as a wire sink only on a message-looking
    receiver — ``acc.add(a, b)`` on a homomorphic codec is arithmetic."""
    return bool(_MSGISH.search(recv_name.lower()))


def real_kinds(kinds: FrozenSet[str]) -> FrozenSet[str]:
    return frozenset(k for k in kinds if not k.startswith(SYM_PREFIX))


@dataclasses.dataclass(frozen=True)
class Hit:
    """One tainted value reaching one sink."""
    sink: str                 # catalog.SINK_*
    kinds: FrozenSet[str]     # taint kinds (real + symbolic param:NAME)
    path: str
    line: int
    col: int
    func: str                 # qualname ("Cls.method" or "fn")
    owner_class: str          # "" for module-level functions
    key: str = ""             # wire key value / label name / attr name
    via: str = ""             # "" direct, else the callee a call-through
                              # walked into


@dataclasses.dataclass
class _FuncAnalysis:
    qualname: str
    params: List[str]
    return_kinds: Set[str] = dataclasses.field(default_factory=set)
    self_env: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    hits: List[Hit] = dataclasses.field(default_factory=list)
    #: (callee key, {param → real arg kinds}, line, col)
    callsites: List[Tuple[Tuple[str, str], Dict[str, FrozenSet[str]],
                          int, int]] = dataclasses.field(
        default_factory=list)


class _Walker:
    """One pass over one function body.  Monotone env (taint only grows);
    the driver runs the body twice env-only for loop-carried taint, then
    once with ``emit=True``."""

    def __init__(self, path: str, modinfo, index: PackageIndex,
                 func_node: ast.AST, qualname: str, owner_class: str,
                 env: Dict[str, Set[str]],
                 summaries: Dict[Tuple[str, str], "_FuncAnalysis"]):
        self.path = path
        self.modinfo = modinfo
        self.index = index
        self.node = func_node
        self.qualname = qualname
        self.owner_class = owner_class
        self.env = env
        self.summaries = summaries
        self.emit = False
        self.analysis = _FuncAnalysis(qualname, _param_names(func_node))

    # -- env ------------------------------------------------------------

    def _get(self, name: str) -> Set[str]:
        return set(self.env.get(name, ())) | set(C.name_kinds(name))

    def _bind(self, tgt: ast.AST, kinds: Set[str]) -> None:
        if not kinds:
            return
        if isinstance(tgt, ast.Name):
            self.env.setdefault(tgt.id, set()).update(kinds)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._bind(e, kinds)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, kinds)
        elif (isinstance(tgt, ast.Attribute)
              and isinstance(tgt.value, ast.Name)
              and tgt.value.id == "self"):
            self.env.setdefault("self." + tgt.attr, set()).update(kinds)
        elif isinstance(tgt, ast.Subscript):
            # d[k] = tainted → the container is tainted; unwrap the
            # subscript layers and re-bind the container target itself
            # (Name or self.attr), not its base object
            base = tgt.value
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, (ast.Name, ast.Attribute)):
                self._bind(base, kinds)

    # -- expressions -----------------------------------------------------

    def eval(self, node: Optional[ast.AST]) -> Set[str]:
        if node is None:
            return set()
        if isinstance(node, ast.Constant):
            return set()
        if isinstance(node, ast.Name):
            return self._get(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr in C.META_ATTRS:
                self.eval(node.value)
                return set()
            if (isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                return (set(self.env.get("self." + node.attr, ()))
                        | set(C.name_kinds(node.attr)))
            return self.eval(node.value) | set(C.name_kinds(node.attr))
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.BinOp):
            return self.eval(node.left) | self.eval(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand)
        if isinstance(node, ast.BoolOp):
            out: Set[str] = set()
            for v in node.values:
                out |= self.eval(v)
            return out
        if isinstance(node, ast.Compare):
            # a comparison yields a bool — declassified, but still walk
            # the operands for sink calls nested inside
            self.eval(node.left)
            for cmp in node.comparators:
                self.eval(cmp)
            return set()
        if isinstance(node, ast.Subscript):
            self.eval(node.slice)
            return self.eval(node.value)
        if isinstance(node, ast.Dict):
            out = set()
            for k in node.keys:
                out |= self.eval(k)
            for v in node.values:
                out |= self.eval(v)
            return out
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            out = set()
            for e in node.elts:
                out |= self.eval(e)
            return out
        if isinstance(node, ast.JoinedStr):
            out = set()
            for v in node.values:
                out |= self.eval(v)
            return out
        if isinstance(node, ast.FormattedValue):
            return self.eval(node.value)
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            return self.eval(node.body) | self.eval(node.orelse)
        if isinstance(node, ast.Lambda):
            return set()
        if isinstance(node, ast.Await):
            return self.eval(node.value)
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, ast.NamedExpr):
            kinds = self.eval(node.value)
            self._bind(node.target, kinds)
            return kinds
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            for gen in node.generators:
                self._bind(gen.target, self.eval(gen.iter))
                for cond in gen.ifs:
                    self.eval(cond)
            if isinstance(node, ast.DictComp):
                return self.eval(node.key) | self.eval(node.value)
            return self.eval(node.elt)
        # conservative fallback: union of child expressions
        out = set()
        for child in ast.iter_child_nodes(node):
            out |= self.eval(child)
        return out

    # -- calls (sources, sinks, declassifiers, call-through) -------------

    def _resolve_key(self, node: ast.AST) -> str:
        values, syms = resolve_type_expr(
            node, self.index, self.modinfo, method_node=self.node,
            params=self.analysis.params)
        if values:
            return "|".join(sorted(values))
        return "?"

    def _hit(self, sink: str, kinds: Set[str], node: ast.AST,
             key: str = "") -> None:
        if self.emit and kinds:
            self.analysis.hits.append(Hit(
                sink, frozenset(kinds), self.path, node.lineno,
                node.col_offset, self.qualname, self.owner_class, key))

    def _call(self, node: ast.Call) -> Set[str]:
        dn = astutil.dotted_name(node.func) or ""
        tail = dn.rsplit(".", 1)[-1] if dn else ""
        recv = (node.func.value
                if isinstance(node.func, ast.Attribute) else None)
        recv_name = ""
        if recv is not None:
            rdn = astutil.dotted_name(recv) or ""
            recv_name = rdn.rsplit(".", 1)[-1]
        recv_kinds = self.eval(recv) if recv is not None else set()
        arg_kinds = [self.eval(a) for a in node.args]
        kw_kinds = {kw.arg: self.eval(kw.value) for kw in node.keywords}
        all_args: Set[str] = set().union(*arg_kinds) if arg_kinds else set()
        for v in kw_kinds.values():
            all_args |= v

        # ---- sinks ----
        if len(node.args) == 2 and (
                tail == "add_params"
                or (tail == "add" and _msgish(recv_name))):
            key = self._resolve_key(node.args[0])
            self._hit(C.SINK_WIRE, arg_kinds[1], node, key)
            return set()
        if (tail in _LOG_METHODS
                and (recv_name in _LOG_RECEIVERS
                     or dn.startswith("logging."))):
            self._hit(C.SINK_LOG, all_args, node)
            return set()
        if tail == "labels" and node.keywords:
            for kw, kinds in kw_kinds.items():
                self._hit(C.SINK_METRICS_LABEL, kinds, node, kw or "")
            return set()
        if (tail in ("observe", "inc", "set", "dec") and node.args
                and recv is not None):
            self._hit(C.SINK_METRICS_VALUE, arg_kinds[0], node)
            return set()
        if tail == "event" and (recv_name == "ledger"
                                or "ledger" in dn.split(".")[:-1]):
            for kw, kinds in kw_kinds.items():
                self._hit(C.SINK_LEDGER, kinds, node, kw or "")
            self._hit(C.SINK_LEDGER, all_args - set().union(
                *kw_kinds.values()) if kw_kinds else all_args, node)
            return set()
        if tail == "span" and (len(node.args) >= 2 or "value" in kw_kinds):
            val = (arg_kinds[1] if len(node.args) >= 2
                   else kw_kinds.get("value", set()))
            self._hit(C.SINK_TRACE, val, node)
            return set()
        if tail in ("reply", "_json") and len(node.args) >= 2:
            self._hit(C.SINK_HTTP, arg_kinds[1], node)
            return set()
        if dn.endswith("wfile.write") and node.args:
            self._hit(C.SINK_HTTP, arg_kinds[0], node)
            return set()
        if tail == "save" and ("checkpoint" in recv_name.lower()
                               or "ckpt" in recv_name.lower()):
            self._hit(C.SINK_CHECKPOINT, all_args, node)
            return set()

        # ---- taint algebra ----
        if tail in C.SOURCE_CALLS:
            return {C.SOURCE_CALLS[tail]}
        if tail in C.TRANSFORMER_CALLS:
            return set(C.TRANSFORMER_CALLS[tail])
        if tail in C.DECLASSIFIER_CALLS:
            return set()
        if tail == "get" and len(node.args) >= 1 and recv is not None:
            # msg.get(ARG_MODEL_PARAMS) re-materializes a tensor payload
            key = self._resolve_key(node.args[0])
            if key in C.TENSOR_PAYLOAD_KEYS:
                return {C.PARAMS}
            return recv_kinds | all_args

        # local call-through: bind argument taint to the callee summary
        callee_key = None
        if isinstance(node.func, ast.Name):
            callee_key = (self.path, node.func.id)
        elif (recv is not None and isinstance(recv, ast.Name)
              and recv.id == "self" and self.owner_class):
            callee_key = (self.path, f"{self.owner_class}.{tail}")
        if callee_key is not None and callee_key in self.summaries:
            summ = self.summaries[callee_key]
            argmap: Dict[str, FrozenSet[str]] = {}
            for i, kinds in enumerate(arg_kinds):
                if i < len(summ.params):
                    rk = real_kinds(frozenset(kinds))
                    if rk:
                        argmap[summ.params[i]] = rk
            for kw, kinds in kw_kinds.items():
                rk = real_kinds(frozenset(kinds))
                if kw and rk and kw in summ.params:
                    argmap[kw] = rk
            if self.emit and argmap:
                self.analysis.callsites.append(
                    (callee_key, argmap, node.lineno, node.col_offset))
            out: Set[str] = set()
            for k in summ.return_kinds:
                if k.startswith(SYM_PREFIX):
                    out |= argmap.get(k[len(SYM_PREFIX):], frozenset())
                else:
                    out.add(k)
            return out

        # unknown call: conservative — taint in, taint out
        return recv_kinds | all_args

    # -- statements ------------------------------------------------------

    def walk(self, body: Sequence[ast.stmt]) -> None:
        for st in body:
            self._stmt(st)

    def _stmt(self, st: ast.stmt) -> None:
        if isinstance(st, ast.Assign):
            kinds = self.eval(st.value)
            for t in st.targets:
                self._bind(t, kinds)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._bind(st.target, self.eval(st.value))
        elif isinstance(st, ast.AugAssign):
            self._bind(st.target, self.eval(st.value))
        elif isinstance(st, ast.Expr):
            self.eval(st.value)
        elif isinstance(st, ast.Return):
            self.analysis.return_kinds |= self.eval(st.value)
        elif isinstance(st, ast.If):
            self.eval(st.test)
            self.walk(st.body)
            self.walk(st.orelse)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._bind(st.target, self.eval(st.iter))
            self.walk(st.body)
            self.walk(st.orelse)
        elif isinstance(st, ast.While):
            self.eval(st.test)
            self.walk(st.body)
            self.walk(st.orelse)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                kinds = self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, kinds)
            self.walk(st.body)
        elif isinstance(st, ast.Try):
            self.walk(st.body)
            for h in st.handlers:
                self.walk(h.body)
            self.walk(st.orelse)
            self.walk(st.finalbody)
        elif isinstance(st, ast.Raise):
            self.eval(st.exc)
            self.eval(st.cause)
        elif isinstance(st, ast.Assert):
            self.eval(st.test)
            self.eval(st.msg)
        elif isinstance(st, getattr(ast, "Match", ())):
            self.eval(st.subject)
            for case in st.cases:
                self.walk(case.body)
        # nested defs/classes analyzed as their own functions; imports,
        # pass/break/continue/global carry no dataflow

    def run(self, emit: bool) -> _FuncAnalysis:
        body = getattr(self.node, "body", [])
        self.emit = False
        self.walk(body)           # pass 1: seed env
        self.walk(body)           # pass 2: loop-carried taint
        self.emit = emit
        if emit:
            self.walk(body)       # pass 3: emission against the fixpoint
        # everything assigned to self.* is this function's contribution
        # to the class attribute environment
        self.analysis.self_env = {
            k: set(v) for k, v in self.env.items()
            if k.startswith("self.")}
        return self.analysis


def _param_names(node: ast.AST) -> List[str]:
    args = getattr(node, "args", None)
    if args is None:
        return []
    names = [a.arg for a in getattr(args, "posonlyargs", []) + args.args]
    if args.vararg:
        names.append(args.vararg.arg)
    names += [a.arg for a in args.kwonlyargs]
    if args.kwarg:
        names.append(args.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def _functions(tree: ast.AST):
    """(node, qualname, owner_class) for every top-level function and
    every method of every top-level class."""
    for st in tree.body:
        if isinstance(st, astutil.FUNC_NODES):
            yield st, st.name, ""
        elif isinstance(st, ast.ClassDef):
            for sub in st.body:
                if isinstance(sub, astutil.FUNC_NODES):
                    yield sub, f"{st.name}.{sub.name}", st.name


def _seed_env(node: ast.AST,
              class_env: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
    env: Dict[str, Set[str]] = {k: set(v) for k, v in class_env.items()}
    for p in _param_names(node):
        env.setdefault(p, set()).add(SYM_PREFIX + p)
    return env


def build_taint_model(contexts, index: PackageIndex) -> List[Hit]:
    """Full two-phase analysis; returns the deduplicated flat hit list
    (real-kind direct hits plus one-level call-through hits)."""
    funcs = []
    for ctx in contexts:
        modinfo = index.modules.get(ctx.path)
        if modinfo is None:
            continue
        for node, qualname, owner in _functions(ctx.tree):
            funcs.append((ctx, modinfo, node, qualname, owner))

    # phase 1: summaries (param names + return kinds + self-attr flow)
    summaries: Dict[Tuple[str, str], _FuncAnalysis] = {}
    for ctx, modinfo, node, qualname, owner in funcs:
        w = _Walker(ctx.path, modinfo, index, node, qualname, owner,
                    _seed_env(node, {}), summaries)
        summaries[(ctx.path, qualname)] = w.run(emit=False)

    # class attribute env: union of every method's self.* contributions
    class_envs: Dict[Tuple[str, str], Dict[str, Set[str]]] = {}
    for (path, qualname), a in summaries.items():
        if "." not in qualname:
            continue
        cls = qualname.split(".", 1)[0]
        env = class_envs.setdefault((path, cls), {})
        for k, v in a.self_env.items():
            env.setdefault(k, set()).update(real_kinds(frozenset(v)))

    # phase 2: emission with the class env seeded
    analyses: Dict[Tuple[str, str], _FuncAnalysis] = {}
    for ctx, modinfo, node, qualname, owner in funcs:
        env = _seed_env(node, class_envs.get((ctx.path, owner), {}))
        w = _Walker(ctx.path, modinfo, index, node, qualname, owner,
                    env, summaries)
        analyses[(ctx.path, qualname)] = w.run(emit=True)

    # phase 3: direct hits + one-level call-through
    hits: List[Hit] = []
    for (path, qualname), a in analyses.items():
        for h in a.hits:
            if real_kinds(h.kinds):
                hits.append(dataclasses.replace(
                    h, kinds=real_kinds(h.kinds)))
        for callee_key, argmap, line, col in a.callsites:
            callee = analyses.get(callee_key)
            if callee is None:
                continue
            for h in callee.hits:
                mapped: Set[str] = set()
                for k in h.kinds:
                    if k.startswith(SYM_PREFIX):
                        mapped |= argmap.get(k[len(SYM_PREFIX):],
                                             frozenset())
                if mapped:
                    hits.append(Hit(
                        h.sink, frozenset(mapped), path, line, col,
                        qualname, a.qualname.split(".", 1)[0]
                        if "." in a.qualname else "",
                        h.key, via=callee.qualname))
    seen = set()
    out = []
    for h in sorted(hits, key=lambda h: (h.path, h.line, h.col, h.sink,
                                         h.key, sorted(h.kinds))):
        sig = (h.sink, h.path, h.line, h.col, h.key, h.kinds)
        if sig not in seen:
            seen.add(sig)
            out.append(h)
    return out
