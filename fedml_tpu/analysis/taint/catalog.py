"""Source / sink / declassifier catalog for the privacy-taint tier.

The catalog is DATA, not code: name patterns that mark a value as
carrying client data, the emission surfaces that count as escapes, and
the sanctioned transformations that cleanse a flow.  Keeping it in one
module means the docs table (docs/STATIC_ANALYSIS.md#privacy-taint-tier)
and the engine cannot drift apart silently — the doc test renders this
module.

Taint kinds
-----------
``example``   raw client rows / batches / per-client label tensors
``client-id`` unbounded per-client identifiers (virtual client ids, not
              bounded comm ranks)
``secret``    PRNG keys and seeds, SecAgg self-mask seeds, DH secret
              keys, mask/key shares
``params``    model update trees (only a privacy problem on SecAgg
              client paths or as tensor reprs in logs)
"""

from __future__ import annotations

import re
from typing import Dict, FrozenSet, Tuple

EXAMPLE = "example"
CLIENT_ID = "client-id"
SECRET = "secret"
PARAMS = "params"

ALL_KINDS = (EXAMPLE, CLIENT_ID, SECRET, PARAMS)

#: variable/attribute base-name patterns → taint kind, applied at USE
#: time (so a tainted NAME taints every expression it appears in, even
#: after flowing through an unknown helper).  Anchored full-match.
NAME_PATTERNS: Dict[str, Tuple[re.Pattern, ...]] = {
    EXAMPLE: tuple(re.compile(p) for p in (
        r"batch(es)?", r"train_batch", r"eval_batch", r"example[s]?",
        r"client_rows", r"raw_rows", r"local_data(set)?", r"train_data",
        r"label_tensor[s]?",
    )),
    CLIENT_ID: tuple(re.compile(p) for p in (
        r"client_id[s]?", r"client_idx", r"client_index",
        r"virtual_client_id[s]?",
    )),
    SECRET: tuple(re.compile(p) for p in (
        r"(prng|rng)_key[s]?", r"b_seed", r"shared_seeds?", r"seed",
        r"master_seed", r"secret_key", r"priv(ate)?_key",
        r"local_mask", r"agg_mask", r"mask_share[s]?",
        r"sk_shares?", r"b_shares?", r"shares?",
    )),
    PARAMS: tuple(re.compile(p) for p in (
        r"weights", r"model_params", r"global_model", r"params",
        r"grads?", r"gradients", r"update_tree", r"local_update",
        r"state_dict", r"model_update",
    )),
}

#: call names (trailing dotted segment) that CREATE taint — the return
#: value carries the kind no matter what it is assigned to.
SOURCE_CALLS: Dict[str, str] = {
    "rows": EXAMPLE,             # ClientPopulation.rows / dataset rows
    "sample_batch": EXAMPLE,
    "next_batch": EXAMPLE,
    "get_batch": EXAMPLE,
    "load_population": EXAMPLE,
    "philox_generator": SECRET,  # data.population per-client PRNG
    "PRNGKey": SECRET,
    "fold_in": SECRET,
}

#: call names whose RESULT is clean regardless of argument taint — the
#: sanctioned escapes.  Aggregates/metadata (shape-level facts), hashes,
#: the wire codecs (encode side), and the SecAgg mask funnels.
DECLASSIFIER_CALLS: FrozenSet[str] = frozenset({
    # builtin / numeric reductions — scalars, never rows
    "len", "int", "float", "bool", "abs", "round", "hash",
    "sum", "min", "max", "sorted",
    # numpy/jax aggregate + histogram reductions
    "mean", "std", "var", "norm", "median", "percentile", "quantile",
    "histogram", "bincount", "count_nonzero", "size_hist", "zipf_sizes",
    # metadata summaries — shape/dtype/nbytes/param counts, never values
    "estimate_nbytes", "summarize_payload", "tree_nbytes",
    "count_trainable", "count_params",
    # admission verdicts: short reason enums DERIVED from, not
    # containing, the screened update
    "admission_check", "add_local_trained_result",
    # content hashes / digests
    "sha256", "md5", "blake2b", "hexdigest", "digest", "crc32",
    # wire codecs: params → opaque encoded bytes (the sanctioned
    # compression path; decode re-materializes on the OTHER role)
    "encode", "encode_update", "compress", "pack",
    # SecAgg mask funnels: the ONLY sanctioned params→wire route on an
    # armed client (sa_utils.mask_upload / lsa_utils.mask_field_vector)
    "mask_upload", "mask_field_vector",
})

#: call names that TRANSFORM taint: the local-epoch update funnel —
#: ``trainer.train(batch)`` consumes raw examples and returns a model
#: update tree (params kind), the first sanctioned reduction of client
#: data.
TRANSFORMER_CALLS: Dict[str, FrozenSet[str]] = {
    "train": frozenset({PARAMS}),
    # per-epoch / per-round jitted funnels: consume batches + PRNG keys,
    # return the updated model tree — the same reduction at other
    # granularities (simulation round steps, model init from a key)
    "train_epoch": frozenset({PARAMS}),
    "_train_epoch": frozenset({PARAMS}),
    "round_step": frozenset({PARAMS}),
    "bucketed_round_step": frozenset({PARAMS}),
    "multi_round_step": frozenset({PARAMS}),
    "init_variables": frozenset({PARAMS}),
}

#: attribute accesses that declassify (shape-level metadata, not values)
META_ATTRS: FrozenSet[str] = frozenset({
    "shape", "dtype", "nbytes", "ndim", "size", "itemsize",
})

#: wire payload keys (by WIRE VALUE) whose message-side values are
#: tensor payloads — reading them back via ``msg.get(...)`` re-taints
#: as params.
TENSOR_PAYLOAD_KEYS: FrozenSet[str] = frozenset({
    "model_params", "wire_update", "compressed_update", "masked_vector",
    "model_wq",
})

#: wire keys (by WIRE VALUE) forming the sanctioned peer-share channel:
#: secret-kind values MAY travel on exactly these keys (Shamir/LCC
#: shares and DH public material), nowhere else — PRIV003 otherwise.
SHARE_CHANNEL_KEYS: FrozenSet[str] = frozenset({
    "share_of_b", "share_of_sk", "b_shares", "sk_shares",
    "mask_share", "public_key", "public_keys",
})

#: module path prefixes that constitute the wire path — PRIV005 (tensor
#: repr in logs) only fires here, where a stray repr lands in hot-path
#: round logs shipped off-device.
WIRE_PATH_PREFIXES: Tuple[str, ...] = (
    "fedml_tpu/core/distributed/",
    "fedml_tpu/cross_silo/",
    "fedml_tpu/cross_device/",
    "fedml_tpu/serving/",
    "fedml_tpu/fa/",
)

#: module path fragments where SecAgg is armed — PRIV004 scope.
SECAGG_PATH_FRAGMENTS: Tuple[str, ...] = (
    "/secagg/", "/lightsecagg/",
)

#: sink identifiers (the engine's Hit.sink field)
SINK_WIRE = "wire"            # Message.add_params / Message.add
SINK_LOG = "log"              # logging.* / log.* / logger.* calls
SINK_METRICS_LABEL = "metrics-label"   # .labels(**kw) label VALUES
SINK_METRICS_VALUE = "metrics-value"   # .observe/.inc/.set values
SINK_LEDGER = "ledger"        # ledger.event(...) attrs
SINK_TRACE = "trace"          # mlops span()/event() values
SINK_HTTP = "http"            # http_json.reply / openai_api._json
SINK_CHECKPOINT = "checkpoint"  # CheckpointManager.save attrs

SINK_LABELS = {
    SINK_WIRE: "Message payload",
    SINK_LOG: "log call",
    SINK_METRICS_LABEL: "metrics label value",
    SINK_METRICS_VALUE: "metrics sample value",
    SINK_LEDGER: "run-ledger attr",
    SINK_TRACE: "trace span value",
    SINK_HTTP: "HTTP response body",
    SINK_CHECKPOINT: "checkpoint attr",
}

#: sinks that are sanctioned per-client surfaces: client-id kind is
#: LEGAL here (bounded retention, not a cardinality explosion).  The
#: wire itself must carry client_idx for routing.
CLIENT_ID_SANCTIONED_SINKS: FrozenSet[str] = frozenset({
    SINK_WIRE, SINK_LEDGER, SINK_TRACE, SINK_CHECKPOINT, SINK_HTTP,
    SINK_METRICS_VALUE, SINK_LOG,
})


def name_kinds(name: str) -> FrozenSet[str]:
    """Taint kinds a bare name/attribute carries by pattern."""
    out = set()
    low = name.lower()
    for kind, pats in NAME_PATTERNS.items():
        if any(p.fullmatch(low) for p in pats):
            out.add(kind)
    return frozenset(out)
