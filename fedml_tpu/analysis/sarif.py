"""SARIF 2.1.0 output for ``fedml lint --sarif <path>``.

One run, one driver ("fedml-lint"), every rule of every tier that
produced a result.  Baselined findings are carried with
``baselineState: "unchanged"`` so a CI annotator can show them dimmed
instead of dropping them; new findings are ``"new"``.  Severity maps
error→error, warning→warning, info→note.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Tuple

from .findings import Finding

_LEVELS = {"error": "error", "warning": "warning", "info": "note"}

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def _result(f: Finding, fingerprint: str, baselined: bool) -> dict:
    return {
        "ruleId": f.rule_id,
        "level": _LEVELS.get(f.severity, "warning"),
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(f.line, 1),
                           "startColumn": max(f.col, 0) + 1},
            },
        }],
        "partialFingerprints": {"fedmlLint/v1": fingerprint},
        "baselineState": "unchanged" if baselined else "new",
    }


def render_sarif(new: List[Tuple[Finding, str]],
                 old: List[Tuple[Finding, str]]) -> str:
    from .rules import rule_catalog

    cat = rule_catalog()
    used = ({f.rule_id for f, _ in new} | {f.rule_id for f, _ in old}
            | {"LINT001"})
    rules = [{
        "id": e["id"],
        "shortDescription": {"text": e["title"]},
        "properties": {"tier": e.get("tier", "file"),
                       "severity": e["severity"]},
    } for e in cat if e["id"] in used]
    results = ([_result(f, fp, False) for f, fp in new]
               + [_result(f, fp, True) for f, fp in old])
    results.sort(key=lambda r: (
        r["locations"][0]["physicalLocation"]["artifactLocation"]["uri"],
        r["locations"][0]["physicalLocation"]["region"]["startLine"],
        r["ruleId"]))
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "fedml-lint",
                "informationUri":
                    "docs/STATIC_ANALYSIS.md",
                "rules": rules,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


def write_sarif(path: Path, new: List[Tuple[Finding, str]],
                old: List[Tuple[Finding, str]]) -> int:
    """Write the report; returns the number of results."""
    path = Path(path)
    if path.parent != Path("."):
        path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(render_sarif(new, old) + "\n", encoding="utf-8")
    return len(new) + len(old)
