"""Value-free payload summaries for logs and diagnostics.

``repr()`` of a model update or a training batch in a log line ships the
raw numbers off-device — exactly the escape PRIV005 hunts.  But the wire
path still needs payload observability ("what did this sync carry?").
``summarize_payload`` is the sanctioned form: STRUCTURE ONLY — leaf
paths, shapes, dtypes and byte counts — never element values.  It is a
registered declassifier in the taint catalog
(``analysis/taint/catalog.py``), so flows through it are clean by
construction; logging anything else tensor-shaped on the wire path is a
finding.
"""

from __future__ import annotations

from typing import Any, List, Tuple

from .serialization import estimate_nbytes

#: leaf descriptors shown before eliding — keeps log lines bounded even
#: for thousand-leaf LLM trees
MAX_LEAVES_SHOWN = 8


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n}B"


def _leaf_desc(obj: Any) -> str:
    shape = getattr(obj, "shape", None)
    dtype = getattr(obj, "dtype", None)
    if shape is not None:
        dims = "x".join(str(d) for d in tuple(shape)) or "scalar"
        return f"{dims}:{dtype}" if dtype is not None else dims
    if isinstance(obj, bool):
        return "bool"
    if isinstance(obj, int):
        return "int"
    if isinstance(obj, float):
        return "float"
    if isinstance(obj, str):
        return f"str[{len(obj)}]"
    if isinstance(obj, (bytes, bytearray)):
        return f"bytes[{len(obj)}]"
    if obj is None:
        return "none"
    return type(obj).__name__


def _walk(obj: Any, path: str, out: List[Tuple[str, str]]) -> None:
    if isinstance(obj, dict):
        for k in obj:
            _walk(obj[k], f"{path}.{k}" if path else str(k), out)
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _walk(v, f"{path}[{i}]", out)
    else:
        out.append((path or "<root>", _leaf_desc(obj)))


def summarize_payload(obj: Any, max_leaves: int = MAX_LEAVES_SHOWN) -> str:
    """Shape/dtype/nbytes summary of a payload pytree — NEVER values.

    ``summarize_payload({"w": np.zeros((3, 4)), "n": 7})`` →
    ``"2 leaves, 104B: n=int, w=3x4:float64"``.  Safe on any object: an
    unrecognized leaf renders as its type name.
    """
    leaves: List[Tuple[str, str]] = []
    _walk(obj, "", leaves)
    nbytes = estimate_nbytes(obj)
    shown = sorted(leaves)[:max_leaves]
    parts = [f"{p}={d}" for p, d in shown]
    if len(leaves) > max_leaves:
        parts.append(f"... +{len(leaves) - max_leaves} more")
    head = f"{len(leaves)} leaves, {_fmt_bytes(nbytes)}"
    return f"{head}: {', '.join(parts)}" if parts else head
