"""Collective-cost extraction from compiled XLA programs.

The reference proves its collective plane by running it on real multi-GPU
(`simulation/nccl/base_framework/common.py:180-228` wraps
torch.distributed broadcast/reduce).  The TPU-era equivalent is
compiler-visible: every collective XLA inserted for a sharded program is
in the compiled HLO with its shape and replica groups, so per-round
communication cost is a STATIC artifact we can extract, regression-test,
and project to larger meshes — no 64-chip run needed to know what a
64-chip round moves over ICI.

`parse_collectives` pulls (op, bytes, replica-group fan-in) for every
collective in an HLO dump; `summarize_compiled` runs it on a
jax ``Compiled`` object; `ici_seconds`/`dcn_seconds` turn bytes into a
latency estimate under an explicit bandwidth model (constants documented
at the definitions — they are *assumptions*, kept in one place).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List

#: bytes per HLO element type
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

#: collective op names as they appear in HLO (async forms counted at
#: their -start; the matching -done moves no additional data)
_COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

#: "%name = <result type> <op>(..." — also matches "ROOT %name = ..." and
#: async "-start"/"-done" forms
_OP_RE = re.compile(
    r"=\s*(.+?)\s+(" + "|".join(_COLLECTIVE_OPS) + r")(-start|-done)?\(")


def _shape_bytes(shape_text: str) -> int:
    """Total bytes of every tensor shape in an HLO result-type string
    (handles tuples by summing members)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def parse_collectives(hlo_text: str) -> List[Dict[str, Any]]:
    """Extract collectives from HLO text: one record per instruction,
    ``{"op", "bytes", "group_size"}`` where bytes is the RESULT payload
    and group_size the replica-group fan-in (0 when absent/flat)."""
    out: List[Dict[str, Any]] = []
    for line in hlo_text.splitlines():
        s = line.strip()
        m = _OP_RE.search(s)
        if not m:
            continue
        op = m.group(2)
        if m.group(3) == "-done":
            # the -start half carries the shapes; -done moves no new data
            continue
        nbytes = _shape_bytes(m.group(1))
        if m.group(3) == "-start":
            # async result type is a tuple aliasing the operands:
            # "(f32[N], f32[N])" — operand alias + result; summing the
            # tuple double-counts the payload, so halve it
            nbytes //= 2
        gsize = 0
        groups = re.search(r"replica_groups=\{\{([\d,]+)\}", s)
        if groups:
            gsize = len(groups.group(1).split(","))
        else:
            # iota form: replica_groups=[G,S]<=[N] → G groups of size S
            iota = re.search(r"replica_groups=\[(\d+),(\d+)\]", s)
            if iota:
                gsize = int(iota.group(2))
        out.append({"op": op, "bytes": nbytes, "group_size": gsize})
    return out


def summarize(hlo_text: str) -> Dict[str, Any]:
    """Aggregate `parse_collectives` into per-op counts + bytes."""
    recs = parse_collectives(hlo_text)
    counts: Dict[str, int] = {}
    bytes_: Dict[str, int] = {}
    for r in recs:
        counts[r["op"]] = counts.get(r["op"], 0) + 1
        bytes_[r["op"]] = bytes_.get(r["op"], 0) + r["bytes"]
    return {"counts": counts, "bytes": bytes_,
            "total_ops": sum(counts.values()),
            "total_bytes": sum(bytes_.values())}


def summarize_compiled(compiled: Any) -> Dict[str, Any]:
    """`summarize` over a jax ``Compiled`` (jit(...).lower(...).compile())."""
    return summarize(compiled.as_text())


#: the four ops the per-entrypoint collective budget covers
#: (``benchmarks/collective_budgets.json``, SHARD004); collective-permute
#: is excluded — it is point-to-point and the budget models fan-in traffic
BUDGET_OPS = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all")


def collective_totals(hlo_text: str,
                      ops: Any = BUDGET_OPS) -> Dict[str, Any]:
    """Count + byte totals restricted to ``ops`` — the ONE number the
    mesh-lint budget ratchet, ``fedml perf programs`` and the bench JSONs
    share, so provenance and lint can never disagree."""
    s = summarize(hlo_text)
    per_op = {op: {"count": s["counts"].get(op, 0),
                   "bytes": s["bytes"].get(op, 0)}
              for op in ops if s["counts"].get(op)}
    return {
        "total_ops": sum(v["count"] for v in per_op.values()),
        "total_bytes": sum(v["bytes"] for v in per_op.values()),
        "per_op": per_op,
    }


# ---- bandwidth model (ASSUMPTIONS, single source of truth) ---------------
#: v5e ICI: 2D torus, ~45 GB/s one-way per link per direction (public
#: "How to Scale Your Model" figure); ring-allreduce effective bandwidth
#: uses the 2(N-1)/N traffic factor.
ICI_BW_V5E = 45e9
#: DCN between hosts/clouds: 200 Gbps-class NICs → ~25 GB/s per host.
DCN_BW = 25e9


def ici_seconds(payload_bytes: float, n_devices: int,
                op: str = "all-reduce", bw: float = ICI_BW_V5E) -> float:
    """Ring-collective latency estimate on ICI for one payload."""
    n = max(int(n_devices), 1)
    if n == 1:
        return 0.0
    factor = {"all-reduce": 2.0 * (n - 1) / n,
              "all-gather": (n - 1) / n,
              "reduce-scatter": (n - 1) / n,
              "collective-permute": 1.0,
              "all-to-all": (n - 1) / n}.get(op, 1.0)
    return factor * payload_bytes / bw


def dcn_seconds(payload_bytes: float, bw: float = DCN_BW) -> float:
    return payload_bytes / bw
