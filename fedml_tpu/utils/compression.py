"""Gradient compression: Top-K sparsification with error feedback.

Capability parity: reference `utils/compression.py:21-146` — TopK (per-tensor
top-k magnitude selection) and EFTopK (error-feedback residual accumulation),
plus flatten/unflatten helpers (`utils/model_utils.py`).

TPU-first: selection is ``jax.lax.top_k`` on the flattened update (one fused
op), residuals are a pytree carried between rounds; compress returns
(values, indices) pairs suitable for the wire.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def tree_spec(tree: Any) -> Any:
    """(treedef, shapes, dtypes) for ``_unflatten`` — no array work, so it
    is the cheap way to get a decompression spec from a reference tree."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef, [jnp.shape(l) for l in leaves],
            [jnp.result_type(l) for l in leaves])


def _flatten(tree: Any) -> Tuple[jnp.ndarray, Any]:
    leaves, _ = jax.tree_util.tree_flatten(tree)
    flat = jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])
    return flat, tree_spec(tree)


def _unflatten(flat: jnp.ndarray, spec: Any) -> Any:
    treedef, shapes, dtypes = spec
    out, off = [], 0
    for shape, dt in zip(shapes, dtypes):
        size = 1
        for s in shape:
            size *= s
        out.append(jnp.reshape(flat[off:off + size], shape).astype(dt))
        off += size
    return jax.tree_util.tree_unflatten(treedef, out)


class TopKCompressor:
    """Keep the k largest-magnitude entries of the flattened update."""

    def __init__(self, compress_ratio: float = 0.01) -> None:
        self.ratio = float(compress_ratio)

    def compress(self, tree: Any) -> Tuple[Dict[str, jnp.ndarray], Any]:
        flat, spec = _flatten(tree)
        k = max(1, int(len(flat) * self.ratio))
        values, idx = jax.lax.top_k(jnp.abs(flat), k)
        values = flat[idx]
        return {"values": values, "indices": idx, "size": len(flat)}, spec

    def decompress(self, payload: Dict[str, jnp.ndarray], spec: Any) -> Any:
        flat = jnp.zeros(int(payload["size"]), jnp.float32)
        flat = flat.at[payload["indices"]].set(payload["values"])
        return _unflatten(flat, spec)


class EFTopKCompressor(TopKCompressor):
    """Error-feedback TopK: the un-sent residual is added back next round
    (reference EFTopK)."""

    def __init__(self, compress_ratio: float = 0.01) -> None:
        super().__init__(compress_ratio)
        self.residual: Optional[jnp.ndarray] = None

    def compress(self, tree: Any) -> Tuple[Dict[str, jnp.ndarray], Any]:
        flat, spec = _flatten(tree)
        if self.residual is not None and self.residual.shape == flat.shape:
            flat = flat + self.residual
        k = max(1, int(len(flat) * self.ratio))
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        values = flat[idx]
        sent = jnp.zeros_like(flat).at[idx].set(values)
        self.residual = flat - sent
        return {"values": values, "indices": idx, "size": len(flat)}, spec
