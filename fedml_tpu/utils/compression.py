"""Gradient compression: Top-K sparsification with error feedback.

Capability parity: reference `utils/compression.py:21-146` — TopK (per-tensor
top-k magnitude selection) and EFTopK (error-feedback residual accumulation),
plus flatten/unflatten helpers (`utils/model_utils.py`).

TPU-first: selection is ``jax.lax.top_k`` on the flattened update (one fused
op), residuals are a pytree carried between rounds; compress returns
(values, indices) pairs suitable for the wire.

``WireCodec`` (docs/ROBUSTNESS.md "Asynchronous rounds") is the cross-silo
wire-compression layer built on the fused kernels in
``ops/wire_compression.py``: per-update DELTA encoding against the last
global the client received (the server keeps the identical reference per
version, so reconstruction is exact up to codec error), int8/bf16
quantization and/or top-k sparsification of the delta, error-feedback
residual kept client-side, and a self-describing full-model downlink
encoding (per-leaf blocked int8) that survives every transport's
serializer.  Decode paths are jitted — on the server the decompression
folds into the aggregation program instead of running as eager host ops.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.mlops import metrics
from ..ops.wire_compression import (
    dequantize_int8_blocked,
    quantize_int8_blocked,
    scatter_flat,
    topk_select,
)

#: shared by the client/server comm managers — one definition so the
#: label set and help text cannot drift between the two ends of the wire
WIRE_BYTES = metrics.counter(
    "fedml_wire_bytes_total",
    "Model payload bytes placed on the wire, by direction (up = client "
    "uploads, down = server broadcasts) and codec (raw when uncompressed)",
    labels=("run_id", "direction", "codec"))


def tree_spec(tree: Any) -> Any:
    """(treedef, shapes, dtypes) for ``_unflatten`` — no array work, so it
    is the cheap way to get a decompression spec from a reference tree."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef, [jnp.shape(l) for l in leaves],
            [jnp.result_type(l) for l in leaves])


def _flatten(tree: Any) -> Tuple[jnp.ndarray, Any]:
    leaves, _ = jax.tree_util.tree_flatten(tree)
    flat = jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])
    return flat, tree_spec(tree)


def _unflatten(flat: jnp.ndarray, spec: Any) -> Any:
    treedef, shapes, dtypes = spec
    out, off = [], 0
    for shape, dt in zip(shapes, dtypes):
        size = 1
        for s in shape:
            size *= s
        out.append(jnp.reshape(flat[off:off + size], shape).astype(dt))
        off += size
    return jax.tree_util.tree_unflatten(treedef, out)


class TopKCompressor:
    """Keep the k largest-magnitude entries of the flattened update."""

    def __init__(self, compress_ratio: float = 0.01) -> None:
        self.ratio = float(compress_ratio)

    def compress(self, tree: Any) -> Tuple[Dict[str, jnp.ndarray], Any]:
        flat, spec = _flatten(tree)
        k = max(1, int(len(flat) * self.ratio))
        values, idx = jax.lax.top_k(jnp.abs(flat), k)
        values = flat[idx]
        return {"values": values, "indices": idx, "size": len(flat)}, spec

    def decompress(self, payload: Dict[str, jnp.ndarray], spec: Any) -> Any:
        flat = jnp.zeros(int(payload["size"]), jnp.float32)
        flat = flat.at[payload["indices"]].set(payload["values"])
        return _unflatten(flat, spec)


class EFTopKCompressor(TopKCompressor):
    """Error-feedback TopK: the un-sent residual is added back next round
    (reference EFTopK)."""

    def __init__(self, compress_ratio: float = 0.01) -> None:
        super().__init__(compress_ratio)
        self.residual: Optional[jnp.ndarray] = None

    def compress(self, tree: Any) -> Tuple[Dict[str, jnp.ndarray], Any]:
        flat, spec = _flatten(tree)
        if self.residual is not None and self.residual.shape == flat.shape:
            flat = flat + self.residual
        k = max(1, int(len(flat) * self.ratio))
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        values = flat[idx]
        sent = jnp.zeros_like(flat).at[idx].set(values)
        self.residual = flat - sent
        return {"values": values, "indices": idx, "size": len(flat)}, spec


# ---------------------------------------------------------------------------
# wire codec: delta + quantize/sparsify, negotiated per cross-silo link
# ---------------------------------------------------------------------------

class WireSpec(NamedTuple):
    """Parsed ``--wire-compression`` selector (static per link)."""

    kind: str          # bf16 | int8 | topk | topk8
    ratio: float = 0.01


_WIRE_KINDS = ("bf16", "int8", "topk", "topk8")

#: capability tokens a client advertises in its status message; the server
#: only assigns a codec whose tokens the link's peer supports
WIRE_CAPS = ("delta", "bf16", "int8", "topk")

#: reserved marker key for per-leaf quantized downlink payloads
_WQ_KEY = "__wq__"


def parse_wire_compression(spec: Any) -> Optional[WireSpec]:
    """``None``/empty → None; else validate + parse.  Raises ``ValueError``
    on an unknown codec or malformed ratio so a typo'd flag fails at
    startup, not on the first upload."""
    if spec is None or spec is False or str(spec).strip() == "":
        return None
    parts = [p for p in str(spec).strip().split(":") if p != ""]
    kind = parts[0].lower()
    if kind == "none":
        return None
    if kind not in _WIRE_KINDS:
        raise ValueError(
            f"unknown wire_compression codec {kind!r}; expected one of "
            f"none|{'|'.join(_WIRE_KINDS)}")
    ratio = 0.01
    if len(parts) > 1:
        if kind in ("bf16", "int8"):
            raise ValueError(
                f"wire_compression {kind} takes no parameter")
        try:
            ratio = float(parts[1])
        except ValueError as e:
            raise ValueError(
                f"malformed wire_compression ratio {parts[1]!r}") from e
        if not 0.0 < ratio <= 1.0:
            raise ValueError("wire_compression top-k ratio must be in (0, 1]")
    return WireSpec(kind, ratio)


def required_caps(spec: WireSpec) -> Tuple[str, ...]:
    """Capability tokens a peer must advertise for this codec to apply."""
    caps = ["delta"]
    if spec.kind == "bf16":
        caps.append("bf16")
    if spec.kind in ("int8", "topk8"):
        caps.append("int8")
    if spec.kind in ("topk", "topk8"):
        caps.append("topk")
    return tuple(caps)


# decode paths are jitted with static sizes: the scatter/dequant/add chain
# compiles once per (codec, model size) and the server's buffer fold calls
# it as one fused program — "decompress inside the agg jit"
@partial(jax.jit, static_argnames=("size",))
def _decode_topk_flat(values, idx, size):
    return scatter_flat(values, idx, size)


@partial(jax.jit, static_argnames=("size",))
def _decode_int8_flat(q, scales, size):
    return dequantize_int8_blocked(q, scales, size)


@partial(jax.jit, static_argnames=("size",))
def _decode_topk8_flat(q, scales, idx, size):
    k = q.shape[0]
    return scatter_flat(dequantize_int8_blocked(q, scales, k), idx, size)


@jax.jit
def _add_delta_tree(ref: Any, delta_flat: jnp.ndarray) -> Any:
    """ref tree + flat f32 delta → reconstructed tree, PER LEAF as
    ``(leaf.astype(f32) + delta_slice).astype(leaf.dtype)``.

    The add MUST run in f32: the delta is an exact f32 difference of the
    client's values, so f32-add-then-cast reproduces the client's update
    BIT-EXACTLY (the error-feedback residual and the async per-version
    reference contract both model an exact server-side apply); narrowing
    the delta before the add would round twice and drift.  What the old
    path wasted — and this one doesn't — is the WHOLE-MODEL flat f32
    materialization: ``_flatten(ref)`` concatenated every leaf into one
    full-model f32 buffer and the add produced another, where the
    per-leaf convert→add→convert chain fuses in XLA without either
    (the per-leaf f32 compute is allowlisted at the wire entrypoints'
    PERF002 registration — exactness requires it)."""
    treedef, shapes, dtypes = tree_spec(ref)
    # reuse _unflatten's offset walk but keep the delta f32 — casting a
    # slice to the leaf dtype before the add would round/truncate it
    delta = _unflatten(delta_flat,
                       (treedef, shapes, [jnp.float32] * len(shapes)))

    def _leaf(r: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
        dt = jnp.result_type(r)
        return (r.astype(jnp.float32) + d).astype(dt)

    return jax.tree_util.tree_map(_leaf, ref, delta)


class WireCodec:
    """Per-link update codec: DELTA against a shared reference + one of
    bf16 cast / blocked-int8 quantize / top-k sparsify / top-k+int8,
    with an error-feedback residual on the encode side.

    One instance per link per direction: the encoder's residual
    accumulates everything the codec dropped, so the information is sent
    eventually rather than lost (EF-SGD / DoubleSqueeze idiom).
    """

    def __init__(self, spec: Any) -> None:
        parsed = spec if isinstance(spec, WireSpec) else (
            parse_wire_compression(spec))
        if parsed is None:
            raise ValueError("WireCodec needs a non-empty codec spec")
        self.spec = parsed
        self._residual: Optional[jnp.ndarray] = None

    # -- uplink: delta encoding ---------------------------------------------
    def encode_delta(self, update: Any, ref: Any) -> Dict[str, Any]:
        """update tree + shared reference tree → wire payload dict (arrays
        + scalars only — serializable by every transport)."""
        flat_u, _ = _flatten(update)
        flat_r, _ = _flatten(ref)
        delta = flat_u - flat_r
        if self._residual is not None and self._residual.shape == delta.shape:
            delta = delta + self._residual
        payload = self._encode_flat(delta)
        decoded = decode_delta_flat(payload)
        self._residual = delta - decoded
        return payload

    def _encode_flat(self, delta: jnp.ndarray) -> Dict[str, Any]:
        kind = self.spec.kind
        d = int(delta.shape[0])
        if kind == "bf16":
            return {"codec": "bf16", "flat": delta.astype(jnp.bfloat16),
                    "size": d}
        if kind == "int8":
            q, s = quantize_int8_blocked(delta)
            return {"codec": "int8", "q": q, "scales": s, "size": d}
        k = max(1, int(d * self.spec.ratio))
        values, idx = topk_select(delta, k)
        if kind == "topk":
            return {"codec": "topk", "values": values, "idx": idx, "size": d}
        q, s = quantize_int8_blocked(values)
        return {"codec": "topk8", "values_q": q, "scales": s, "idx": idx,
                "size": d}

    # -- downlink: self-describing full-model encoding -----------------------
    @staticmethod
    def encode_model(tree: Any, kind: str = "int8") -> Any:
        """Full-model broadcast payload: every float leaf is replaced by a
        marker dict holding its blocked-int8 (or bf16) form plus enough
        metadata to invert it WITHOUT a reference tree — the client may
        not have one yet (INIT).  Container structure is preserved, so
        any transport serializer that carries the original tree carries
        this one."""
        if kind not in ("int8", "bf16"):
            kind = "int8"   # topk on a full model is meaningless

        def _leaf(x: Any) -> Any:
            arr = jnp.asarray(x)
            if not jnp.issubdtype(arr.dtype, jnp.floating):
                return x
            if kind == "bf16":
                return {_WQ_KEY: "bf16", "flat": arr.astype(jnp.bfloat16),
                        "dtype": str(arr.dtype)}
            flat = arr.reshape(-1).astype(jnp.float32)
            q, s = quantize_int8_blocked(flat)
            return {_WQ_KEY: "int8", "q": q, "scales": s,
                    "shape": list(arr.shape), "dtype": str(arr.dtype)}

        return jax.tree_util.tree_map(_leaf, tree)

    @staticmethod
    def is_encoded_model(tree: Any) -> bool:
        for leaf in jax.tree_util.tree_leaves(
                tree, is_leaf=lambda x: isinstance(x, dict)
                and _WQ_KEY in x):
            if isinstance(leaf, dict) and _WQ_KEY in leaf:
                return True
        return False

    @staticmethod
    def decode_model(tree: Any) -> Any:
        """Invert ``encode_model``.  Deterministic: every decoder of the
        same payload reconstructs bit-identical values, which is what
        makes the decoded broadcast usable as the shared delta
        reference."""

        def _is_marker(x: Any) -> bool:
            return isinstance(x, dict) and _WQ_KEY in x

        def _leaf(x: Any) -> Any:
            if not _is_marker(x):
                return x
            if x[_WQ_KEY] == "bf16":
                return jnp.asarray(x["flat"]).astype(x["dtype"])
            flat = _decode_int8_flat(jnp.asarray(x["q"]),
                                     jnp.asarray(x["scales"]),
                                     int(jnp.size(jnp.asarray(x["q"]))))
            return flat.reshape(x["shape"]).astype(x["dtype"])

        return jax.tree_util.tree_map(_leaf, tree, is_leaf=_is_marker)


def decode_delta_flat(payload: Dict[str, Any]) -> jnp.ndarray:
    """Wire payload → flat f32 delta (jitted per codec/size)."""
    codec = str(payload["codec"])
    size = int(payload["size"])
    if codec == "bf16":
        return jnp.asarray(payload["flat"]).astype(jnp.float32)
    if codec == "int8":
        return _decode_int8_flat(jnp.asarray(payload["q"]),
                                 jnp.asarray(payload["scales"]), size)
    if codec == "topk":
        return _decode_topk_flat(jnp.asarray(payload["values"]),
                                 jnp.asarray(payload["idx"]), size)
    if codec == "topk8":
        return _decode_topk8_flat(jnp.asarray(payload["values_q"]),
                                  jnp.asarray(payload["scales"]),
                                  jnp.asarray(payload["idx"]), size)
    raise ValueError(f"unknown wire payload codec {codec!r}")


def decode_delta(payload: Dict[str, Any], ref: Any) -> Any:
    """payload + shared reference tree → reconstructed update tree
    (ref + delta in each leaf's own dtype — one fused jit per tree
    structure, no whole-model f32 widening of the reference)."""
    return _add_delta_tree(ref, decode_delta_flat(payload))
