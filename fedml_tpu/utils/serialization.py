"""Pytree wire codec for the message-driven control plane.

The reference pickles torch state_dicts over gRPC (`grpc_comm_manager.py` —
pickled Message objects) and uploads them to S3.  Pickle of arbitrary objects
is a security hole and torch-specific; this build serializes JAX pytrees to a
self-describing binary format: a JSON header (treedef as nested lists +
dtypes/shapes) plus raw little-endian buffers.  No code execution on decode.
"""

from __future__ import annotations

import io
import json
import struct
from typing import Any, Dict, List, Tuple

import numpy as np

try:  # register bfloat16/fp8 dtypes with numpy (ships with jax)
    import ml_dtypes  # noqa: F401
except ImportError:
    pass

_MAGIC = b"FTPT"  # fedml-tpu pytree


def _flatten_struct(obj: Any, leaves: List[np.ndarray]) -> Any:
    """Replace arrays/scalars with leaf placeholders, recursing containers."""
    from ..core.fhe.fhe_agg import EncryptedTree

    if isinstance(obj, EncryptedTree):
        return _encode_encrypted_tree(obj, leaves)
    if isinstance(obj, dict):
        return {"t": "d",
                "k": sorted(obj.keys()),
                "v": [_flatten_struct(obj[k], leaves) for k in sorted(obj.keys())]}
    if isinstance(obj, (list, tuple)):
        return {"t": "l" if isinstance(obj, list) else "u",
                "v": [_flatten_struct(x, leaves) for x in obj]}
    if obj is None:
        return {"t": "n"}
    if isinstance(obj, (bool, int, float, str)):
        return {"t": "s", "v": obj}
    arr = np.asarray(obj)
    leaves.append(arr)
    return {"t": "a", "i": len(leaves) - 1}


def _encode_encrypted_tree(enc: Any, leaves: List[np.ndarray]) -> Any:
    """FHE ciphertext trees ride the wire as JSON (hex bigints) — still no
    code execution on decode (`core/fhe/fhe_agg.py` EncryptedTree)."""
    import jax

    skeleton = jax.tree_util.tree_unflatten(
        enc.treedef, list(range(len(enc.leaves))))
    return {
        "t": "fhe",
        "skel": _flatten_struct(skeleton, leaves),
        "shapes": [list(s) for s in enc.shapes],
        "dtypes": [str(np.dtype(d)) for d in enc.dtypes],
        "leaves": [_encode_ct(ct, leaves) for ct in enc.leaves],
    }


def _encode_ct(ct: Any, leaves: List[np.ndarray]) -> Any:
    if hasattr(ct, "key_id"):     # RLWE: two int64 arrays ride as leaves
        leaves.append(np.asarray(ct.a))
        leaves.append(np.asarray(ct.b))
        return {"kind": "rlwe", "size": ct.size, "wt": ct.weight_total,
                "kid": int(ct.key_id), "ai": len(leaves) - 2,
                "bi": len(leaves) - 1}
    return {"kind": "paillier",
            "size": ct.size, "sb": ct.slot_bits, "k": ct.slots_per_ct,
            "wt": ct.weight_total, "n": hex(ct.n),
            "c": [hex(c) for c in ct.ciphertexts]}


def _decode_encrypted_tree(spec: Any, leaves: List[np.ndarray]) -> Any:
    import jax

    from ..core.fhe.fhe_agg import EncryptedTree
    from ..core.fhe.paillier import PackedCiphertext

    skeleton = _unflatten_struct(spec["skel"], leaves)
    treedef = jax.tree_util.tree_structure(skeleton)
    cts = []
    for m in spec["leaves"]:
        if m.get("kind") == "rlwe":
            from ..core.fhe.rlwe import RlwePackedCiphertext

            cts.append(RlwePackedCiphertext(
                np.asarray(leaves[int(m["ai"])], np.int64),
                np.asarray(leaves[int(m["bi"])], np.int64),
                int(m["size"]), int(m["wt"]), int(m["kid"])))
        else:
            cts.append(PackedCiphertext(
                [int(c, 16) for c in m["c"]], int(m["size"]),
                int(m["sb"]), int(m["k"]), int(m["wt"]),
                int(m["n"], 16)))
    return EncryptedTree(treedef, [tuple(s) for s in spec["shapes"]],
                         [np.dtype(d) for d in spec["dtypes"]], cts)


def _unflatten_struct(spec: Any, leaves: List[np.ndarray]) -> Any:
    t = spec["t"]
    if t == "fhe":
        return _decode_encrypted_tree(spec, leaves)
    if t == "d":
        return {k: _unflatten_struct(v, leaves)
                for k, v in zip(spec["k"], spec["v"])}
    if t == "l":
        return [_unflatten_struct(x, leaves) for x in spec["v"]]
    if t == "u":
        return tuple(_unflatten_struct(x, leaves) for x in spec["v"])
    if t == "n":
        return None
    if t == "s":
        return spec["v"]
    return leaves[spec["i"]]


def dumps_pytree(tree: Any) -> bytes:
    leaves: List[np.ndarray] = []
    struct_spec = _flatten_struct(tree, leaves)
    header = {
        "spec": struct_spec,
        "leaves": [{"dtype": str(a.dtype), "shape": list(a.shape)}
                   for a in leaves],
    }
    hbytes = json.dumps(header).encode()
    out = io.BytesIO()
    out.write(_MAGIC)
    out.write(struct.pack("<I", len(hbytes)))
    out.write(hbytes)
    for a in leaves:
        out.write(np.ascontiguousarray(a).tobytes())
    return out.getvalue()


def loads_pytree(data: bytes) -> Any:
    if data[:4] != _MAGIC:
        raise ValueError("not a fedml_tpu pytree payload")
    hlen = struct.unpack("<I", data[4:8])[0]
    header = json.loads(data[8:8 + hlen].decode())
    off = 8 + hlen
    leaves: List[np.ndarray] = []
    for meta in header["leaves"]:
        dt = np.dtype(meta["dtype"])
        n = int(np.prod(meta["shape"])) if meta["shape"] else 1
        nbytes = n * dt.itemsize
        arr = np.frombuffer(data[off:off + nbytes], dtype=dt).reshape(
            meta["shape"])
        leaves.append(arr)
        off += nbytes
    return _unflatten_struct(header["spec"], leaves)


def message_to_wire(msg_params: Dict[str, Any]) -> bytes:
    """Serialize a Message's params dict (may contain pytrees)."""
    return dumps_pytree(msg_params)


def message_from_wire(data: bytes) -> Dict[str, Any]:
    return loads_pytree(data)


def estimate_nbytes(obj: Any) -> int:
    """Wire-size estimate of a message payload WITHOUT serializing it:
    array leaves count their raw buffer bytes, scalars/strings their
    natural width, containers a small framing constant.  Used by the
    chaos plane's bandwidth shaping and the bytes-on-wire accounting —
    both need a per-message cost, neither can afford a second
    ``dumps_pytree`` pass per send."""
    if obj is None or isinstance(obj, bool):
        return 1
    if isinstance(obj, (int, float)):
        return 8
    if isinstance(obj, str):
        return len(obj)
    if isinstance(obj, bytes):
        return len(obj)
    if isinstance(obj, dict):
        return 16 + sum(estimate_nbytes(k) + estimate_nbytes(v)
                        for k, v in obj.items())
    if isinstance(obj, (list, tuple)):
        return 16 + sum(estimate_nbytes(x) for x in obj)
    nbytes = getattr(obj, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    try:
        return int(np.asarray(obj).nbytes)
    except Exception:  # noqa: BLE001 — opaque object: flat guess, never raise
        return 64
