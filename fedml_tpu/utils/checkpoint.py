"""Round-level checkpoint/resume.

The reference has NO first-class FL checkpointing (SURVEY §5 — rounds restart
from 0 on failure); this is a required upgrade in the TPU build.  Orbax-backed
when available, with a numpy .npz fallback; state = {round_idx, global
variables pytree, server algorithm state}.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

#: orbax steps are version-encoded as ``round_idx * _VSCALE + version`` so a
#: re-save of the same round WRITES FIRST and deletes the old version after
#: the new one has committed — a crash anywhere in between always leaves a
#: restorable step.  (A delete-then-save overwrite would open a window where
#: the newest — possibly only — checkpoint is gone.)  4096 versions per
#: round is far beyond the one-save-per-accepted-upload cadence.
_VSCALE = 4096


class RoundCheckpointer:
    def __init__(self, ckpt_dir: str, keep: int = 3) -> None:
        self.dir = ckpt_dir
        self.keep = keep
        os.makedirs(ckpt_dir, exist_ok=True)
        self._mgr = None
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self._mgr = ocp.CheckpointManager(
                os.path.abspath(ckpt_dir),
                options=ocp.CheckpointManagerOptions(max_to_keep=keep))
        except Exception:
            self._ocp = None

    # -- save ----------------------------------------------------------------
    def save(self, round_idx: int, state: Dict[str, Any],
             force: bool = False) -> None:
        """``force=True`` allows re-saving an existing round — the
        cross-silo server persists the in-flight round's partial
        received-results set on every accepted upload, re-saving the same
        round index as the set grows (crash-resume then re-solicits only
        the missing clients).  On the orbax path every save lands on a
        fresh version-encoded step and stale versions are pruned only
        after the new step commits, so there is no window without a
        restorable checkpoint; the npz fallback's ``os.replace`` is
        atomic on its own."""
        state = jax.tree_util.tree_map(np.asarray, state)
        if self._mgr is not None:
            existing = [s for s in self._mgr.all_steps()
                        if s // _VSCALE == round_idx]
            if existing and not force:
                raise ValueError(
                    f"round {round_idx} already checkpointed; pass "
                    "force=True to re-save it")
            version = (max(existing) % _VSCALE + 1) if existing else 0
            self._mgr.save(round_idx * _VSCALE + version,
                           args=self._ocp.args.StandardSave(state))
            self._mgr.wait_until_finished()
            for stale in existing:
                try:
                    self._mgr.delete(stale)
                except Exception:  # noqa: BLE001 — leftover versions are
                    # harmless (restore always picks the newest) and the
                    # max_to_keep GC sweeps them eventually
                    pass
            return
        from .serialization import dumps_pytree

        path = os.path.join(self.dir, f"round_{round_idx:08d}.ckpt")
        with open(path + ".tmp", "wb") as f:
            f.write(dumps_pytree(state))
        os.replace(path + ".tmp", path)
        self._gc_fallback()

    def _gc_fallback(self) -> None:
        files = sorted(f for f in os.listdir(self.dir) if f.endswith(".ckpt"))
        for f in files[:-self.keep]:
            os.remove(os.path.join(self.dir, f))

    # -- restore -------------------------------------------------------------
    def latest_round(self) -> Optional[int]:
        if self._mgr is not None:
            step = self._mgr.latest_step()
            return None if step is None else int(step) // _VSCALE
        files = sorted(f for f in os.listdir(self.dir) if f.endswith(".ckpt"))
        if not files:
            return None
        return int(files[-1].split("_")[1].split(".")[0])

    def restore(self, round_idx: Optional[int] = None
                ) -> Optional[Dict[str, Any]]:
        step = round_idx if round_idx is not None else self.latest_round()
        if step is None:
            return None
        if self._mgr is not None:
            versions = [s for s in self._mgr.all_steps()
                        if s // _VSCALE == step]
            if not versions:
                return None
            try:
                # StandardRestore (no target) restores the tree as saved —
                # required when restoring from a FRESH manager (the crash-
                # restart path), where orbax has no registered handler to
                # infer the item type from
                return self._mgr.restore(
                    max(versions), args=self._ocp.args.StandardRestore())
            except Exception:
                return self._mgr.restore(max(versions))
        from .serialization import loads_pytree

        path = os.path.join(self.dir, f"round_{step:08d}.ckpt")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return loads_pytree(f.read())
