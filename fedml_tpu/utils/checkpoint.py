"""Round-level checkpoint/resume.

The reference has NO first-class FL checkpointing (SURVEY §5 — rounds restart
from 0 on failure); this is a required upgrade in the TPU build.  Orbax-backed
when available, with a numpy .npz fallback; state = {round_idx, global
variables pytree, server algorithm state}.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


class RoundCheckpointer:
    def __init__(self, ckpt_dir: str, keep: int = 3) -> None:
        self.dir = ckpt_dir
        self.keep = keep
        os.makedirs(ckpt_dir, exist_ok=True)
        self._mgr = None
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
            self._mgr = ocp.CheckpointManager(
                os.path.abspath(ckpt_dir),
                options=ocp.CheckpointManagerOptions(max_to_keep=keep))
        except Exception:
            self._ocp = None

    # -- save ----------------------------------------------------------------
    def save(self, round_idx: int, state: Dict[str, Any]) -> None:
        state = jax.tree_util.tree_map(np.asarray, state)
        if self._mgr is not None:
            self._mgr.save(round_idx,
                           args=self._ocp.args.StandardSave(state))
            self._mgr.wait_until_finished()
            return
        from .serialization import dumps_pytree

        path = os.path.join(self.dir, f"round_{round_idx:08d}.ckpt")
        with open(path + ".tmp", "wb") as f:
            f.write(dumps_pytree(state))
        os.replace(path + ".tmp", path)
        self._gc_fallback()

    def _gc_fallback(self) -> None:
        files = sorted(f for f in os.listdir(self.dir) if f.endswith(".ckpt"))
        for f in files[:-self.keep]:
            os.remove(os.path.join(self.dir, f))

    # -- restore -------------------------------------------------------------
    def latest_round(self) -> Optional[int]:
        if self._mgr is not None:
            step = self._mgr.latest_step()
            return None if step is None else int(step)
        files = sorted(f for f in os.listdir(self.dir) if f.endswith(".ckpt"))
        if not files:
            return None
        return int(files[-1].split("_")[1].split(".")[0])

    def restore(self, round_idx: Optional[int] = None
                ) -> Optional[Dict[str, Any]]:
        step = round_idx if round_idx is not None else self.latest_round()
        if step is None:
            return None
        if self._mgr is not None:
            return self._mgr.restore(step)
        from .serialization import loads_pytree

        path = os.path.join(self.dir, f"round_{step:08d}.ckpt")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return loads_pytree(f.read())
