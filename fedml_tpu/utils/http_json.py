"""Shared stdlib JSON-over-HTTP handler scaffold.

One place for the pattern every control/serving HTTP surface repeats
(quiet logging, JSON replies with Content-Length, body parsing with a
clean 400): subclass `JsonHandler` and implement do_GET/do_POST with
`self.reply(code, dict)` and `self.json_body()`.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict
from urllib.parse import parse_qs, urlparse


class BadRequest(Exception):
    """Raise inside a handler to produce a clean 400 with a message."""


class JsonHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # noqa: D102 — quiet server
        pass

    def reply(self, code: int, body: Dict[str, Any]) -> None:
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def json_body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length", 0) or 0)
        if not n:
            return {}
        try:
            return json.loads(self.rfile.read(n).decode())
        except Exception as e:  # noqa: BLE001
            raise BadRequest("bad json") from e

    def query(self) -> Dict[str, str]:
        """Last-wins flat query dict (order-independent, never raises)."""
        q = parse_qs(urlparse(self.path).query)
        return {k: v[-1] for k, v in q.items()}

    def query_float(self, name: str, default: float) -> float:
        raw = self.query().get(name)
        if raw is None:
            return default
        try:
            return float(raw)
        except ValueError as e:
            raise BadRequest(f"{name} must be a number") from e


class DeepBacklogHTTPServer(ThreadingHTTPServer):
    """`ThreadingHTTPServer` with a real listen backlog.

    The stdlib default ``request_queue_size`` is 5: any burst of
    concurrent clients beyond that overflows the kernel accept queue and
    the excess connections are RESET (measured: 48 simultaneous clients
    against the OpenAI endpoint dropped requests).  Every HTTP surface in
    this framework (serving gateway, OpenAI API, inference runner,
    control plane) should build its server through this class."""

    request_queue_size = 128
