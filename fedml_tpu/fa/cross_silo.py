"""Federated-analytics cross-silo runtime — FA over the message plane.

Capability parity: reference `fa/cross_silo/` (Client/Server managers
mirroring the FL cross-silo protocol, driving FAClientAnalyzer /
FAServerAggregator instead of trainers).  Runs over any comm backend
(INPROC for tests, GRPC/MQTT_* across hosts).

Protocol: server sends FA_INIT (task + params) → each client runs
``local_analyze`` on its data and replies FA_SUBMIT → server aggregates;
for iterative tasks (TrieHH) the server broadcasts FA_NEXT_ROUND with the
surviving prefixes until done, then FA_FINISH.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Sequence

from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager
from .fa_frame import FA_TASKS

MSG_FA_INIT = "FA_INIT"
MSG_FA_SUBMIT = "FA_SUBMIT"
MSG_FA_NEXT_ROUND = "FA_NEXT_ROUND"
MSG_FA_FINISH = "FA_FINISH"


class FAServerManager(FedMLCommManager):
    """Rank 0; aggregates client submissions per round."""

    def __init__(self, args: Any, comm=None, rank: int = 0, size: int = 0,
                 backend: str = "INPROC") -> None:
        self.task = str(getattr(args, "fa_task", "avg")).lower()
        if self.task not in FA_TASKS:
            raise ValueError(f"unknown FA task {self.task!r}; known: "
                             f"{sorted(FA_TASKS)}")
        _, g_cls = FA_TASKS[self.task]
        self.aggregator = g_cls(args)
        self.n_clients = int(size) - 1
        self.result: Any = None
        self.done = threading.Event()
        self._subs: Dict[int, Any] = {}
        self._round = 0
        self._prefixes: List[str] = [""]
        self.max_rounds = int(getattr(args, "comm_round", 5) or 5)
        super().__init__(args, comm, rank, size, backend)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MSG_FA_SUBMIT, self._on_submit)

    def run(self) -> None:
        self.register_message_receive_handlers()
        self._broadcast_round()
        self.com_manager.handle_receive_message()

    def _broadcast_round(self) -> None:
        mtype = MSG_FA_INIT if self._round == 0 else MSG_FA_NEXT_ROUND
        for rank in range(1, self.n_clients + 1):
            msg = Message(mtype, 0, rank)
            msg.add_params("fa_task", self.task)
            msg.add_params("round", self._round)
            if self.task == "heavy_hitter_triehh":
                msg.add_params("prefixes", list(self._prefixes))
                msg.add_params("prefix_len", self._round + 1)
            self.send_message(msg)

    def _on_submit(self, msg: Message) -> None:
        self._subs[msg.get_sender_id()] = msg.get("submission")
        if len(self._subs) < self.n_clients:
            return
        subs = [self._subs[r] for r in sorted(self._subs)]
        self._subs.clear()
        out = self.aggregator.aggregate(subs)
        self._round += 1
        iterative = (self.task == "heavy_hitter_triehh"
                     and self._round < self.max_rounds and out)
        if iterative:
            self._prefixes = out
            self._broadcast_round()
            return
        self.result = out if self.task != "heavy_hitter_triehh" \
            else (out or self._prefixes)
        logging.info("FA server: %s result %s", self.task, self.result)
        for rank in range(1, self.n_clients + 1):
            self.send_message(Message(MSG_FA_FINISH, 0, rank))
        self.done.set()
        self.finish()


class FAClientManager(FedMLCommManager):
    """Rank ≥ 1; runs the local analyzer on demand."""

    def __init__(self, args: Any, local_data: Sequence, comm=None,
                 rank: int = 1, size: int = 0,
                 backend: str = "INPROC") -> None:
        task = str(getattr(args, "fa_task", "avg")).lower()
        a_cls, _ = FA_TASKS[task]
        self.analyzer = a_cls(args)
        self.local_data = local_data
        super().__init__(args, comm, rank, size, backend)
        self.analyzer.set_id(rank)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(MSG_FA_INIT, self._on_round)
        self.register_message_receive_handler(MSG_FA_NEXT_ROUND,
                                              self._on_round)
        self.register_message_receive_handler(MSG_FA_FINISH, self._on_finish)

    def _on_round(self, msg: Message) -> None:
        prefixes = msg.get("prefixes")
        if prefixes is not None:  # TrieHH round state
            self.analyzer.cur_prefixes = list(prefixes)
            self.analyzer.prefix_len = int(msg.get("prefix_len", 1))
        self.analyzer.local_analyze(self.local_data, self.args)
        reply = Message(MSG_FA_SUBMIT, self.rank, 0)
        reply.add_params("submission", self.analyzer.get_client_submission())
        self.send_message(reply)

    def _on_finish(self, msg: Message) -> None:
        self.finish()


def run_cross_silo_fa(args: Any, client_datasets: Dict[int, Sequence],
                      backend: str = "INPROC") -> Any:
    """Convenience driver: server + one client manager per dataset on
    threads (reference fa/cross_silo entry)."""
    n = len(client_datasets)
    server = FAServerManager(args, rank=0, size=n + 1, backend=backend)
    clients = [FAClientManager(args, data, rank=rank, size=n + 1,
                               backend=backend)
               for rank, (_, data) in enumerate(
                   sorted(client_datasets.items()), start=1)]
    threads = [threading.Thread(target=c.run, daemon=True,
                                name=f"fa-client-{c.rank}") for c in clients]
    for t in threads:
        t.start()
    try:
        server.run()
    finally:
        # reap the client loops instead of abandoning daemon threads (they
        # hold comm queues that would otherwise outlive this call).  On the
        # error path — the comm base's dispatch guard re-raises a handler
        # crash out of run() — the clients never saw FA_FINISH, so stop
        # their receive loops explicitly or the joins would time out
        for c, t in zip(clients, threads):
            if t.is_alive():
                try:
                    c.finish()
                except Exception:
                    # one client's broken transport must not abort the
                    # sweep (or mask the original error from run())
                    logging.exception("FA client %d: finish() during "
                                      "teardown failed", c.rank)
        for t in threads:
            t.join(timeout=30)
    return server.result
