"""Federated Analytics (FA) — base frame + analyzers + SP simulator.

Capability parity: reference `fa/` (2.6k LoC mini-framework): base classes
(`fa/base_frame/client_analyzer.py`, `server_aggregator.py`), local analyzers
+ aggregators for avg, intersection (PSI), union, cardinality, frequency
estimation, k-percentile, heavy-hitter TrieHH (`fa/local_analyzer/`,
`fa/aggregator/`, `fa/utils/trie.py`), and the SP simulator
(`fa/simulation/sp/simulator.py`).
"""

from __future__ import annotations

import abc
import hashlib
import logging
from collections import Counter
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


class FAClientAnalyzer(abc.ABC):
    def __init__(self, args: Any = None) -> None:
        self.args = args
        self.id = 0
        self.client_submission: Any = None

    def set_id(self, client_id: int) -> None:
        self.id = client_id

    def get_client_submission(self) -> Any:
        return self.client_submission

    def set_client_submission(self, v: Any) -> None:
        self.client_submission = v

    @abc.abstractmethod
    def local_analyze(self, train_data: Sequence, args: Any = None) -> None:
        ...


class FAServerAggregator(abc.ABC):
    def __init__(self, args: Any = None) -> None:
        self.args = args
        self.server_data: Any = None

    @abc.abstractmethod
    def aggregate(self, local_submissions: List[Any]) -> Any:
        ...


# ---------------------------------------------------------------------------
# analyzers / aggregators
# ---------------------------------------------------------------------------

class AvgAnalyzer(FAClientAnalyzer):
    def local_analyze(self, train_data, args=None):
        vals = np.asarray(list(train_data), np.float64)
        self.set_client_submission((float(vals.mean()), len(vals)))


class AvgAggregator(FAServerAggregator):
    def aggregate(self, subs):
        tot = sum(n for _, n in subs)
        self.server_data = sum(m * n for m, n in subs) / max(tot, 1)
        return self.server_data


class IntersectionAnalyzer(FAClientAnalyzer):
    def local_analyze(self, train_data, args=None):
        self.set_client_submission(set(train_data))


class IntersectionAggregator(FAServerAggregator):
    """PSI capability: set intersection across clients."""

    def aggregate(self, subs):
        out = set(subs[0])
        for s in subs[1:]:
            out &= set(s)
        self.server_data = out
        return out


class UnionAggregator(FAServerAggregator):
    def aggregate(self, subs):
        out = set()
        for s in subs:
            out |= set(s)
        self.server_data = out
        return out


class CardinalityAggregator(FAServerAggregator):
    def aggregate(self, subs):
        out = set()
        for s in subs:
            out |= set(s)
        self.server_data = len(out)
        return self.server_data


class FrequencyAnalyzer(FAClientAnalyzer):
    def local_analyze(self, train_data, args=None):
        self.set_client_submission(Counter(train_data))


class FrequencyAggregator(FAServerAggregator):
    def aggregate(self, subs):
        total: Counter = Counter()
        for c in subs:
            total.update(c)
        n = sum(total.values())
        self.server_data = {k: v / n for k, v in total.items()}
        return self.server_data


class KPercentileAnalyzer(FAClientAnalyzer):
    def local_analyze(self, train_data, args=None):
        self.set_client_submission(sorted(float(v) for v in train_data))


class KPercentileAggregator(FAServerAggregator):
    """Exact k-percentile over pooled sorted client lists (the reference
    implements an iterative secure variant; capability = the statistic)."""

    def __init__(self, args=None, k: float = 50.0) -> None:
        super().__init__(args)
        self.k = float(getattr(args, "k_percentile", k) or k)

    def aggregate(self, subs):
        pooled = np.concatenate([np.asarray(s, np.float64) for s in subs])
        self.server_data = float(np.percentile(pooled, self.k))
        return self.server_data


class TrieHHAnalyzer(FAClientAnalyzer):
    """Heavy-hitter discovery: each round a sampled client votes for the
    prefix of its (hashed-selected) item extending the current trie."""

    def __init__(self, args=None) -> None:
        super().__init__(args)
        self.cur_prefixes: List[str] = [""]
        self.prefix_len = 1

    def local_analyze(self, train_data, args=None):
        votes: Counter = Counter()
        for w in train_data:
            w = str(w)
            for p in self.cur_prefixes:
                if w.startswith(p) and len(w) >= self.prefix_len:
                    votes[w[: self.prefix_len]] += 1
        self.set_client_submission(votes)


class TrieHHAggregator(FAServerAggregator):
    def __init__(self, args=None, theta: int = 2, max_len: int = 10) -> None:
        super().__init__(args)
        self.theta = int(getattr(args, "triehh_theta", theta) or theta)
        self.max_len = int(getattr(args, "triehh_max_len", max_len) or max_len)

    def aggregate(self, subs):
        votes: Counter = Counter()
        for c in subs:
            votes.update(c)
        self.server_data = sorted(
            p for p, v in votes.items() if v >= self.theta)
        return self.server_data


FA_TASKS: Dict[str, Tuple[type, type]] = {
    "avg": (AvgAnalyzer, AvgAggregator),
    "intersection": (IntersectionAnalyzer, IntersectionAggregator),
    "union": (IntersectionAnalyzer, UnionAggregator),
    "cardinality": (IntersectionAnalyzer, CardinalityAggregator),
    "frequency": (FrequencyAnalyzer, FrequencyAggregator),
    "k_percentile": (KPercentileAnalyzer, KPercentileAggregator),
    "heavy_hitter_triehh": (TrieHHAnalyzer, TrieHHAggregator),
}


class FASimulator:
    """SP simulator (reference `fa/simulation/sp/simulator.py`): run the
    analyzer on every client's data, aggregate on the server.  TrieHH runs
    ``comm_round`` prefix-extension rounds."""

    def __init__(self, args: Any, client_datasets: Dict[int, Sequence]):
        self.args = args
        self.datasets = client_datasets
        task = str(getattr(args, "fa_task", "avg")).lower()
        if task not in FA_TASKS:
            raise ValueError(f"unknown FA task {task!r}; known: "
                             f"{sorted(FA_TASKS)}")
        a_cls, g_cls = FA_TASKS[task]
        self.task = task
        self.analyzer = a_cls(args)
        self.aggregator = g_cls(args)

    def run(self) -> Any:
        if self.task == "heavy_hitter_triehh":
            return self._run_triehh()
        subs = []
        for cid, data in self.datasets.items():
            self.analyzer.set_id(cid)
            self.analyzer.local_analyze(data, self.args)
            subs.append(self.analyzer.get_client_submission())
        result = self.aggregator.aggregate(subs)
        logging.info("FA %s result: %s", self.task, result)
        return result

    def _run_triehh(self) -> List[str]:
        rounds = int(getattr(self.args, "comm_round", 5) or 5)
        prefixes = [""]
        for r in range(rounds):
            self.analyzer.cur_prefixes = prefixes
            self.analyzer.prefix_len = r + 1
            subs = []
            for cid, data in self.datasets.items():
                self.analyzer.set_id(cid)
                self.analyzer.local_analyze(data, self.args)
                subs.append(self.analyzer.get_client_submission())
            new_prefixes = self.aggregator.aggregate(subs)
            if not new_prefixes:
                break
            prefixes = new_prefixes
        return prefixes
