"""Natural (per-user) federated partitions.

Capability parity: the reference's LEAF-family loaders return data keyed by
REAL client identity rather than a synthetic Dirichlet split — femnist per
writer, fed_shakespeare per speaker from client-keyed h5
(`/root/reference/python/fedml/data/fed_shakespeare/data_loader.py:24-90`),
stackoverflow per user (`.../stackoverflow_nwp/data_loader.py`), mnist per
LEAF user (`.../MNIST/data_loader.py:33-66` read_data), dispatched at
`.../data/data_loader.py:287-375`.  In every case the loader also OVERRIDES
``client_num_in_total`` with the number of natural users.

This module reads three client-keyed on-disk formats into one canonical
in-memory form ``{user: (x, y)}`` per split:

* **npz cache** (the framework's canonical format, what `fedml_tpu data
  import` emits): ``<name>_train.npz`` / ``<name>_test.npz`` with array
  pairs ``x_<user>`` / ``y_<user>``;
* **LEAF JSON** dirs (``train/*.json`` with keys users/user_data);
* **client-keyed HDF5** (fed_shakespeare/fed_cifar100 layout:
  ``examples/<user>/<field>``).

`load_natural(args)` then assembles the standard 8-tuple dataset with one
client per user, and stashes the global-row map Parrot's device-resident
gather needs.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

UserData = Dict[str, Tuple[np.ndarray, np.ndarray]]


# ---------------------------------------------------------------- readers
def read_npz_users(path: str) -> Optional[UserData]:
    """``x_<user>``/``y_<user>`` arrays → {user: (x, y)} (sorted users)."""
    if not os.path.exists(path):
        return None
    z = np.load(path, allow_pickle=False)
    users = sorted(k[2:] for k in z.files if k.startswith("x_"))
    out: UserData = {}
    for u in users:
        x = z["x_" + u]
        if np.issubdtype(x.dtype, np.integer) and x.ndim > 2:
            x = x.astype(np.float32) / 255.0  # uint8 image archives
        out[u] = (x, z["y_" + u])
    return out or None


def read_leaf_json_dir(split_dir: str) -> Optional[UserData]:
    """LEAF ``all_data*.json`` files (keys: users, user_data) → {user:
    (x, y)} — the reference's `read_data` contract."""
    if not os.path.isdir(split_dir):
        return None
    out: UserData = {}
    for fname in sorted(os.listdir(split_dir)):
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(split_dir, fname)) as f:
            blob = json.load(f)
        for u in blob.get("users", []):
            d = blob["user_data"][u]
            out[u] = (np.asarray(d["x"], np.float32),
                      np.asarray(d["y"]))
    return out or None


#: field-name preference for client-keyed h5 layouts: fed_shakespeare uses
#: snippets (byte strings → TFF char preprocessing), stackoverflow_nwp
#: uses tokens (byte sentences → word-vocab tokenization);
#: fed_cifar100 uses image+label (label is coarse_label's sibling)
_H5_X_FIELDS = ("snippets", "tokens", "image", "pixels", "x")
_H5_Y_FIELDS = ("label", "labels", "y")


def read_h5_users(path: str, x_field: Optional[str] = None,
                  y_field: Optional[str] = None) -> Optional[UserData]:
    """Reference-schema h5 (``examples/<user>/<field>``), TFF-exact:

    * ``snippets`` (fed_shakespeare,
      `data/fed_shakespeare/data_loader.py:24-47`): byte strings →
      char-vocab sequences of length 81 → x = seq[:, :-1],
      y = seq[:, 1:];
    * ``tokens`` (stackoverflow_nwp, `data/stackoverflow_nwp/dataset.py`
      + `utils.py:54-84`): byte sentences tokenized with the
      ``stackoverflow.word_count`` vocab living next to the h5;
    * ``image``/``pixels`` + ``label`` (fed_cifar100): arrays as-is.
    """
    if not os.path.exists(path):
        return None
    import h5py

    from .tff_text import (
        shakespeare_preprocess,
        split_next_token,
        stackoverflow_tokenize,
        stackoverflow_word_dict,
    )

    out: UserData = {}
    with h5py.File(path, "r") as h:
        grp = h["examples"]
        users = sorted(grp.keys())
        if not users:
            return None
        if x_field is None:
            fields = set(grp[users[0]].keys())
            x_field = next((f for f in _H5_X_FIELDS if f in fields), None)
            if x_field is None:
                raise KeyError(
                    f"no recognized x field in {path} (have {sorted(fields)},"
                    f" expected one of {_H5_X_FIELDS})")
            if y_field is None:
                y_field = next((f for f in _H5_Y_FIELDS if f in fields),
                               None)
        so_dict = None
        if x_field == "tokens":
            wc = os.path.join(os.path.dirname(path),
                              "stackoverflow.word_count")
            if not os.path.exists(wc):
                raise FileNotFoundError(
                    f"stackoverflow h5 needs the word-count vocab next to "
                    f"it ({wc}) — the reference's DEFAULT_WORD_COUNT_FILE")
            so_dict = stackoverflow_word_dict(wc)
        for u in users:
            raw = grp[u][x_field][()]
            arr = np.asarray(raw)
            numeric = np.issubdtype(arr.dtype, np.number)
            if x_field == "snippets" and not numeric:
                x, y = split_next_token(shakespeare_preprocess(raw))
            elif x_field == "tokens" and not numeric:
                x, y = split_next_token(
                    stackoverflow_tokenize(raw, so_dict))
            else:
                # numeric snippets/tokens = already-tokenized layout:
                # pass through (y=x → trainer derives next-token targets)
                x = arr
                y = np.asarray(grp[u][y_field]) if y_field else x
            out[u] = (x, y)
    return out or None


#: reference TFF archive stems: these h5 file names don't carry the
#: fedml dataset name (`fed_shakespeare/data_loader.py:15-16`,
#: `stackoverflow_nwp/data_loader.py:16-17`).  stackoverflow_lr is
#: deliberately ABSENT: its tag-prediction pipeline must not consume the
#: next-word-prediction archive.
_REFERENCE_H5_STEMS = {
    "fed_shakespeare": "shakespeare",
    "shakespeare": "shakespeare",
    "stackoverflow_nwp": "stackoverflow",
}


def _h5_stems(dataset: str):
    """Candidate file stems for <stem>_{train,test}.h5, most specific
    first (single source of truth for the naming rule)."""
    stems = [dataset, dataset.replace("fed_", "")]
    ref = _REFERENCE_H5_STEMS.get(dataset)
    if ref:
        stems.append(ref)
    return list(dict.fromkeys(stems))


# ---------------------------------------------------------------- assembly
def _natural_paths(cache_dir: str, dataset: str) -> Tuple[str, str]:
    base = dataset.replace("fed_", "")
    for stem in (dataset, base, f"leaf_{base}"):
        tr = os.path.join(cache_dir, f"{stem}_train.npz")
        if os.path.exists(tr):
            return tr, os.path.join(cache_dir, f"{stem}_test.npz")
    return (os.path.join(cache_dir, f"{dataset}_train.npz"),
            os.path.join(cache_dir, f"{dataset}_test.npz"))


def load_user_splits(cache_dir: str, dataset: str
                     ) -> Optional[Tuple[UserData, UserData]]:
    """Try the cache formats in order: npz cache, LEAF JSON dir, h5."""
    tr_path, te_path = _natural_paths(cache_dir, dataset)
    train = read_npz_users(tr_path)
    if train is not None:
        test = read_npz_users(te_path) or {}
        return train, test

    leaf_root = os.path.join(cache_dir, dataset.upper())
    if not os.path.isdir(leaf_root):
        leaf_root = os.path.join(cache_dir, dataset)
    train = read_leaf_json_dir(os.path.join(leaf_root, "train"))
    if train is not None:
        test = read_leaf_json_dir(os.path.join(leaf_root, "test")) or {}
        return train, test

    for stem in _h5_stems(dataset):
        h5_tr = os.path.join(cache_dir, f"{stem}_train.h5")
        train = read_h5_users(h5_tr)
        if train is not None:
            test = read_h5_users(
                os.path.join(cache_dir, f"{stem}_test.h5")) or {}
            return train, test
    return None


def load_natural(args: Any, class_num: int = 0) -> Optional[Tuple]:
    """Standard 8-tuple dataset with ONE CLIENT PER NATURAL USER, or None
    when no client-keyed files exist.  Mirrors the reference loaders'
    side effect: ``args.client_num_in_total`` becomes the user count.
    ``class_num`` 0 → derived from the observed labels (max+1), so an
    imported dataset with an unknown name never silently trains a
    10-class head against a wider label space."""
    cache_dir = str(getattr(args, "data_cache_dir", "") or "")
    dataset = str(getattr(args, "dataset", ""))
    if not cache_dir:
        return None
    splits = load_user_splits(cache_dir, dataset)
    if splits is None:
        return None
    train_by_user, test_by_user = splits
    users: List[str] = sorted(train_by_user.keys())

    xs, ys, row_map = [], [], {}
    train_local, test_local, local_num = {}, {}, {}
    row = 0
    xe_all, ye_all = [], []
    for cid, u in enumerate(users):
        x, y = train_by_user[u]
        train_local[cid] = (x, y)
        local_num[cid] = int(len(y))
        row_map[cid] = np.arange(row, row + len(y), dtype=np.int64)
        row += len(y)
        xs.append(x)
        ys.append(y)
        xt, yt = test_by_user.get(u, (x[:0], y[:0]))
        test_local[cid] = (xt, yt)
        xe_all.append(xt)
        ye_all.append(yt)

    x_train = np.concatenate(xs)
    y_train = np.concatenate(ys)
    x_test = np.concatenate(xe_all) if xe_all else x_train[:0]
    y_test = np.concatenate(ye_all) if ye_all else y_train[:0]

    if not class_num:
        if np.issubdtype(y_train.dtype, np.integer):
            class_num = int(y_train.max()) + 1
            if len(y_test):
                class_num = max(class_num, int(y_test.max()) + 1)
        else:
            raise ValueError(
                "cannot infer class_num from non-integer labels; pass a "
                "known dataset name or extend DATASET_CLASSES")

    setattr(args, "client_num_in_total", len(users))
    setattr(args, "client_row_map", row_map)
    setattr(args, "natural_users", users)
    logging.info("natural partition: %d users, %d train / %d test samples",
                 len(users), len(y_train), len(y_test))
    return (len(y_train), len(y_test), (x_train, y_train),
            (x_test, y_test), local_num, train_local, test_local,
            class_num)


# ---------------------------------------------------------------- import
def import_to_cache(src: str, dataset: str, cache_dir: str,
                    fmt: str = "auto") -> Dict[str, Any]:
    """``fedml_tpu data import``: convert a standard download (LEAF JSON
    dir with train/+test/, or a client-keyed h5 pair) into the npz cache
    format the natural loader reads.  Returns a summary dict."""
    os.makedirs(cache_dir, exist_ok=True)
    readers = []
    if fmt in ("auto", "leaf"):
        readers.append(("leaf", lambda split: read_leaf_json_dir(
            os.path.join(src, split))))
    if fmt in ("auto", "h5"):
        def _h5(split):
            for stem in _h5_stems(dataset):
                got = read_h5_users(os.path.join(src, f"{stem}_{split}.h5"))
                if got is not None:
                    return got
            return None

        readers.append(("h5", _h5))
    if fmt in ("auto", "npz"):
        readers.append(("npz", lambda split: read_npz_users(
            os.path.join(src, f"{dataset}_{split}.npz"))))

    train = test = None
    used = None
    for name, rd in readers:
        train = rd("train")
        if train is not None:
            test = rd("test") or {}
            used = name
            break
    if train is None:
        raise FileNotFoundError(
            f"no client-keyed data found under {src} (tried formats: "
            f"{[n for n, _ in readers]})")

    for split, data in (("train", train), ("test", test)):
        arrs = {}
        for u, (x, y) in data.items():
            arrs["x_" + u] = x
            arrs["y_" + u] = np.asarray(y)
        np.savez_compressed(
            os.path.join(cache_dir, f"{dataset}_{split}.npz"), **arrs)
    sizes = [len(y) for _, y in train.values()]
    return {"dataset": dataset, "format": used, "users": len(train),
            "train_samples": int(np.sum(sizes)),
            "out": os.path.join(cache_dir, f"{dataset}_train.npz")}
