"""Dataset sources.

Capability parity: reference `data/` loaders for mnist, femnist, cifar10/100,
cinic10, (fed_)shakespeare, stackoverflow, adult-style tabular
(`data/data_loader.py:247-580`).  The reference auto-downloads from S3
(`constants.py:34`); this build is zero-egress, so each source tries the local
cache (``data_cache_dir``: .npz files or torchvision layout) and otherwise
generates a DETERMINISTIC synthetic stand-in with identical shapes/classes —
class-structured so FL convergence tests are meaningful.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import numpy as np

Arrays = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]

_SHAKESPEARE_SNIPPET = (
    "to be or not to be that is the question whether tis nobler in the mind "
    "to suffer the slings and arrows of outrageous fortune or to take arms "
    "against a sea of troubles and by opposing end them to die to sleep no "
    "more and by a sleep to say we end the heartache and the thousand natural "
    "shocks that flesh is heir to tis a consummation devoutly to be wished "
    "all the worlds a stage and all the men and women merely players they "
    "have their exits and their entrances and one man in his time plays many "
    "parts his acts being seven ages the quality of mercy is not strained it "
    "droppeth as the gentle rain from heaven upon the place beneath it is "
    "twice blest it blesseth him that gives and him that takes "
)


from .tff_text import shakespeare_vocab_size, stackoverflow_vocab_size

#: single source of truth for label-space sizes (used by load_arrays AND
#: the natural-partition path, so they can never drift apart); the text
#: vocab sizes come from the TFF-exact preprocessing module so they can't
#: diverge from the tokenizers
DATASET_CLASSES = {
    "mnist": 10, "femnist": 62,
    "cifar10": 10, "cifar100": 100, "cinic10": 10, "fed_cifar100": 100,
    "shakespeare": shakespeare_vocab_size(),
    "fed_shakespeare": shakespeare_vocab_size(),
    "stackoverflow_nwp": stackoverflow_vocab_size(),
    "stackoverflow_lr": 500,
    "ilsvrc2012": 1000, "imagenet": 1000,
    "gld23k": 203, "gld160k": 2028,
}


def dataset_class_num(dataset: str, default: int = 10) -> int:
    return DATASET_CLASSES.get(dataset.lower(), default)


def _try_npz(cache_dir: str, name: str) -> Optional[Arrays]:
    path = os.path.join(cache_dir, f"{name}.npz")
    if os.path.exists(path):
        z = np.load(path)

        def _x(a: np.ndarray) -> np.ndarray:
            # uint8 image archives (the standard ingest format) → [0,1] floats
            if np.issubdtype(a.dtype, np.integer):
                return a.astype(np.float32) / 255.0
            return a

        return (_x(z["x_train"]), z["y_train"].astype(np.int64),
                _x(z["x_test"]), z["y_test"].astype(np.int64))
    return None


def _try_torchvision(cache_dir: str, name: str) -> Optional[Arrays]:
    try:
        import torchvision  # type: ignore

        cls = {"mnist": torchvision.datasets.MNIST,
               "cifar10": torchvision.datasets.CIFAR10,
               "cifar100": torchvision.datasets.CIFAR100}.get(name)
        if cls is None:
            return None
        tr = cls(cache_dir, train=True, download=False)
        te = cls(cache_dir, train=False, download=False)
        xt = np.asarray(tr.data, np.float32) / 255.0
        xe = np.asarray(te.data, np.float32) / 255.0
        if xt.ndim == 3:
            xt, xe = xt[..., None], xe[..., None]
        return (xt, np.asarray(tr.targets, np.int64),
                xe, np.asarray(te.targets, np.int64))
    except Exception:
        return None


def _synthetic_images(shape: Tuple[int, ...], n_classes: int, n_train: int,
                      n_test: int, seed: int, hard: bool = False) -> Arrays:
    """Class-structured images: per-class template + noise, so linear/conv
    models can actually learn (deterministic).  Large images (≥96px) build
    templates at low resolution and upsample, and add noise in float32
    batches, keeping peak memory ~n·H·W·C·4 bytes instead of several GB.

    ``hard=True`` (the north-star bench data): the plain construction
    saturates at test acc 1.0 at 50k scale, which makes an accuracy guard
    weak evidence.  Hard mode adds per-sample class MIXING (convex combo
    of two class templates, label = dominant — irreducible ambiguity near
    the 0.5 boundary), per-sample affine jitter (random ±3px roll — a
    template-memorizing degenerate model can't be shift-robust) and
    intensity scaling, plus train-label noise, so a ResNet-class model
    plateaus below 1.0 like real CIFAR."""
    rng = np.random.RandomState(seed)
    h, w = shape[0], shape[1]
    lowres = h >= 96
    if lowres:  # store 16px templates; upsample per gathered batch
        templates = rng.rand(n_classes, 16, 16,
                             *shape[2:]).astype(np.float32)
    else:
        templates = rng.rand(n_classes, *shape).astype(np.float32)

    def make(n, train):
        y = rng.randint(0, n_classes, size=n)
        x = templates[y]
        if hard:
            # convex mix with a second class (BEFORE the lowres upsample —
            # nearest-neighbor repeat commutes with the convex combination)
            y2 = rng.randint(0, n_classes, size=n)
            lam = rng.uniform(0.60, 1.0, size=n).astype(np.float32)
            lam_b = lam.reshape((n,) + (1,) * (x.ndim - 1))
            x = lam_b * x + (1.0 - lam_b) * templates[y2]
        if lowres:
            x = np.repeat(np.repeat(x, -(-h // 16), axis=1),
                          -(-w // 16), axis=2)[:, :h, :w]
        noise = rng.standard_normal(size=x.shape).astype(np.float32)
        x = np.clip(x + 0.35 * noise, 0.0, 1.0).astype(np.float32)
        if hard:
            # per-sample affine jitter: random roll + intensity scale.
            # Group by the 49 distinct (dy,dx) shifts — one vectorized
            # roll per group instead of a Python loop over every sample.
            sh = rng.randint(-3, 4, size=(n, 2))
            for dy in range(-3, 4):
                for dx in range(-3, 4):
                    if dy == 0 and dx == 0:
                        continue
                    sel = (sh[:, 0] == dy) & (sh[:, 1] == dx)
                    if sel.any():
                        x[sel] = np.roll(x[sel], (dy, dx), axis=(1, 2))
            x *= rng.uniform(0.8, 1.2, size=n).astype(
                np.float32).reshape((n,) + (1,) * (x.ndim - 1))
            # clip back to [0,1]: the uint8 npz export quantizes by 255,
            # so values past 1.0 would WRAP and corrupt bright pixels
            x = np.clip(x, 0.0, 1.0)
            if train:
                flip = rng.rand(n) < 0.02          # 2% train label noise
                y = np.where(flip, rng.randint(0, n_classes, size=n), y)
        return x.astype(np.float32), y.astype(np.int64)

    xt, yt = make(n_train, True)
    xe, ye = make(n_test, False)
    return xt, yt, xe, ye


def synthetic_classification(n_features: int = 60, n_classes: int = 10,
                             n_train: int = 2000, n_test: int = 500,
                             seed: int = 0) -> Arrays:
    """LEAF/Li-et-al-style synthetic logistic data (reference
    `data/synthetic_*`): y = argmax(Wx + b) with gaussian x."""
    rng = np.random.RandomState(seed)
    W = rng.randn(n_features, n_classes).astype(np.float32)
    b = rng.randn(n_classes).astype(np.float32)

    def make(n):
        x = rng.randn(n, n_features).astype(np.float32)
        logits = x @ W + b + 0.1 * rng.randn(n, n_classes)
        return x, np.argmax(logits, axis=1).astype(np.int64)

    xt, yt = make(n_train)
    xe, ye = make(n_test)
    return xt, yt, xe, ye


def shakespeare_sequences(seq_len: int = 80, n_train: int = 2000,
                          n_test: int = 400, seed: int = 0,
                          cache_dir: str = "") -> Arrays:
    """Char-level next-char sequences, vocab 90 (reference fed_shakespeare).
    Uses the full corpus from cache if present, else the embedded snippet."""
    text = _SHAKESPEARE_SNIPPET * 50
    if cache_dir:
        p = os.path.join(cache_dir, "shakespeare.txt")
        if os.path.exists(p):
            with open(p, "r", errors="ignore") as f:
                text = f.read()
    codes = np.frombuffer(text.encode("ascii", "ignore"), dtype=np.uint8)
    codes = np.clip(codes - 32, 0, 89).astype(np.int64)  # printable → [0,90)
    rng = np.random.RandomState(seed)

    def make(n):
        starts = rng.randint(0, max(len(codes) - seq_len - 1, 1), size=n)
        x = np.stack([codes[s:s + seq_len] for s in starts])
        y = np.stack([codes[s + 1:s + seq_len + 1] for s in starts])
        return x, y

    xt, yt = make(n_train)
    xe, ye = make(n_test)
    return xt, yt, xe, ye


def adult_tabular(n_train: int = 4000, n_test: int = 1000, seed: int = 0,
                  n_features: int = 105) -> Arrays:
    """Adult-census-style binary tabular data for vertical FL (reference
    `model/finance/` VFL usage); synthetic logistic ground truth."""
    rng = np.random.RandomState(seed)
    w = rng.randn(n_features).astype(np.float32)

    def make(n):
        x = rng.randn(n, n_features).astype(np.float32)
        p = 1.0 / (1.0 + np.exp(-(x @ w) / np.sqrt(n_features) * 3.0))
        return x, (rng.rand(n) < p).astype(np.int64)

    xt, yt = make(n_train)
    xe, ye = make(n_test)
    return xt, yt, xe, ye


def synthetic_segmentation(n_train: int = 800, n_test: int = 160,
                           seed: int = 0, size: int = 24,
                           n_classes: int = 4) -> Arrays:
    """Per-pixel labeled images for federated segmentation (reference
    `simulation/mpi/fedseg/` capability): random rectangles of class c drawn
    on background class 0; x carries class-correlated intensity."""
    rng = np.random.RandomState(seed)

    def make(n):
        x = 0.1 * rng.rand(n, size, size, 3).astype(np.float32)
        y = np.zeros((n, size, size), np.int64)
        for i in range(n):
            for _ in range(2):
                c = rng.randint(1, n_classes)
                h0, w0 = rng.randint(0, size - 6, 2)
                h1, w1 = h0 + rng.randint(4, 7), w0 + rng.randint(4, 7)
                y[i, h0:h1, w0:w1] = c
                x[i, h0:h1, w0:w1, :] = c / n_classes + 0.1 * rng.rand(
                    h1 - h0, w1 - w0, 3)
        return x, y

    xt, yt = make(n_train)
    xe, ye = make(n_test)
    return xt, yt, xe, ye


def stackoverflow_lr_bow(n_train: int = 4000, n_test: int = 800,
                         seed: int = 0, vocab: int = 10004,
                         n_tags: int = 500) -> Arrays:
    """StackOverflow tag-prediction bag-of-words (reference
    `data/stackoverflow_lr/data_loader.py`): x = sparse word counts over a
    10k vocab, y = tag id.  Synthetic: each tag has a characteristic word
    distribution, so a linear model is learnable."""
    rng = np.random.RandomState(seed)
    # each tag prefers a small set of vocabulary words
    tag_words = rng.randint(0, vocab, size=(n_tags, 12))

    def make(n):
        y = rng.randint(0, n_tags, size=n)
        x = np.zeros((n, vocab), np.float32)
        rows = np.repeat(np.arange(n), 12)
        np.add.at(x, (rows, tag_words[y].ravel()), 1.0)
        noise = rng.randint(0, vocab, size=(n, 6))
        np.add.at(x, (np.repeat(np.arange(n), 6), noise.ravel()), 1.0)
        return x / np.maximum(x.sum(1, keepdims=True), 1.0), y.astype(np.int64)

    xt, yt = make(n_train)
    xe, ye = make(n_test)
    return xt, yt, xe, ye


def leaf_synthetic(alpha: float, beta: float, n_features: int = 60,
                   n_classes: int = 10, n_clusters: int = 10,
                   n_train: int = 2000, n_test: int = 500,
                   seed: int = 0) -> Arrays:
    """LEAF SYNTHETIC(α, β) (reference `data/synthetic_0_0`,
    `data/synthetic_0.5_0.5`, `data/synthetic_1_1`): α scales how much each
    latent client cluster's model (W_k, b_k) deviates from a shared model,
    β scales how much each cluster's input distribution mean v_k deviates
    from zero; y = argmax(W_k x + b_k)."""
    rng = np.random.RandomState(seed)
    # per-cluster model deviations (full per-entry draws, as in LEAF's
    # W_k ~ N(u_k, 1): a scalar offset would shift every class logit
    # equally and never change argmax labels)
    dW = rng.randn(n_clusters, n_features, n_classes).astype(np.float32)
    db = rng.randn(n_clusters, n_classes).astype(np.float32)
    v = rng.randn(n_clusters)          # per-cluster feature-mean offsets
    W0 = rng.randn(n_features, n_classes).astype(np.float32)
    b0 = rng.randn(n_classes).astype(np.float32)

    def make(n):
        k = rng.randint(0, n_clusters, size=n)
        x = (rng.randn(n, n_features) + beta * v[k][:, None]).astype(
            np.float32)
        Wk = W0[None] + alpha * dW[k]
        bk = b0[None] + alpha * db[k]
        logits = np.einsum("nf,nfc->nc", x, Wk) + bk
        return x, np.argmax(logits, axis=1).astype(np.int64)

    xt, yt = make(n_train)
    xe, ye = make(n_test)
    return xt, yt, xe, ye


def nus_wide_features(n_train: int = 4000, n_test: int = 800,
                      seed: int = 0, n_low: int = 634, n_tag: int = 1000,
                      n_classes: int = 5) -> Arrays:
    """NUS-WIDE two-view features for vertical FL (reference
    `data/NUS_WIDE/nus_wide_data_loader.py`: 634-d low-level image features
    + 1000-d tag features, 5 selected label classes).  The two feature
    blocks are concatenated [image | tags]; VFL parties split on columns."""
    rng = np.random.RandomState(seed)
    d = n_low + n_tag
    centers = rng.randn(n_classes, d).astype(np.float32)

    def make(n):
        y = rng.randint(0, n_classes, size=n)
        x = centers[y] + rng.randn(n, d).astype(np.float32)
        # tag block is sparse non-negative counts in the real data
        x[:, n_low:] = np.maximum(x[:, n_low:] - 1.0, 0.0)
        return x.astype(np.float32), y.astype(np.int64)

    xt, yt = make(n_train)
    xe, ye = make(n_test)
    return xt, yt, xe, ye


def lending_club_tabular(n_train: int = 4000, n_test: int = 1000,
                         seed: int = 0, n_features: int = 90) -> Arrays:
    """lending-club loan-default binary classification (reference
    `data/lending_club_loan/` — finance VFL demo); synthetic logistic
    ground truth over 90 numeric features with class imbalance ~0.2."""
    rng = np.random.RandomState(seed)
    w = rng.randn(n_features).astype(np.float32)

    def make(n):
        x = rng.randn(n, n_features).astype(np.float32)
        score = (x @ w) / np.sqrt(n_features) * 3.0 - 1.4  # ~20% positives
        p = 1.0 / (1.0 + np.exp(-score))
        return x, (rng.rand(n) < p).astype(np.int64)

    xt, yt = make(n_train)
    xe, ye = make(n_test)
    return xt, yt, xe, ye


def text_topic_bow(n_train: int = 3000, n_test: int = 600, seed: int = 0,
                   vocab: int = 5000, n_topics: int = 20) -> Arrays:
    """Topic-classification bag-of-words (reference `data/fednlp/` text
    classification tasks, 20news-style: 20 topics).  Each topic has a
    characteristic word distribution so linear/MLP models are learnable."""
    rng = np.random.RandomState(seed)
    topic_words = rng.randint(0, vocab, size=(n_topics, 15))

    def make(n):
        y = rng.randint(0, n_topics, size=n)
        x = np.zeros((n, vocab), np.float32)
        rows = np.repeat(np.arange(n), 15)
        np.add.at(x, (rows, topic_words[y].ravel()), 1.0)
        noise = rng.randint(0, vocab, size=(n, 8))
        np.add.at(x, (np.repeat(np.arange(n), 8), noise.ravel()), 1.0)
        return x / np.maximum(x.sum(1, keepdims=True), 1.0), y.astype(np.int64)

    xt, yt = make(n_train)
    xe, ye = make(n_test)
    return xt, yt, xe, ye


def edge_case_poison(x: np.ndarray, y: np.ndarray, n_classes: int,
                     target_label: int = 1, frac: float = 0.05,
                     seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Edge-case backdoor examples (reference
    `data/edge_case_examples/` + `core/security/attack/edge_case_attack.py`):
    low-probability tail inputs (for images: a fixed corner trigger far from
    the class templates; for token sequences: a fixed rare token prefix) all
    labeled ``target_label``, with the label matching the base task's shape."""
    rng = np.random.RandomState(seed + 7)
    n = max(int(len(x) * frac), 8)
    if np.issubdtype(x.dtype, np.integer):  # token sequences
        hi = int(x.max()) + 1
        xe = rng.randint(0, max(hi, 2), size=(n,) + x.shape[1:]).astype(
            x.dtype)
        xe[..., :4] = hi - 1  # rare-token trigger prefix
    else:
        xe = rng.rand(n, *x.shape[1:]).astype(x.dtype)
        if xe.ndim == 4:  # stamp a deterministic corner trigger
            xe[:, :4, :4] = 1.0
    ye = np.full((n,) + y.shape[1:], target_label % n_classes, y.dtype)
    return xe, ye


def load_arrays(dataset: str, cache_dir: str, seed: int = 0,
                scale: float = 1.0, hard: bool = False) -> Tuple[Arrays, int]:
    """→ ((x_train, y_train, x_test, y_test), num_classes).  ``scale``
    shrinks the synthetic fallbacks for fast tests; ``hard`` applies the
    non-saturating construction (mixing/jitter/label noise) to synthetic
    IMAGE fallbacks — the north-star bench data regime."""
    dataset = dataset.lower()
    os.makedirs(cache_dir, exist_ok=True) if cache_dir else None
    sz = lambda n: max(int(n * scale), 64)

    if dataset in ("mnist", "femnist"):
        classes = dataset_class_num(dataset)
        real = _try_npz(cache_dir, dataset) or _try_torchvision(cache_dir,
                                                                dataset)
        return (real or _synthetic_images((28, 28, 1), classes, sz(6000),
                                          sz(1000), seed,
                                          hard=hard)), classes
    if dataset in ("cifar10", "cifar100", "cinic10", "fed_cifar100"):
        classes = dataset_class_num(dataset)
        key = "cifar100" if "100" in dataset else "cifar10"
        real = _try_npz(cache_dir, key) or _try_torchvision(cache_dir, key)
        return (real or _synthetic_images((32, 32, 3), classes, sz(5000),
                                          sz(1000), seed,
                                          hard=hard)), classes
    if dataset in ("shakespeare", "fed_shakespeare"):
        return shakespeare_sequences(80, sz(2000), sz(400), seed,
                                     cache_dir), 90
    if dataset == "stackoverflow_nwp":
        xt, yt, xe, ye = shakespeare_sequences(20, sz(2000), sz(400), seed)
        return (xt % 10004, yt % 10004, xe % 10004, ye % 10004), 10004
    if dataset == "stackoverflow_lr":
        return stackoverflow_lr_bow(sz(4000), sz(800), seed), 500
    if dataset in ("ilsvrc2012", "imagenet"):
        # reference data/ImageNet loader (`data_loader.py:375`); synthetic
        # fallback keeps the 1000-class 224px contract but few samples
        real = _try_npz(cache_dir, "ilsvrc2012")
        return (real or _synthetic_images((224, 224, 3), 1000,
                                          max(int(1300 * scale), 256),
                                          max(int(200 * scale), 64),
                                          seed)), 1000
    if dataset in ("gld23k", "gld160k"):
        # Google Landmarks federated splits (`data_loader.py:395,421`)
        classes = 203 if dataset == "gld23k" else 2028
        real = _try_npz(cache_dir, dataset)
        return (real or _synthetic_images((96, 96, 3), classes,
                                          max(sz(2000), classes),
                                          max(sz(400), classes),
                                          seed)), classes
    if dataset.startswith("edge_case_") or dataset.endswith("_poisoned"):
        # poisoned variant of a base dataset (`data_loader.py:582+`):
        # appends edge-case backdoor examples to the train split
        base = (dataset.replace("edge_case_", "").replace("_poisoned", "")
                or "cifar10")
        (xt, yt, xe, ye), classes = load_arrays(base, cache_dir, seed, scale)
        px, py = edge_case_poison(xt, yt, classes, seed=seed)
        return (np.concatenate([xt, px]), np.concatenate([yt, py]),
                xe, ye), classes
    if dataset in ("synthetic_seg", "fets2021", "autonomous_driving"):
        # fets2021: federated brain-tumor segmentation (reference
        # `data/FeTS2021/`); autonomous_driving: street-scene segmentation
        # (reference `data/AutonomousDriving/`) — both map to the per-pixel
        # CE segmentation engine on synthetic masks in the zero-egress image
        size = 24 if dataset == "synthetic_seg" else 32
        return synthetic_segmentation(sz(800), sz(160), seed, size=size), 4
    if dataset in ("adult", "uci", "uci_adult"):
        # reference `data/UCI/` adult-census loader
        return adult_tabular(sz(4000), sz(1000), seed), 2
    if dataset == "reddit":
        # reference `data/reddit/` next-word-prediction, 10k BPE vocab.
        # The synthetic stand-in maps the 90 base symbols bijectively onto
        # ids spread across the 10k range (learnable, and the model really
        # exercises its full vocab embedding/softmax)
        xt, yt, xe, ye = shakespeare_sequences(20, sz(2000), sz(400), seed)
        spread = lambda a: (a * 111) % 10000
        return (spread(xt), spread(yt), spread(xe), spread(ye)), 10000
    if dataset in ("fednlp", "20news", "agnews"):
        return text_topic_bow(sz(3000), sz(600), seed), 20
    if dataset in ("nus_wide", "nus-wide"):
        return nus_wide_features(sz(4000), sz(800), seed), 5
    if dataset in ("lending_club_loan", "lending_club"):
        return lending_club_tabular(sz(4000), sz(1000), seed), 2
    if dataset.startswith("synthetic_") and dataset != "synthetic_seg":
        # LEAF SYNTHETIC(α,β) names: synthetic_0_0 / _0.5_0.5 / _1_1
        parts = dataset.split("_")[1:]
        try:
            a, b = float(parts[0]), float(parts[1])
        except (IndexError, ValueError):
            a = b = 0.0
        return leaf_synthetic(a, b, n_train=sz(2000), n_test=sz(500),
                              seed=seed), 10
    # default synthetic
    return synthetic_classification(60, 10, sz(2000), sz(500), seed), 10
