"""TFF-exact text preprocessing for the reference h5 schemas.

Capability parity, byte-for-byte: the reference consumes the TFF archive
layouts and tokenizations —

* fed_shakespeare (`data/fed_shakespeare/utils.py:15-77`): h5 group
  ``examples/<client>/snippets`` of byte strings; char vocab
  ``[<pad>] + CHAR_VOCAB + [<bos>] + [<eos>]`` (+1 OOV bucket → 90 ids),
  each snippet becomes bos+chars+eos padded to multiples of
  SEQUENCE_LENGTH+1 and chunked; ``split`` yields x = seq[:, :-1],
  y = seq[:, 1:].
* stackoverflow_nwp (`data/stackoverflow_nwp/utils.py:27-84`): h5 group
  ``examples/<client>/tokens`` of byte sentences plus a
  ``stackoverflow.word_count`` file ("word count" per line); word vocab
  ``[<pad>] + 10k most frequent + [<bos>] + [<eos>]`` with OOV hashed to
  ``len(word_dict)`` (vocab 10004), sentences truncated to 20 words,
  bos/eos/pad to length 21.

These functions reproduce that preprocessing exactly (verified against
the reference's own utils in tests/test_natural_partition.py) so a real
TFF-schema archive dropped into ``data_cache_dir`` trains identically.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import zlib

import numpy as np

#: the TFF shakespeare char vocabulary, verbatim
#: (`fed_shakespeare/utils.py:18-20`)
SHAKESPEARE_CHAR_VOCAB = list(
    "dhlptx@DHLPTX $(,048cgkoswCGKOSW[_#'/37;?bfjnrvzBFJNRVZ\"&*.26:"
    "\naeimquyAEIMQUY]!%)-159\r"
)
SHAKESPEARE_SEQ_LEN = 80          # McMahan et al. AISTATS 2017
PAD, BOS, EOS = "<pad>", "<bos>", "<eos>"


def shakespeare_word_dict() -> Dict[str, int]:
    words = [PAD] + SHAKESPEARE_CHAR_VOCAB + [BOS] + [EOS]
    return {w: i for i, w in enumerate(words)}


def shakespeare_vocab_size() -> int:
    return len(shakespeare_word_dict()) + 1          # +1 OOV bucket


def shakespeare_preprocess(snippets: Iterable[bytes],
                           max_seq_len: int = SHAKESPEARE_SEQ_LEN
                           ) -> np.ndarray:
    """Byte snippets → [N, max_seq_len+1] int sequences (TFF-exact)."""
    wd = shakespeare_word_dict()
    oov = len(wd)
    bos, eos, pad = wd[BOS], wd[EOS], wd[PAD]
    out: List[List[int]] = []
    for sn in snippets:
        text = sn.decode("utf8") if isinstance(sn, (bytes, bytearray)) \
            else str(sn)
        tokens = [bos] + [wd.get(c, oov) for c in text] + [eos]
        if len(tokens) % (max_seq_len + 1) != 0:
            tokens += [pad] * ((-len(tokens)) % (max_seq_len + 1))
        out.extend(tokens[i:i + max_seq_len + 1]
                   for i in range(0, len(tokens), max_seq_len + 1))
    return np.asarray(out, np.int64).reshape(-1, max_seq_len + 1)


def split_next_token(seqs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """x = seq[:, :-1], y = seq[:, 1:] (`fed_shakespeare/utils.py:80-84`)."""
    ds = np.asarray(seqs)
    return ds[:, :-1], ds[:, 1:]


# ------------------------------------------------------------ stackoverflow
SO_SEQ_LEN = 20
SO_VOCAB_WORDS = 10_000


def stackoverflow_word_dict(word_count_path: str,
                            vocab_size: int = SO_VOCAB_WORDS
                            ) -> Dict[str, int]:
    """``stackoverflow.word_count`` ("word count" per line, frequency
    order) → the reference's OrderedDict vocab.  (Deviation: a file
    shorter than vocab_size yields a smaller vocab instead of the
    reference's StopIteration crash — lets small fixtures work.)"""
    frequent: List[str] = []
    with open(word_count_path) as f:
        for line in f:
            if len(frequent) >= vocab_size:
                break
            if line.strip():
                frequent.append(line.split()[0])
    words = [PAD] + frequent + [BOS] + [EOS]
    return {w: i for i, w in enumerate(words)}


def stackoverflow_tokenize(sentences: Iterable[bytes],
                           word_dict: Dict[str, int],
                           max_seq_len: int = SO_SEQ_LEN,
                           num_oov_buckets: int = 1) -> np.ndarray:
    """Byte sentences → [N, max_seq_len+1] ids (TFF-exact: truncate to
    max_seq_len words, bos prefix, eos only when short, pad to 21; OOV
    hashes into buckets past the vocab)."""
    n = len(word_dict)
    bos, eos, pad = word_dict[BOS], word_dict[EOS], word_dict[PAD]

    def wid(w: str) -> int:
        if w in word_dict:
            return word_dict[w]
        # Deterministic OOV bucketing (crc32, not Python's salted hash()):
        # token ids must not vary with PYTHONHASHSEED across processes.
        # Deviation from TFF's fingerprint64 — bucket ASSIGNMENT may differ
        # from TFF's, but is stable across runs, which TFF also guarantees.
        return zlib.crc32(w.encode("utf8")) % num_oov_buckets + n

    out = []
    for sn in sentences:
        text = sn.decode("utf8") if isinstance(sn, (bytes, bytearray)) \
            else str(sn)
        words = text.split(" ")[:max_seq_len]
        tokens = [wid(w) for w in words]
        if len(tokens) < max_seq_len:
            tokens = tokens + [eos]
        tokens = [bos] + tokens
        if len(tokens) < max_seq_len + 1:
            tokens += [pad] * (max_seq_len + 1 - len(tokens))
        out.append(tokens)
    return np.asarray(out, np.int64).reshape(-1, max_seq_len + 1)


def stackoverflow_vocab_size(vocab_size: int = SO_VOCAB_WORDS,
                             num_oov_buckets: int = 1) -> int:
    return vocab_size + 3 + num_oov_buckets          # pad/bos/eos + oov
