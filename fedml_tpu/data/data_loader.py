"""fedml_tpu.data.load(args) — the standard dataset tuple.

Capability parity: reference `data/data_loader.py:234-580` — returns
``[train_num, test_num, train_global, test_global, local_num_dict,
train_local_dict, test_local_dict, class_num]`` (consumed at
`simulation/sp/fedavg/fedavg_api.py:18-27`), with partition_method
"homo"/"hetero" + partition_alpha Dirichlet label skew.

TPU-first: "data loaders" are host numpy ``(x, y)`` tuples; batching/padding
to fixed shapes happens at the engine boundary (`ml/engine/local_update.py
make_batches`), so the data layer stays framework-free and the compiled
functions see static shapes only.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from .datasets import load_arrays
from .partition import partition, record_data_stats

DatasetTuple = Tuple[int, int, Tuple, Tuple, Dict, Dict, Dict, int]


def load(args: Any) -> DatasetTuple:
    dataset = str(getattr(args, "dataset", "synthetic"))
    cache_dir = str(getattr(args, "data_cache_dir", "") or "")
    seed = int(getattr(args, "random_seed", 0) or 0)
    n_clients = int(getattr(args, "client_num_in_total", 10))
    method = str(getattr(args, "partition_method", "hetero"))
    alpha = float(getattr(args, "partition_alpha", 0.5) or 0.5)
    scale = float(getattr(args, "data_scale", 1.0) or 1.0)

    # natural per-user partitions (LEAF family): client-keyed files beat
    # the synthetic Dirichlet split, mirroring the reference loaders
    # (`data/data_loader.py:287-375` always load femnist/shakespeare/
    # stackoverflow by their real users).  partition_method "natural"
    # REQUIRES them; the fed_* datasets use them opportunistically.
    if method == "natural" or dataset.startswith("fed_") \
            or dataset in ("femnist", "stackoverflow_nwp",
                           "stackoverflow_lr"):
        from .datasets import dataset_class_num
        from .natural import load_natural

        # unknown dataset names (default=0) derive class_num from labels
        out = load_natural(args, dataset_class_num(dataset, default=0))
        if out is not None:
            return out
        if method == "natural":
            raise FileNotFoundError(
                f"partition_method 'natural' needs client-keyed files for "
                f"{dataset!r} under {cache_dir!r} (run `fedml_tpu data "
                f"import` first); none found")

    (x_train, y_train, x_test, y_test), class_num = load_arrays(
        dataset, cache_dir, seed=seed, scale=scale,
        hard=bool(getattr(args, "synthetic_hard", False)))

    def _per_sample_label(y: np.ndarray) -> np.ndarray:
        if y.ndim == 1:
            return y
        if y.ndim == 2:  # token sequences → first token
            return y[:, 0]
        # dense masks (segmentation) → most frequent foreground class
        flat = y.reshape(len(y), -1)
        out = np.empty(len(y), flat.dtype)
        for i, row in enumerate(flat):
            fg = row[row > 0]
            out[i] = np.bincount(fg).argmax() if len(fg) else 0
        return out

    part_labels = _per_sample_label(y_train)
    net_dataidx_map = partition(part_labels, n_clients, method, alpha, seed)
    test_map = partition(_per_sample_label(y_test),
                         n_clients, "homo", alpha, seed + 1)

    train_local: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    test_local: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    local_num: Dict[int, int] = {}
    for cid in range(n_clients):
        idx = net_dataidx_map[cid]
        train_local[cid] = (x_train[idx], y_train[idx])
        local_num[cid] = int(len(idx))
        tidx = test_map[cid]
        test_local[cid] = (x_test[tidx], y_test[tidx])

    stats = record_data_stats(part_labels, net_dataidx_map)
    setattr(args, "data_stats", stats)
    # global-row index map per client, for the Parrot device-resident gather
    setattr(args, "client_row_map",
            {c: np.asarray(v, np.int64) for c, v in net_dataidx_map.items()})

    return (len(y_train), len(y_test), (x_train, y_train), (x_test, y_test),
            local_num, train_local, test_local, class_num)
