"""Virtual client populations for hyper-scale simulation.

The standard loader (`data_loader.load`) materializes one ``(x, y)``
tuple per client — fine at 10²–10³ clients, fatal at 10⁵–10⁶: a million
dict entries plus a ``[N, cap]`` row-index matrix is gigabytes of host
memory before a single round runs.  A :class:`ClientPopulation` instead
keeps ONE base array pair and derives each client's row indices
**lazily** from a counter-based RNG (Philox keyed by a sha256 digest of
``(seed, cid)``), so the only O(N) state is the ``sizes`` vector
(~4 MB at 10⁶ clients int32).  Determinism is positional, not
sequential: client 734_211's rows are the same whether it is the first
client ever solicited or the millionth, which is what makes
crash-resume and distributed cohort assembly reproducible.

Mirrors FedJAX's ``ClientDataset``-over-shared-arrays idiom (arxiv
2108.02117) without materializing the per-client views.
"""

from __future__ import annotations

import hashlib
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "ClientPopulation",
    "philox_generator",
    "zipf_sizes",
    "size_hist",
    "expand_size_hist",
    "decode_sizes",
    "load_population",
]


def philox_generator(*parts: Any) -> np.random.Generator:
    """Counter-based generator keyed by a sha256 digest of ``parts``.

    sha256 (not python ``hash()``, which is salted per-process) so the
    stream for a given ``(run_id, seed, round)`` or ``(seed, cid)`` is
    identical across processes, hosts and restarts."""
    digest = hashlib.sha256(
        ":".join(str(p) for p in parts).encode()).digest()
    key = int.from_bytes(digest[:16], "little")
    return np.random.Generator(np.random.Philox(key=key))


def zipf_sizes(n_clients: int, seed: int = 0, exponent: float = 1.2,
               min_size: int = 8, max_size: int = 4096) -> np.ndarray:
    """Heavy-tailed per-client dataset sizes (Zipf-ish, bounded).

    Real federated populations are dominated by small clients with a
    long tail of heavy ones (LEAF, Parrot §4); a bounded power law over
    ranks reproduces that histogram deterministically."""
    if n_clients <= 0:
        return np.zeros(0, np.int64)
    g = philox_generator("zipf_sizes", seed, n_clients, exponent)
    # bounded Pareto: size = min·(1-u)^(-1/α) clipped at max — the bulk
    # sits near min with a polynomial tail (top 1% of clients hold ~16%
    # of all samples at α=1.2)
    u = g.random(n_clients)
    sizes = min_size * (1.0 - u) ** (-1.0 / float(exponent))
    return np.clip(np.round(sizes), min_size, max_size).astype(np.int64)


def size_hist(sizes: np.ndarray) -> list:
    """Compact histogram encoding of a per-client size vector:
    ascending ``[[size, count], ...]`` pairs.

    The multiset of sizes is preserved exactly — everything downstream
    of the committed artifacts (``bucket_plan`` strata, PERF003 padding
    stats, slot-utilization acceptance) is a function of the multiset,
    so a 100k-line ``"sizes"`` array compresses to a few thousand pairs
    with identical results.  Per-client ORDER is not preserved; the
    decoded vector is sorted ascending."""
    vals, counts = np.unique(np.asarray(sizes, np.int64),
                             return_counts=True)
    return [[int(v), int(c)] for v, c in zip(vals, counts)]


def expand_size_hist(hist: Any) -> np.ndarray:
    """Inverse of `size_hist`: ``[[size, count], ...]`` → sorted int64
    per-client size vector."""
    if not hist:
        return np.zeros(0, np.int64)
    arr = np.asarray(hist, np.int64).reshape(-1, 2)
    return np.repeat(arr[:, 0], arr[:, 1])


def decode_sizes(payload: Any) -> np.ndarray:
    """Loader shim for committed size files: accepts the legacy dense
    form (``{"sizes": [...]}`` or a bare list) and the compact histogram
    form (``{"size_hist": [[size, count], ...]}``)."""
    if isinstance(payload, dict):
        if "size_hist" in payload:
            return expand_size_hist(payload["size_hist"])
        return np.asarray(payload["sizes"], np.int64)
    return np.asarray(payload, np.int64)


class ClientPopulation:
    """A (possibly virtual) population of simulated clients over one
    shared base array pair.

    Two construction modes:

    - :meth:`from_dataset` wraps the standard loader's output — every
      client's rows come from ``args.client_row_map``, so trajectories
      are bit-identical to the device-resident ParrotAPI path.  Used for
      parity configs and any population that fits the loader.
    - :meth:`virtual` scales to 10⁵–10⁶ clients: client ``cid`` draws
      ``sizes[cid]`` rows from the base arrays via a Philox stream keyed
      on ``(seed, cid)`` — computed on demand, never stored.
    """

    def __init__(self, x: np.ndarray, y: np.ndarray, sizes: np.ndarray,
                 rows_fn: Callable[[int], np.ndarray],
                 test: Tuple[np.ndarray, np.ndarray],
                 class_num: int, virtual: bool, seed: int = 0):
        self.x = x
        self.y = y
        self.sizes = np.asarray(sizes, np.int64)
        self.n_clients = int(len(self.sizes))
        self._rows_fn = rows_fn
        self.test = test
        self.class_num = int(class_num)
        self.virtual = bool(virtual)
        self.seed = int(seed)

    def rows(self, cid: int) -> np.ndarray:
        """Row indices into ``self.x``/``self.y`` for one client."""
        return self._rows_fn(int(cid))

    @property
    def total_samples(self) -> int:
        return int(self.sizes.sum())

    # ------------------------------------------------------------------
    @classmethod
    def from_dataset(cls, args: Any, dataset: Tuple) -> "ClientPopulation":
        """Parity mode: identical client→row mapping to the standard
        loader (requires ``args.client_row_map``, set by ``data.load``)."""
        (_tn, _te, train_global, test_global, local_num,
         _trl, _tel, class_num) = dataset
        row_map: Dict[int, np.ndarray] = getattr(args, "client_row_map")
        n = int(getattr(args, "client_num_in_total", len(row_map)))
        sizes = np.asarray([len(row_map[c]) for c in range(n)], np.int64)
        x, y = train_global
        return cls(np.asarray(x), np.asarray(y), sizes,
                   lambda cid: np.asarray(row_map[cid], np.int64),
                   (np.asarray(test_global[0]), np.asarray(test_global[1])),
                   class_num, virtual=False,
                   seed=int(getattr(args, "random_seed", 0) or 0))

    @classmethod
    def virtual(cls, x: np.ndarray, y: np.ndarray, sizes: np.ndarray,
                test: Tuple[np.ndarray, np.ndarray], class_num: int,
                seed: int = 0) -> "ClientPopulation":
        """Lazy population: rows for client ``cid`` are a deterministic
        function of ``(seed, cid)`` — nothing per-client is stored."""
        x = np.asarray(x)
        y = np.asarray(y)
        n_rows = int(len(y))
        sizes = np.asarray(sizes, np.int64)

        def rows_fn(cid: int) -> np.ndarray:
            g = philox_generator("client_rows", seed, cid)
            return g.integers(0, n_rows, size=int(sizes[cid]),
                              dtype=np.int64)

        return cls(x, y, sizes, rows_fn, test, class_num,
                   virtual=True, seed=seed)


def load_population(args: Any,
                    dataset: Optional[Tuple] = None) -> ClientPopulation:
    """Population for the hyper-scale backend.

    ``population_sizes_path`` or ``client_num_in_total`` above the
    loader-materialization threshold selects a virtual population over
    the base arrays of the (small) source dataset; otherwise the
    standard loader's partition is wrapped 1:1 for parity."""
    import json
    from . import data_loader

    n = int(getattr(args, "client_num_in_total", 10))
    sizes_path = getattr(args, "population_sizes_path", None)
    threshold = int(getattr(args, "population_virtual_threshold", 2048))

    if sizes_path:
        with open(sizes_path) as f:
            payload = json.load(f)
        sizes = decode_sizes(payload)
        n = len(sizes)
    elif n > threshold:
        sizes = zipf_sizes(n, seed=int(getattr(args, "random_seed", 0) or 0))
    else:
        sizes = None

    if sizes is None:
        ds = dataset if dataset is not None else data_loader.load(args)
        return ClientPopulation.from_dataset(args, ds)

    # virtual path: load base arrays once at a small materialized client
    # count (the partition is discarded — only the global arrays matter)
    if dataset is None:
        saved = getattr(args, "client_num_in_total", None)
        try:
            args.client_num_in_total = min(int(saved or 10), 64)
            dataset = data_loader.load(args)
        finally:
            args.client_num_in_total = saved
    (_tn, _te, train_global, test_global, _ln, _trl, _tel,
     class_num) = dataset
    return ClientPopulation.virtual(
        np.asarray(train_global[0]), np.asarray(train_global[1]),
        sizes, (np.asarray(test_global[0]), np.asarray(test_global[1])),
        class_num, seed=int(getattr(args, "random_seed", 0) or 0))
