"""Dataset partitioners — IID ("homo") and Dirichlet non-IID ("hetero").

Capability parity: reference `core/data/noniid_partition.py` (124 LoC,
`partition_class_samples_with_dirichlet_distribution`) and the cifar loaders'
`partition_method`/`partition_alpha` contract (`data/data_loader.py:448-525`).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def homo_partition(n_samples: int, n_clients: int, seed: int = 0
                   ) -> Dict[int, np.ndarray]:
    rng = np.random.RandomState(seed)
    idx = rng.permutation(n_samples)
    return {i: np.sort(part) for i, part in
            enumerate(np.array_split(idx, n_clients))}


def hetero_partition(labels: np.ndarray, n_clients: int, alpha: float = 0.5,
                     seed: int = 0, min_size_floor: int = 1
                     ) -> Dict[int, np.ndarray]:
    """Dirichlet(alpha) label-skew partition (reference
    `partition_class_samples_with_dirichlet_distribution`): for each class,
    split its sample indices across clients by p ~ Dir(alpha), balancing so no
    client exceeds n/n_clients early; retry until every client has at least
    ``min_size_floor`` samples."""
    labels = np.asarray(labels).reshape(-1)
    n = len(labels)
    classes = np.unique(labels)
    rng = np.random.RandomState(seed)
    min_size = 0
    tries = 0
    while min_size < min_size_floor:
        idx_batch: List[List[int]] = [[] for _ in range(n_clients)]
        for k in classes:
            idx_k = np.where(labels == k)[0]
            rng.shuffle(idx_k)
            p = rng.dirichlet(np.repeat(alpha, n_clients))
            # balance clause from the reference implementation
            p = np.array([pv * (len(b) < n / n_clients)
                          for pv, b in zip(p, idx_batch)])
            p = p / p.sum() if p.sum() > 0 else np.repeat(1.0 / n_clients,
                                                          n_clients)
            cuts = (np.cumsum(p) * len(idx_k)).astype(int)[:-1]
            for b, part in zip(idx_batch, np.split(idx_k, cuts)):
                b.extend(part.tolist())
        min_size = min(len(b) for b in idx_batch)
        tries += 1
        if tries > 100:
            break
    return {i: np.sort(np.array(b, dtype=np.int64))
            for i, b in enumerate(idx_batch)}


def partition(labels: np.ndarray, n_clients: int, method: str = "hetero",
              alpha: float = 0.5, seed: int = 0) -> Dict[int, np.ndarray]:
    if method in ("homo", "iid"):
        return homo_partition(len(labels), n_clients, seed)
    return hetero_partition(labels, n_clients, alpha, seed)


def record_data_stats(labels: np.ndarray,
                      net_dataidx_map: Dict[int, np.ndarray]) -> Dict:
    """Per-client class histogram (reference `record_net_data_stats`)."""
    stats = {}
    for cid, idx in net_dataidx_map.items():
        unq, cnt = np.unique(np.asarray(labels)[idx], return_counts=True)
        stats[cid] = {int(u): int(c) for u, c in zip(unq, cnt)}
    return stats
