"""EdgeService — the always-on native edge daemon.

Capability parity: the reference Android service layer
(`android/fedmlsdk/src/main/java/ai/fedml/edge/service/EdgeService.java`
foreground service + `ClientAgentManager.java`): a device binds its edge id
to the control plane once, heartbeats, and whenever MLOps dispatches
start_train it joins the federated run with the ON-DEVICE native trainer —
no Python job package, no JAX.  stop_train aborts the run; the daemon
outlives any number of runs.

Control plane topics are the scheduler agent schema
(`flserver_agent/{edge_id}/start_train` etc., `scheduler/agents.py`); the
run itself rides the cross-device wire protocol (`edge_client.py` over
MQTT+object-store — real TCP MQTT when configured).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Callable, Dict, Optional

from ..scheduler.agents import (
    _make_broker,
    _topic_active,
    _topic_start,
    _topic_status,
    _topic_stop,
)


class EdgeService:
    """Long-lived native-client daemon: bind → heartbeat → train on demand."""

    def __init__(self, edge_id: str, channel: str = "edges",
                 heartbeat_s: float = 5.0,
                 dataset_provider: Optional[Callable[[Any], tuple]] = None
                 ) -> None:
        self.edge_id = str(edge_id)
        self.broker = _make_broker(channel, f"edge-{edge_id}")
        self.heartbeat_s = float(heartbeat_s)
        #: on a real device the training data lives on the device; the
        #: provider maps run config → dataset tuple (default: the standard
        #: loader, which reads the local cache dir)
        self.dataset_provider = dataset_provider
        self._stop = threading.Event()
        self._hb: Optional[threading.Thread] = None
        self._runs: Dict[str, Any] = {}        # run_id → EdgeClientManager
        self._threads: Dict[str, threading.Thread] = {}
        # runs stopped before/while their client was still being built
        # (the SlaveAgent _cancelled invariant: a stop_train landing in the
        # setup window must still kill the run)
        self._cancelled: set = set()
        self._lock = threading.Lock()
        self.completed: Dict[str, str] = {}    # run_id → final status

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "EdgeService":
        self.broker.subscribe(_topic_start(self.edge_id), self._on_start)
        self.broker.subscribe(_topic_stop(self.edge_id), self._on_stop)
        self._send_active("ONLINE")
        self._hb = threading.Thread(target=self._heartbeat, daemon=True,
                                    name=f"edge-hb-{self.edge_id}")
        self._hb.start()
        logging.info("edge service %s online", self.edge_id)
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            run_ids = list(self._runs)
        for run_id in run_ids:
            self._abort(run_id)
        self._send_active("OFFLINE")
        self.broker.unsubscribe(_topic_start(self.edge_id), self._on_start)
        self.broker.unsubscribe(_topic_stop(self.edge_id), self._on_stop)
        close = getattr(self.broker, "close", None)
        if close:
            close()                    # PahoBroker: socket + loop thread

    def _heartbeat(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            self._send_active("ONLINE")

    def _send_active(self, state: str) -> None:
        # SlaveAgent's active schema ('state' + 'ts') so one consumer
        # serves both daemon kinds; native edges advertise no job slots
        import time

        self.broker.publish(_topic_active(self.edge_id), json.dumps(
            {"edge_id": self.edge_id, "state": state, "ts": time.time(),
             "role": "native-edge"}).encode())

    # -- train dispatch -----------------------------------------------------
    def _on_start(self, topic: str, payload: bytes) -> None:
        req = json.loads(payload.decode())
        run_id = str(req.get("run_id", "0"))
        with self._lock:
            # dup-guard keys on _threads (populated synchronously HERE) —
            # at-least-once delivery can redeliver start_train before the
            # run thread has built its client
            if run_id in self._threads:
                return
            done = self.completed.get(run_id)
            if done in ("FINISHED", "KILLED", "FAILED"):
                # redelivered start_train after the run already ended —
                # re-publish the recorded terminal status instead of
                # silently re-running the whole job
                self._report(run_id, done)
                return
            if run_id in self._cancelled:
                # stop_train outran its start_train (topics guarantee no
                # cross-topic ordering): refuse to start, like SlaveAgent
                self._report(run_id, "KILLED")
                return
            t = threading.Thread(target=self._run_round_loop,
                                 args=(run_id, req), daemon=True,
                                 name=f"edge-run-{self.edge_id}-{run_id}")
            self._threads[run_id] = t
        t.start()

    def _on_stop(self, topic: str, payload: bytes) -> None:
        req = json.loads(payload.decode())
        self._abort(str(req.get("run_id", "0")))

    def _abort(self, run_id: str) -> None:
        with self._lock:
            self._cancelled.add(run_id)
            client = self._runs.pop(run_id, None)
        if client is not None:
            try:
                client.finish()
            except Exception:  # noqa: BLE001
                logging.exception("edge %s: abort of run %s failed",
                                  self.edge_id, run_id)
            self._report(run_id, "KILLED")

    def _report(self, run_id: str, status: str) -> None:
        self.completed[run_id] = status
        self.broker.publish(_topic_status(run_id), json.dumps(
            {"edge_id": self.edge_id, "run_id": run_id,
             "status": status}).encode())

    def _run_round_loop(self, run_id: str, req: Dict[str, Any]) -> None:
        """Join the federated run as a native-trainer client (the
        TrainingExecutor role)."""
        try:
            import fedml_tpu
            from .edge_client import EdgeClientManager

            cfg = dict(req.get("config") or {})
            cfg.setdefault("run_id", run_id)
            args = fedml_tpu.Config(**cfg)
            rank = int(req.get("rank", 1))
            size = int(req.get("size", 2))
            provider = self.dataset_provider or (
                lambda a: fedml_tpu.data.load(a))
            dataset = provider(args)
            bundle = fedml_tpu.model.create(args, dataset[-1])
            client = EdgeClientManager(args, bundle, dataset, rank, size,
                                       backend=str(req.get("backend",
                                                           "MQTT_S3")))
            with self._lock:
                if run_id in self._cancelled:
                    # stop_train landed during setup — never join the run
                    self._report(run_id, "KILLED")
                    return
                self._runs[run_id] = client
            self._report(run_id, "TRAINING")
            client.run()                 # blocks until server FINISH
            with self._lock:
                aborted = run_id not in self._runs  # _abort popped it
            if not aborted:
                self._report(run_id, "FINISHED")
        except Exception:  # noqa: BLE001
            with self._lock:
                killed = run_id in self._cancelled
            if killed:
                # the abort tore the transport down under client.run() —
                # that unwind is the KILL completing, not a failure
                logging.info("edge %s: run %s unwound after stop",
                             self.edge_id, run_id)
            else:
                logging.exception("edge %s: run %s failed", self.edge_id,
                                  run_id)
                self._report(run_id, "FAILED")
        finally:
            with self._lock:
                self._runs.pop(run_id, None)
                self._threads.pop(run_id, None)
