"""Cross-device server plane — "BeeHive" equivalent.

Capability parity: reference `cross_device/server_mnn/fedml_server_manager.py:
14-421` + `fedml_aggregator.py:60-120` + `runner.py:156-169`: the Python side
is SERVER-ONLY; clients are native-code edge devices. The reference's global
model is an `.mnn` file round-tripped through torch tensors; here the edge
artifact is a flat numpy `.npz` bundle (the native C++ trainer's layout, see
`native/native_trainer.py`), written per round so devices can fetch it
out-of-band exactly like the MNN file on S3.

The wire schema is the cross-silo one — the protocol-parity property the
reference checks in `tests/android_protocol_test/test_protocol.py`: one
server implementation drives JAX silos and native devices interchangeably.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core import mlops
from ..core.alg_frame.server_aggregator import ServerAggregator
from ..cross_silo.server.fedml_aggregator import FedMLAggregator
from ..cross_silo.server.fedml_server_manager import FedMLServerManager


def write_edge_bundle(params: Dict[str, np.ndarray], path: str) -> str:
    """Serialize a flat weight dict as the edge artifact (`.npz`), the
    analogue of `write_tensor_dict_to_mnn` (`server_mnn/utils.py`)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **{k: np.asarray(v) for k, v in params.items()})
    return path


def read_edge_bundle(path: str) -> Dict[str, np.ndarray]:
    """Read an edge artifact back into a flat weight dict (the analogue of
    `read_mnn_as_tensor_dict`, `server_mnn/utils.py:11-30`)."""
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


class EdgeServerAggregator(ServerAggregator):
    """Server-side eval in the native weight layout (the reference evaluates
    the aggregated MNN model server-side, `fedml_aggregator.py:222-240`)."""

    def __init__(self, bundle: Any, args: Any) -> None:
        super().__init__(bundle, args)
        from ..native.native_trainer import NativeClientTrainer

        self._edge_eval = NativeClientTrainer(bundle, args)

    def test(self, test_data, device=None, args=None):
        self._edge_eval.params = {
            k: np.asarray(v) for k, v in self.params.items()}
        return self._edge_eval.test(test_data)


class EdgeServerManager(FedMLServerManager):
    """Cross-device server: cross-silo round protocol + per-round edge
    artifact emission and a start_train run-config broadcast
    (reference `fedml_server_manager.py:58-100`)."""

    def __init__(self, args: Any, aggregator: FedMLAggregator, comm=None,
                 rank: int = 0, client_num: int = 0,
                 backend: str = "MQTT_S3") -> None:
        super().__init__(args, aggregator, comm, rank, client_num, backend)
        self.artifact_dir = str(
            getattr(args, "edge_artifact_dir", "") or
            os.path.join(os.path.expanduser("~"), ".fedml_tpu", "edge",
                         str(getattr(args, "run_id", "0"))))

    def start_train(self) -> None:
        """Broadcast the run config JSON (edges, hyperparams) — the MLOps
        `start_train` message the reference sends at `:58-100`."""
        run_config = {
            "run_id": str(getattr(self.args, "run_id", "0")),
            "edges": list(range(1, self.client_num + 1)),
            "hyperparameters": {
                "comm_round": int(self.args.comm_round),
                "batch_size": int(getattr(self.args, "batch_size", 32)),
                "learning_rate": float(
                    getattr(self.args, "learning_rate", 0.1)),
                "epochs": int(getattr(self.args, "epochs", 1)),
            },
            "timestamp": time.time(),
        }
        os.makedirs(self.artifact_dir, exist_ok=True)
        with open(os.path.join(self.artifact_dir, "run_config.json"),
                  "w") as f:
            json.dump(run_config, f)
        logging.info("cross-device run config: %s", run_config)

    def _emit_artifact(self, round_idx: int) -> None:
        params = self.aggregator.get_global_model_params()
        if isinstance(params, dict) and all(
                isinstance(v, (np.ndarray, np.generic)) or hasattr(v, "shape")
                for v in params.values()):
            path = os.path.join(self.artifact_dir,
                                f"global_model_r{round_idx}.npz")
            write_edge_bundle(params, path)
            mlops.log_aggregated_model_info(round_idx, model_url=path)

    def handle_message_receive_model_from_client(self, msg) -> None:
        before = self.args.round_idx
        super().handle_message_receive_model_from_client(msg)
        if self.args.round_idx != before:  # a round just closed
            self._emit_artifact(before)

    def run(self) -> None:
        self.start_train()
        super().run()


def build_cross_device_runner(args: Any, device: Any, dataset: Tuple,
                              bundle: Any, client_trainer=None,
                              server_aggregator=None):
    """Reference `runner.py:156-169`: cross_device raises unless this process
    is the server. A `role="simulated"` escape hatch federates native edge
    clients in-process (the protocol test the reference keeps in
    `tests/android_protocol_test`)."""
    role = str(getattr(args, "role", "server"))
    if role not in ("server", "simulated"):
        raise RuntimeError(
            "cross_device: the Python runtime is server-only; edge devices "
            "run the native client (fedml_tpu/native)")
    agg_impl = server_aggregator or EdgeServerAggregator(bundle, args)
    if agg_impl.get_model_params() is None:
        # initial global model in the native layout: linear head on flat input
        d = int(np.prod(dataset[2][0].shape[1:]))
        classes = int(dataset[-1])
        agg_impl.set_model_params({
            "w1": np.zeros(0, np.float32), "b1": np.zeros(0, np.float32),
            "w2": np.zeros((d, classes), np.float32),
            "b2": np.zeros(classes, np.float32)})
    client_num = int(args.client_num_per_round)
    aggregator = FedMLAggregator(args, agg_impl, dataset[3])
    backend = str(getattr(args, "backend", "MQTT_S3")).upper()
    server = EdgeServerManager(args, aggregator, rank=0,
                               client_num=client_num, backend=backend)
    if role == "server":
        return _ServerOnlyRunner(server)
    return _SimulatedEdgeRunner(args, server, bundle, dataset, client_num,
                                backend)


class _ServerOnlyRunner:
    def __init__(self, server: EdgeServerManager) -> None:
        self.server = server

    def train(self):
        self.server.run()
        hist = self.server.aggregator.metrics_history
        return hist[-1] if hist else {}


class _SimulatedEdgeRunner:
    """Server + native edge clients on threads (protocol test harness)."""

    def __init__(self, args, server, bundle, dataset, client_num, backend):
        from .edge_client import EdgeClientManager

        self.server = server
        self.clients = [
            EdgeClientManager(args, bundle, dataset, rank, client_num + 1,
                              backend=backend)
            for rank in range(1, client_num + 1)
        ]

    def train(self):
        threads = [threading.Thread(target=c.run, daemon=True)
                   for c in self.clients]
        for t in threads:
            t.start()
        self.server.run()
        for t in threads:
            t.join(timeout=30)
        hist = self.server.aggregator.metrics_history
        return hist[-1] if hist else {}
