"""Edge client — the on-device counterpart of the cross-device plane.

Capability parity: reference BeeHive (`cross_device/`, §2.6): the Python side
is server-only; clients are native-code devices (Android MobileNN) speaking
the MQTT+S3 message schema.  Here the edge client is a thin protocol loop
(the `ClientAgentManager`/`TrainingExecutor` role) that delegates training to
the native C++ trainer (`native/`) and exchanges FLAT numpy weight dicts —
no JAX on the device.

The SAME server (`cross_silo/server/fedml_server_manager.py`) drives JAX
silos and native edge devices interchangeably, which is the protocol-parity
property `tests/android_protocol_test/test_protocol.py` checks in the
reference.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

import numpy as np

from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager
from ..cross_silo.message_define import MyMessage
from ..native.native_trainer import NativeClientTrainer


class EdgeClientManager(FedMLCommManager):
    """Native-trainer-backed client speaking the cross-silo/device schema."""

    def __init__(self, args: Any, bundle: Any, dataset, rank: int,
                 size: int, backend: str = "MQTT_S3") -> None:
        super().__init__(args, None, rank, size, backend)
        (_, _, _, _, self.local_num, self.train_local, self.test_local,
         _) = dataset
        self.trainer = NativeClientTrainer(bundle, args)

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_INIT_CONFIG, self._handle_round)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT, self._handle_round)
        self.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_FINISH, self._handle_finish)

    def run(self) -> None:
        self.register_message_receive_handlers()
        msg = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS,
                      self.get_sender_id(), 0)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                       MyMessage.CLIENT_STATUS_ONLINE)
        msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_OS, "edge-native")
        self.send_message(msg)
        self.com_manager.handle_receive_message()

    def _handle_round(self, msg: Message) -> None:
        global_model = msg.get(MyMessage.MSG_ARG_KEY_MODEL_PARAMS)
        client_index = int(msg.get(MyMessage.MSG_ARG_KEY_CLIENT_INDEX))
        self.trainer.set_id(client_index)
        self.trainer.set_model_params({
            k: np.asarray(v, np.float32) for k, v in global_model.items()})
        x, y = self.train_local[client_index]
        self.trainer.train((x, y))
        weights = {k: np.asarray(v) for k, v in self.trainer.params.items()
                   if k != "loss"}
        up = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER,
                     self.get_sender_id(), 0)
        up.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, weights)
        up.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES,
                      float(self.local_num[client_index]))
        self.send_message(up)

    def _handle_finish(self, msg: Message) -> None:
        logging.info("edge client %d: finish", self.rank)
        self.finish()
