"""Portable served-model export — the TPU-era `convert_model_to_onnx`.

Capability parity: the reference's deploy pipeline exports models to ONNX
for Triton bring-up (`model_scheduler/device_model_deployment.py:839`
convert_model_to_onnx).  The XLA-native equivalent is a serialized
StableHLO artifact (`jax.export`): the inference function is traced once
with the trained params baked in, producing a single self-contained file
any JAX runtime (CPU/TPU/GPU) can load and call WITHOUT the model's python
code — exactly the deploy-time decoupling ONNX gives torch.

Artifact layout (a directory, the model-card deploy format):
    model.stablehlo   serialized jax.export blob (params baked in)
    export.json       {"input_shape", "input_dtype", "task", "classes"}
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .fedml_predictor import FedMLPredictor

ARTIFACT = "model.stablehlo"
META = "export.json"


def export_model(bundle: Any, variables: Dict[str, Any], out_dir: str,
                 batch_size: int = 8,
                 input_shape: Optional[Tuple[int, ...]] = None) -> str:
    """Trace + serialize the bundle's inference forward with ``variables``
    baked in; returns the artifact directory."""
    import jax
    import jax.numpy as jnp
    from jax import export as jexport

    shape = tuple(input_shape
                  or getattr(bundle, "input_shape", None) or ())
    if not shape:
        raise ValueError("bundle has no input_shape; pass input_shape=")
    in_dtype = getattr(bundle, "input_dtype", jnp.float32)

    def infer(x):
        logits, _ = bundle.apply(variables, x, train=False)
        return logits

    spec = jax.ShapeDtypeStruct((batch_size,) + shape, in_dtype)
    # lower for every deploy target, or the artifact only runs on the
    # export-time backend (the portability contract of the format)
    exp = jexport.export(jax.jit(infer),
                         platforms=("cpu", "tpu"))(spec)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, ARTIFACT), "wb") as f:
        f.write(exp.serialize())
    meta = {"input_shape": list(shape),
            "batch_size": int(batch_size),
            "input_dtype": str(np.dtype(in_dtype)),
            "task": str(getattr(bundle, "task", "classification")),
            "classes": int(getattr(bundle, "num_classes", 0))}
    with open(os.path.join(out_dir, META), "w") as f:
        json.dump(meta, f, indent=1)
    return out_dir


class ExportedPredictor(FedMLPredictor):
    """Serve a StableHLO artifact: no model code, no flax — just the
    compiled computation (the Triton-container role, in-process)."""

    def __init__(self, artifact_dir: str) -> None:
        from jax import export as jexport

        with open(os.path.join(artifact_dir, ARTIFACT), "rb") as f:
            self._exported = jexport.deserialize(bytearray(f.read()))
        with open(os.path.join(artifact_dir, META)) as f:
            self.meta = json.load(f)
        self._batch = int(self.meta.get("batch_size", 8))

    def predict(self, request: Any) -> Any:
        import jax.numpy as jnp

        x = np.asarray(request["inputs"],
                       self.meta.get("input_dtype", "float32"))
        n = x.shape[0]
        if n == 0:
            return {"predictions": [], "logits": []}
        # the export is fixed-batch: short chunks pad up and slice back
        outs = []
        for i in range(0, len(x), self._batch):
            chunk = x[i:i + self._batch]
            if len(chunk) < self._batch:
                fill = np.zeros((self._batch - len(chunk),) + x.shape[1:],
                                x.dtype)
                chunk = np.concatenate([chunk, fill])
            outs.append(np.asarray(self._exported.call(jnp.asarray(chunk))))
        logits = np.concatenate(outs)[:n]
        return {"predictions": np.argmax(logits, -1).tolist(),
                "logits": logits.tolist()}

    def ready(self) -> bool:
        return True
