"""Batched LLM serving engine — the scalellm-equivalent runtime.

Capability parity: reference `serving/scalellm/` (a prebuilt GPU serving
runtime wrapper exposing generate/complete).  TPU-era design: continuous
batching on top of one jit-compiled fixed-shape decode step —

* requests enter a queue; a worker admits up to ``max_batch`` sequences
  into the active set BETWEEN decode steps (new arrivals don't wait for
  the whole previous batch to finish — continuous batching);
* every step runs ONE forward over a fixed [max_batch, window] token
  buffer (inactive rows are padding), so XLA compiles exactly once and
  the MXU sees a full batch regardless of arrival pattern;
* greedy or temperature sampling per request; finished rows retire and
  their slots are re-admitted immediately.
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from concurrent.futures import Future, TimeoutError as FuturesTimeoutError
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.mlops import flight_recorder, ledger, tracing
from ..core.mlops import metrics as _metrics
from ..core.mlops.lock_profiler import named_lock
from .admission import ServingAdmissionController, ShedError

#: request-id stream (one per process): every request carries ``rid``
#: through its lifecycle events so the anatomy correlator can join them
_rid_counter = itertools.count(1)


class _EngineMetrics:
    """Per-engine cached label children — one label lookup at construction
    instead of one per decode step.  Metric objects resolve get-or-create
    at construction (the ledger idiom) so an engine built after a test's
    ``REGISTRY.reset()`` still lands on the exposition surface."""

    #: decode ledger sampling stride: per-step ledger writes on the token
    #: hot loop would be the overhead the self-measurement exists to
    #: catch, so decode_batch events aggregate this many steps
    DECODE_LEDGER_EVERY = 64

    def __init__(self, engine_label: str) -> None:
        self.label = engine_label
        self.ttft = _metrics.histogram(
            "fedml_llm_ttft_seconds", "Submit-to-first-token latency",
            labels=("engine",),
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                     15.0, 60.0)).labels(engine=engine_label)
        self.step = _metrics.histogram(
            "fedml_llm_decode_step_seconds",
            "Latency of one decode dispatch", labels=("engine",),
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 5.0)).labels(engine=engine_label)
        self.tokens = _metrics.counter(
            "fedml_llm_tokens_total", "Tokens generated",
            labels=("engine",)).labels(engine=engine_label)
        self.tps = _metrics.gauge(
            "fedml_llm_tokens_per_s",
            "Decode throughput since engine start",
            labels=("engine",)).labels(engine=engine_label)
        self.queue = _metrics.gauge(
            "fedml_llm_queue_depth", "Requests waiting for a batch slot",
            labels=("engine",)).labels(engine=engine_label)
        self.active = _metrics.gauge(
            "fedml_llm_active_requests", "Requests occupying batch slots",
            labels=("engine",)).labels(engine=engine_label)
        # TTFT decomposition (queue + prefill + first-decode): each leg
        # its own histogram so /metrics alone can check the identity
        self.queue_wait = _metrics.histogram(
            "fedml_llm_queue_wait_seconds",
            "Submit-to-admit wait for a batch slot (the queue leg of "
            "TTFT: ttft = queue_wait + prefill + first_decode)",
            labels=("engine",),
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 15.0, 60.0)).labels(engine=engine_label)
        self.prefill = _metrics.histogram(
            "fedml_llm_prefill_seconds",
            "Admission-prefill latency (the prefill leg of TTFT)",
            labels=("engine",),
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 5.0)).labels(engine=engine_label)
        self.tbt = _metrics.histogram(
            "fedml_llm_tbt_seconds",
            "Per-request mean time-between-tokens, observed at FINISH "
            "only (cancelled requests never count)",
            labels=("engine",),
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 5.0)).labels(engine=engine_label)
        self._shed_total = _metrics.counter(
            "fedml_llm_shed_total",
            "Requests refused admission by the serving admission policy",
            labels=("engine", "reason"))
        self._requests_total = _metrics.counter(
            "fedml_llm_requests_total",
            "Requests by terminal lifecycle outcome",
            labels=("engine", "outcome"))
        self.occupancy = _metrics.gauge(
            "fedml_llm_batch_occupancy",
            "Active batch slots / max_batch, sampled on the engine loop",
            labels=("engine",)).labels(engine=engine_label)
        self.kv_tokens = _metrics.gauge(
            "fedml_llm_kv_cache_tokens",
            "KV-cache positions in use across active slots, sampled on "
            "the engine loop", labels=("engine",)).labels(
                engine=engine_label)
        self._decode_lock = named_lock("_EngineMetrics._decode_lock")
        self._decode_steps = 0
        self._decode_secs = 0.0

    # -- per-request lifecycle ----------------------------------------------
    # events: submit → (queue) → admit|shed → prefill → first_token →
    # (decode) → finish|cancel.  Every emission carries ``rid`` so
    # `loadgen.anatomy.request_anatomy` can join a request's lifecycle
    # back together; all ledger writes are one-dict-hit no-ops when the
    # run ledger is disarmed.

    def note_submit(self, req: "_Request") -> None:
        if ledger.enabled():
            ledger.event("serving", "submit", rid=req.rid,
                         engine=self.label, prompt_tokens=len(req.ids),
                         max_new=req.remaining)
            req.span = tracing.start_span(
                "serving.request", rid=req.rid, engine=self.label)

    def note_shed(self, req: "_Request", reason: str,
                  queue_depth: int) -> None:
        req.outcome = "shed"
        req.finish_reason = "shed"
        self._shed_total.labels(engine=self.label, reason=reason).inc()
        self._requests_total.labels(engine=self.label,
                                    outcome="shed").inc()
        if ledger.enabled():
            ledger.event("serving", "shed", rid=req.rid,
                         engine=self.label, reason=reason,
                         queue_depth=int(queue_depth))
        if req.span is not None:
            req.span.set_attr("reason", reason)
            req.span.end("shed")

    def note_admit(self, req: "_Request", slot: int) -> None:
        req.t_admit = time.monotonic()
        wait = req.t_admit - req.t_submit
        self.queue_wait.observe(wait)
        if ledger.enabled():
            ledger.event("serving", "admit", rid=req.rid,
                         engine=self.label, slot=int(slot),
                         queue_wait_s=round(wait, 6))

    def note_prefill(self, req: "_Request", secs: float) -> None:
        req.t_prefill_done = time.monotonic()
        self.prefill.observe(secs)
        if ledger.enabled():
            ledger.event("serving", "prefill", rid=req.rid,
                         engine=self.label, secs=round(secs, 6),
                         tokens=len(req.ids))

    def note_token(self, req: "_Request") -> None:
        now = time.monotonic()
        if req.t_first_token is None:
            req.t_first_token = now
            self.ttft.observe(now - req.t_submit)
            if ledger.enabled():
                ledger.event("serving", "first_token", rid=req.rid,
                             engine=self.label,
                             ttft_s=round(now - req.t_submit, 6),
                             queue_wait_s=round(req.queue_wait_s(), 6),
                             prefill_s=round(req.prefill_s(), 6),
                             first_decode_s=round(
                                 req.first_decode_s(now), 6))
        req.t_last_token = now
        req.n_generated += 1
        self.tokens.inc()

    def note_retire(self, req: "_Request", outcome: str) -> None:
        """Terminal lifecycle transition: ``finish`` or ``cancel``.
        Idempotent per request; TBT is observed on FINISH only so a
        cancelled stream's tokens never skew the TBT percentiles."""
        if req.outcome is not None:
            return
        req.outcome = outcome
        req.t_finish = time.monotonic()
        self._requests_total.labels(engine=self.label,
                                    outcome=outcome).inc()
        if outcome == "finish" and req.n_generated >= 2 \
                and req.t_first_token is not None \
                and req.t_last_token is not None:
            self.tbt.observe((req.t_last_token - req.t_first_token)
                             / (req.n_generated - 1))
        if ledger.enabled():
            ledger.event("serving", outcome, rid=req.rid,
                         engine=self.label, tokens=req.n_generated,
                         finish_reason=req.finish_reason,
                         service_s=round(req.t_finish - req.t_submit, 6))
        if req.span is not None:
            req.span.set_attr("tokens", req.n_generated)
            req.span.end(None if outcome == "finish" else outcome)

    def note_decode(self, dt: float, batch_size: int) -> None:
        """Sampled run-ledger attribution for the decode loop: one
        ``decode_batch`` event per DECODE_LEDGER_EVERY dispatches."""
        if not ledger.enabled():
            return
        with self._decode_lock:
            self._decode_steps += 1
            self._decode_secs += dt
            if self._decode_steps < self.DECODE_LEDGER_EVERY:
                return
            steps, secs = self._decode_steps, self._decode_secs
            self._decode_steps = 0
            self._decode_secs = 0.0
        ledger.event("serving", "decode_batch", engine=self.label,
                     steps=steps, secs=round(secs, 6), batch=batch_size)


_scatter_cache_row_jit = None


def _scatter_cache_row(cache, row_cache, slot):
    """Write a 1-row prefilled KV cache into row ``slot`` of the batch
    cache (one jitted donate-in-place dispatch for all layers) — the
    admission path of `KVCacheLLMEngine._prefill_admit`."""
    global _scatter_cache_row_jit
    if _scatter_cache_row_jit is None:
        import jax

        def _impl(cache, row_cache, slot):
            return [
                {"k": layer["k"].at[slot].set(row["k"][0]),
                 "v": layer["v"].at[slot].set(row["v"][0])}
                for layer, row in zip(cache, row_cache)]

        _scatter_cache_row_jit = jax.jit(_impl, donate_argnums=(0,))
    return _scatter_cache_row_jit(cache, row_cache, slot)


class _Request:
    def __init__(self, prompt_ids: List[int], max_new: int,
                 temperature: float, top_k: int = 0,
                 top_p: float = 1.0, on_token=None) -> None:
        self.ids = list(prompt_ids)
        self.remaining = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k or 0)
        self.top_p = float(top_p if top_p is not None else 1.0)
        self.on_token = on_token        # per-token streaming callback
        self.future: "Future[np.ndarray]" = Future()
        #: "stop" (ran to its token budget), "length" (the engine had to
        #: truncate: cache capacity < prompt+max_new), "cancelled", or
        #: "shed" — OpenAI semantics, surfaced to callers via
        #: future.request.finish_reason
        self.finish_reason = "stop"
        self.cancelled = threading.Event()
        # -- lifecycle telemetry (submit → admit|shed → prefill →
        #    first_token → finish|cancel); rid joins a request's ledger
        #    events + span back together in `loadgen.anatomy`
        self.rid = next(_rid_counter)
        self.t_submit = time.monotonic()
        self.t_admit: Optional[float] = None
        self.t_prefill_done: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_last_token: Optional[float] = None
        self.t_finish: Optional[float] = None
        self.n_generated = 0
        #: terminal lifecycle outcome ("finish" | "cancel" | "shed"),
        #: set exactly once by _EngineMetrics.note_retire / note_shed
        self.outcome: Optional[str] = None
        self.span: Optional[tracing.Span] = None
        self.future.request = self  # type: ignore[attr-defined]

    # -- TTFT decomposition legs (ttft = queue_wait + prefill +
    #    first_decode by construction; un-measured legs report 0.0)
    def queue_wait_s(self) -> float:
        if self.t_admit is None:
            return 0.0
        return self.t_admit - self.t_submit

    def prefill_s(self) -> float:
        if self.t_prefill_done is None or self.t_admit is None:
            return 0.0
        return self.t_prefill_done - self.t_admit

    def first_decode_s(self, t_first: float) -> float:
        base = self.t_prefill_done or self.t_admit or self.t_submit
        return t_first - base

    def cancel(self) -> None:
        """Ask the worker to retire this request at the next step (used by
        streaming consumers that disconnect mid-generation)."""
        self.cancelled.set()

    def emit(self, token: int) -> None:
        if self.on_token is not None:
            try:
                self.on_token(int(token))
            except Exception:  # noqa: BLE001 — consumer bugs can't kill the worker
                pass


def _sample_token(row: np.ndarray, req: "_Request", rng: np.random.Generator
                  ) -> int:
    """Greedy / temperature with optional top-k then nucleus (top-p)
    filtering (reference serving templates' sampling controls)."""
    if req.temperature <= 0:
        return int(np.argmax(row))
    logits = row.astype(np.float64) / req.temperature
    if req.top_k > 0 and req.top_k < len(logits):
        kth = np.partition(logits, -req.top_k)[-req.top_k]
        logits = np.where(logits < kth, -np.inf, logits)
    p = np.exp(logits - np.max(logits))
    p = p / p.sum()
    if req.top_p < 1.0:
        # top_p<=0 degenerates to keep-top-token (HF convention)
        order = np.argsort(-p)
        csum = np.cumsum(p[order])
        cut = max(int(np.searchsorted(csum, max(req.top_p, 0.0))) + 1, 1)
        mask = np.zeros_like(p)
        mask[order[:cut]] = 1.0
        p = p * mask
        p = p / p.sum()
    return int(rng.choice(len(p), p=p))


class BatchedLLMEngine:
    def __init__(self, bundle: Any, variables: Dict[str, Any],
                 max_batch: int = 8, window: Optional[int] = None,
                 max_wait_s: float = 0.005,
                 admission: Optional[ServingAdmissionController] = None
                 ) -> None:
        import jax
        import jax.numpy as jnp

        self.bundle = bundle
        self.variables = variables
        self.max_batch = int(max_batch)
        self.window = int(window or getattr(bundle, "input_shape",
                                            (64,))[0] or 64)
        self.max_wait_s = float(max_wait_s)
        self.admission = admission
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._active: List[Optional[_Request]] = [None] * self.max_batch
        self._stop = threading.Event()
        self._np_rng = np.random.default_rng(7)
        self._metrics = _EngineMetrics("batched")
        #: guards loop-mutated counters that stats() snapshots from other
        #: threads (the autoscaler + load report read while the loop writes)
        self._state_lock = named_lock("BatchedLLMEngine._state_lock")
        self._tokens_done = 0
        self._t_start = time.monotonic()

        def step(variables, x, pos):
            # sequences are LEFT-aligned with zero right-padding; under
            # causal attention logits at index pos[i]-1 are EXACTLY the
            # unpadded next-token logits (padding can't attend backward),
            # so no attention mask is needed
            logits, _ = bundle.apply(variables, x, train=False)
            idx = jnp.clip(pos - 1, 0, x.shape[1] - 1)
            return jnp.take_along_axis(
                logits, idx[:, None, None], axis=1)[:, 0, :]  # [B, V]

        self._step = jax.jit(step)
        self._jnp = jnp
        self._jax = jax
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-engine")
        self._worker.start()

    # -- public API ---------------------------------------------------------
    def submit(self, prompt_ids, max_new: int = 20,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, on_token=None) -> "Future[np.ndarray]":
        req = _Request(list(np.asarray(prompt_ids).tolist()), max_new,
                       temperature, top_k, top_p, on_token)
        if self._stop.is_set():
            req.future.set_exception(RuntimeError("engine stopped"))
            return req.future
        self._metrics.note_submit(req)
        if req.remaining <= 0:  # zero-budget: resolve without a decode step
            self._metrics.note_retire(req, "finish")
            req.future.set_result(np.asarray(req.ids))
            return req.future
        if self.admission is not None:
            depth = self._pending.qsize()
            ok, reason = self.admission.admit(depth)
            if not ok:
                self._metrics.note_shed(req, reason, depth)
                req.future.set_exception(
                    ShedError(reason, f"request shed ({reason}); "
                                      f"queue_depth={depth}"))
                return req.future
        self._pending.put(req)
        return req.future

    def generate(self, prompt_ids, max_new: int = 20,
                 temperature: float = 0.0, timeout: float = 120.0,
                 top_k: int = 0, top_p: float = 1.0) -> np.ndarray:
        fut = self.submit(prompt_ids, max_new, temperature, top_k, top_p)
        try:
            return fut.result(timeout)
        except (TimeoutError, FuturesTimeoutError):
            # free the slot: a timed-out request must not keep generating
            # into an orphaned future
            req = getattr(fut, "request", None)
            if req is not None:
                req.cancel()
            raise

    def stop(self) -> None:
        self._stop.set()
        self._worker.join(timeout=5.0)
        # a submit() racing stop() may have put() after the worker's final
        # drain — resolve any such stragglers here
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            if not req.future.done():
                self._metrics.note_retire(req, "cancel")
                req.future.set_exception(RuntimeError("engine stopped"))

    @property
    def active_count(self) -> int:
        return sum(1 for r in self._active if r is not None)

    @property
    def alive(self) -> bool:
        """True while the engine can serve: not stopped AND the worker
        thread hasn't died (e.g. from a step exception)."""
        return not self._stop.is_set() and self._worker.is_alive()

    # -- worker -------------------------------------------------------------
    def _admit(self) -> None:
        for slot in range(self.max_batch):
            if self._active[slot] is None:
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    return
                self._active[slot] = req
                self._metrics.note_admit(req, slot)

    def _retire(self, req: "_Request", outcome: str) -> None:
        self._metrics.note_retire(req, outcome)
        if self.admission is not None:
            self.admission.note_finish()

    def _loop(self) -> None:
        jnp = self._jnp
        while not self._stop.is_set():
            self._admit()
            if self.active_count == 0:
                try:
                    # idle: block on a coarse stop-aware wait (max_wait_s
                    # only bounds BATCHING latency, not idle polling)
                    req = self._pending.get(timeout=0.5)
                    self._active[0] = req
                    self._metrics.note_admit(req, 0)
                except queue.Empty:
                    continue
            x = np.zeros((self.max_batch, self.window), np.int32)
            pos = np.ones((self.max_batch,), np.int32)
            for slot, req in enumerate(self._active):
                if req is not None:
                    tail = req.ids[-self.window:]
                    x[slot, :len(tail)] = tail  # left-aligned window
                    pos[slot] = len(tail)
            t_step = time.monotonic()
            with self._metrics.step.time():
                logits = np.asarray(self._step(self.variables,
                                               jnp.asarray(x),
                                               jnp.asarray(pos)))
            # histogram-only attribution: per-token flight-log writes
            # would BE the overhead the recorder exists to catch
            dt_step = time.monotonic() - t_step
            flight_recorder.observe_phase(
                "device_compute", dt_step, program="serving/decode_step")
            self._metrics.note_decode(dt_step, self.active_count)
            produced = 0
            for slot, req in enumerate(self._active):
                if req is None:
                    continue
                if req.cancelled.is_set():
                    req.finish_reason = "cancelled"
                    self._retire(req, "cancel")
                    if not req.future.done():
                        req.future.set_result(np.asarray(req.ids))
                    self._active[slot] = None
                    continue
                nxt = _sample_token(logits[slot], req, self._np_rng)
                req.ids.append(nxt)
                self._metrics.note_token(req)
                produced += 1
                req.emit(nxt)
                req.remaining -= 1
                if req.remaining <= 0:
                    self._retire(req, "finish")
                    req.future.set_result(np.asarray(req.ids))
                    self._active[slot] = None  # slot freed mid-flight
            with self._state_lock:
                self._tokens_done += produced
                tokens_done = self._tokens_done
            self._metrics.queue.set(self._pending.qsize())
            self._metrics.active.set(self.active_count)
            self._metrics.occupancy.set(self.active_count / self.max_batch)
            self._metrics.tps.set(tokens_done / max(
                time.monotonic() - self._t_start, 1e-9))
        # drain on shutdown: active AND still-pending requests must resolve
        for req in self._active:
            if req is not None and not req.future.done():
                self._retire(req, "cancel")
                req.future.set_result(np.asarray(req.ids))
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            if not req.future.done():
                self._metrics.note_retire(req, "cancel")
                req.future.set_exception(RuntimeError("engine stopped"))

    def stats(self) -> Dict[str, float]:
        """Live metrics in the autoscaler's `observe` shape.  The counter
        snapshot happens under ``_state_lock`` (the loop batches its
        updates under the same lock) and the SAME values are pushed to the
        Prometheus gauges, so the load report and /metrics can't disagree."""
        with self._state_lock:
            tokens_done = self._tokens_done
        dt = max(time.monotonic() - self._t_start, 1e-9)
        tps = tokens_done / dt
        depth = self._pending.qsize()
        active = self.active_count
        self._metrics.tps.set(tps)
        self._metrics.queue.set(depth)
        self._metrics.active.set(active)
        self._metrics.occupancy.set(active / self.max_batch)
        return {"tokens_per_s": tps, "queue_depth": depth,
                "active": active, "capacity": self.max_batch}


class LLMEnginePredictor:
    """FedMLPredictor-shaped adapter: plugs a BatchedLLMEngine into the
    HTTP inference runner and the OpenAI-compatible chat API (reference
    serving/templates/hf_template — generation backend behind /predict and
    /v1/chat/completions).  ``encode``/``decode`` map text ↔ token ids;
    defaults to the char-level codec of the shakespeare-vocab models."""

    def __init__(self, engine: BatchedLLMEngine, encode=None,
                 decode=None) -> None:
        self.engine = engine
        self.encode = encode or (lambda s: [
            min(max(ord(c) - 32, 0), 89) for c in s] or [0])
        self.decode = decode or (lambda ids: "".join(
            chr(int(i) + 32) for i in ids))

    def predict(self, request: Any) -> str:
        r = self.predict_full(request)
        return r["stream"] if "stream" in r else r["text"]

    def predict_full(self, request: Any) -> Dict[str, Any]:
        """predict + OpenAI metadata.  Non-streaming → {"text",
        "finish_reason"} ("length" when the engine truncated the token
        budget); streaming → {"stream": generator, "finish": callable
        returning the final reason once the stream ends}."""
        if isinstance(request, str):
            request = {"prompt": request}
        prompt = str(request.get("prompt", ""))
        raw_max = request.get("max_tokens")
        max_tokens = 20 if raw_max is None else int(raw_max)
        temperature = float(request.get("temperature", 0.0) or 0.0)
        raw_k, raw_p = request.get("top_k"), request.get("top_p")
        top_k = 0 if raw_k is None else int(raw_k)
        top_p = 1.0 if raw_p is None else float(raw_p)
        ids = self.encode(prompt)
        timeout = float(request.get("timeout", 300.0) or 300.0)
        if request.get("stream"):
            holder: Dict[str, str] = {}
            gen = self._stream_tokens(ids, max_tokens, temperature,
                                      top_k, top_p, timeout, holder)
            return {"stream": gen,
                    "finish": lambda: holder.get("finish", "stop")}
        fut = self.engine.submit(ids, max_new=max_tokens,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p)
        req = getattr(fut, "request", None)
        try:
            out = fut.result(timeout)
        except (TimeoutError, FuturesTimeoutError):
            # free the slot — otherwise timed-out requests keep generating
            # into orphaned futures until they starve live traffic.  Both
            # names: futures.TimeoutError only aliases the builtin on 3.11+
            if req is not None:
                req.cancel()
            raise
        return {"text": self.decode(out[len(ids):]),
                "finish_reason": getattr(req, "finish_reason", "stop")}

    def _stream_tokens(self, ids, max_tokens, temperature, top_k, top_p,
                       timeout: float = 300.0, holder: Optional[dict] = None):
        """Generator yielding decoded tokens AS the engine produces them —
        the lazy iterable the SSE path consumes incrementally.  ``timeout``
        bounds the inter-token gap (from the request, not hardcoded); a
        consumer that disconnects (GeneratorExit) or times out CANCELS the
        underlying engine request so the slot stops generating into an
        orphaned queue."""
        q: "queue.Queue" = queue.Queue()
        fut = self.engine.submit(ids, max_new=max_tokens,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p, on_token=q.put)
        fut.add_done_callback(lambda _f: q.put(None))
        req = getattr(fut, "request", None)
        try:
            while True:
                try:
                    tok = q.get(timeout=timeout)
                except queue.Empty:
                    if req is not None:
                        req.cancel()
                    if holder is not None:
                        holder["finish"] = "timeout"
                    raise TimeoutError(
                        f"no token for {timeout:.0f}s; request cancelled")
                if tok is None:
                    break
                yield self.decode([tok])
            if holder is not None and req is not None:
                holder["finish"] = req.finish_reason
        except GeneratorExit:
            if req is not None:
                req.cancel()
            raise

    def ready(self) -> bool:
        return self.engine.alive


class KVCacheLLMEngine:
    """Continuous batching over a per-row KV cache (`kv_cache_lm.KVCacheLM`)
    — the prefill/decode architecture of scalellm/vLLM, with CHUNKED
    prefill: prompt tokens are teacher-forced through the same fixed-shape
    decode step as generation, one token per row per step, so newly
    admitted prompts stream in while other rows keep generating and the
    engine has exactly ONE compiled step.  Each generated token costs
    O(cache_len) attention instead of the full-window O(T²) re-forward of
    `BatchedLLMEngine`."""

    def __init__(self, lm: Any, max_batch: int = 8,
                 tokens_per_dispatch: int = 8,
                 admission: Optional[ServingAdmissionController] = None
                 ) -> None:
        import jax
        import jax.numpy as jnp

        self.lm = lm
        self.max_batch = int(max_batch)
        #: inner on-device loop length: when every active row has cache
        #: headroom, decode_multi samples k tokens per dispatch (greedy,
        #: temperature, top-k and nucleus filtering all run on-device)
        #: with NO host round trip in between — a ~k x dispatch-latency win
        self.tokens_per_dispatch = max(int(tokens_per_dispatch), 1)
        self.admission = admission
        self._pending: "queue.Queue[_Request]" = queue.Queue()
        self._active: List[Optional[_Request]] = [None] * self.max_batch
        # per-slot decode state: position only (prefill progress is
        # _pos vs len(req.ids))
        self._pos = np.zeros((self.max_batch,), np.int32)
        self._cache = lm.init_cache(self.max_batch)
        self._stop = threading.Event()
        self._np_rng = np.random.default_rng(11)
        self._rng_key = jax.random.PRNGKey(13)
        #: guards loop-mutated counters that stats() snapshots from other
        #: threads (the autoscaler + load report read while the loop writes)
        self._state_lock = named_lock("KVCacheLLMEngine._state_lock")
        self._tokens_done = 0
        self._t_start = time.monotonic()
        self._metrics = _EngineMetrics("kv")
        self._jax, self._jnp = jax, jnp
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="kv-llm-engine")
        self._worker.start()

    # -- public API (mirrors BatchedLLMEngine) ------------------------------
    def submit(self, prompt_ids, max_new: int = 20,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, on_token=None) -> "Future[np.ndarray]":
        req = _Request(list(np.asarray(prompt_ids).tolist()), max_new,
                       temperature, top_k, top_p, on_token)
        if self._stop.is_set():
            req.future.set_exception(RuntimeError("engine stopped"))
            return req.future
        self._metrics.note_submit(req)
        cap = self.lm.max_len
        req.prefix = []
        if len(req.ids) + req.remaining > cap:
            # cache capacity split: generation gets what it asked for up to
            # half the cache; the prompt TAIL keeps the rest (the full
            # sequence is still returned) — so a long prompt is never cut
            # to a single token just because max_new was large
            gen = min(req.remaining,
                      max(cap - len(req.ids), cap // 2))
            keep = cap - gen
            if len(req.ids) > keep:
                req.prefix = req.ids[:-keep]
                req.ids = req.ids[-keep:]
            if gen < req.remaining:
                # fewer tokens than asked for: surface it, don't hide it
                req.finish_reason = "length"
            req.remaining = gen
        if req.remaining <= 0 or len(req.ids) == 0:
            self._metrics.note_retire(req, "finish")
            req.future.set_result(np.asarray(req.prefix + req.ids))
            return req.future
        if self.admission is not None:
            depth = self._pending.qsize()
            ok, reason = self.admission.admit(depth)
            if not ok:
                self._metrics.note_shed(req, reason, depth)
                req.future.set_exception(
                    ShedError(reason, f"request shed ({reason}); "
                                      f"queue_depth={depth}"))
                return req.future
        self._pending.put(req)
        return req.future

    def generate(self, prompt_ids, max_new: int = 20,
                 temperature: float = 0.0, timeout: float = 120.0,
                 top_k: int = 0, top_p: float = 1.0) -> np.ndarray:
        fut = self.submit(prompt_ids, max_new, temperature, top_k, top_p)
        try:
            return fut.result(timeout)
        except (TimeoutError, FuturesTimeoutError):
            # free the slot: a timed-out request must not keep generating
            # into an orphaned future
            req = getattr(fut, "request", None)
            if req is not None:
                req.cancel()
            raise

    def stop(self) -> None:
        self._stop.set()
        self._worker.join(timeout=5.0)
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            if not req.future.done():
                req.future.set_exception(RuntimeError("engine stopped"))

    @property
    def active_count(self) -> int:
        return sum(1 for r in self._active if r is not None)

    @property
    def alive(self) -> bool:
        return not self._stop.is_set() and self._worker.is_alive()

    # -- worker -------------------------------------------------------------
    def _admit(self) -> bool:
        """Admit pending requests into free slots; returns True iff any
        admitted request was ADMISSION-PREFILLED (its first token is one
        short dispatch away — the turbo-dispatch precondition)."""
        any_prefilled = False
        for slot in range(self.max_batch):
            if self._active[slot] is None:
                try:
                    req = self._pending.get_nowait()
                except queue.Empty:
                    break
                self._active[slot] = req
                self._pos[slot] = 0
                self._metrics.note_admit(req, slot)
                any_prefilled |= self._prefill_admit(slot, req)
        return any_prefilled

    def _retire(self, req: "_Request", outcome: str) -> None:
        self._metrics.note_retire(req, outcome)
        if self.admission is not None:
            self.admission.note_finish()

    #: admission prefill length buckets (prompt padded up to the next
    #: bucket): one compiled prefill variant per bucket actually seen,
    #: instead of one per prompt length
    _PREFILL_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048)

    def _prefill_admit(self, slot: int, req: "_Request") -> bool:
        """TTFT path: run the REAL prefill over the admitted prompt in one
        dispatch and scatter its cache row into the batch cache, instead
        of teacher-forcing the prompt through ceil(P/k) decode dispatches.
        Measured on v5e (GPT-2 geometry, 45-token prompt, k=16): served
        TTFT 1075 ms → one prefill + one decode dispatch.  Decode resumes
        at the LAST prompt position: feeding ids[P-1] at pos P-1 rewrites
        identical K/V and yields the logits that sample token P."""
        p = len(req.ids)
        k = self.tokens_per_dispatch
        # short prompts: chunked prefill already reaches generation in one
        # dispatch, and the scatter would cost more than it saves
        if p <= max(k, 2):
            return False
        tp = next((b for b in self._PREFILL_BUCKETS
                   if b >= p and b <= self.lm.max_len), None)
        if tp is None:
            tp = self.lm.max_len
        jnp = self._jnp
        toks = np.zeros((1, tp), np.int32)
        toks[0, :p] = req.ids
        t_prefill = time.monotonic()
        try:
            row_cache, _ = self.lm.prefill(jnp.asarray(toks),
                                           jnp.asarray([p], np.int32))
        except Exception:  # noqa: BLE001 — no donation yet: safe fallback
            logging.exception("kv-engine: admission prefill failed; "
                              "falling back to chunked prefill")
            return False
        try:
            self._cache = _scatter_cache_row(
                self._cache, row_cache, jnp.asarray(slot, np.int32))
        except Exception:  # noqa: BLE001
            # the scatter DONATES self._cache; an execution-time failure
            # (e.g. OOM) may have consumed it.  Rebuild an empty cache and
            # restart every active row's prefill from position 0 — req.ids
            # holds prompt + generated tokens, so chunked re-prefill
            # resumes each request correctly (slower, never wrong)
            logging.exception("kv-engine: admission scatter failed; "
                              "rebuilding cache and re-prefilling")
            dead = any(
                getattr(leaf, "is_deleted", lambda: False)()
                for layer in self._cache for leaf in layer.values())
            if dead:
                self._cache = self.lm.init_cache(self.max_batch)
                self._pos[:] = 0
            return False
        self._pos[slot] = p - 1
        self._metrics.note_prefill(req, time.monotonic() - t_prefill)
        return True

    #: admission-turbo dispatch length: the FIRST dispatch after an
    #: admission-PREFILLED request joins runs this many tokens instead of
    #: tokens_per_dispatch, so its first token lands after a 2-token
    #: dispatch rather than a full one.  Applies ONLY when the prompt was
    #: actually prefilled at admission (a chunk-prefilling short prompt
    #: would otherwise pay an extra dispatch RTT before its first token).
    #: Measured through the serve bench on the tunneled v5e: TTFT idle
    #: 236 -> 197 ms (the ~100 ms dispatch RTT bounds the gain there;
    #: a locally-attached chip saves most of the (k-2) decode-step
    #: share).  Set to 0 to disable.
    ADMIT_TURBO_K = 2

    def _loop(self) -> None:
        jnp = self._jnp
        while not self._stop.is_set():
            turbo = self._admit()
            if self.active_count == 0:
                try:
                    req = self._pending.get(timeout=0.5)
                except queue.Empty:
                    continue
                self._active[0] = req
                self._pos[0] = 0
                self._metrics.note_admit(req, 0)
                turbo = self._prefill_admit(0, req)
            self._metrics.queue.set(self._pending.qsize())
            self._metrics.active.set(self.active_count)
            self._metrics.occupancy.set(self.active_count / self.max_batch)
            self._metrics.kv_tokens.set(int(sum(
                int(self._pos[s]) for s, r in enumerate(self._active)
                if r is not None)))
            with self._state_lock:
                tokens_done = self._tokens_done
            self._metrics.tps.set(tokens_done / max(
                time.monotonic() - self._t_start, 1e-9))
            k = self.tokens_per_dispatch
            if turbo and self.ADMIT_TURBO_K and self.ADMIT_TURBO_K < k:
                k = self.ADMIT_TURBO_K
            if k > 1 and self._can_multi(k):
                self._step_multi(k)
                continue
            # build this step's token vector: next prompt token (chunked
            # prefill) or the last sampled token
            tokens = np.zeros((self.max_batch,), np.int32)
            for slot, req in enumerate(self._active):
                if req is None:
                    continue
                if req.cancelled.is_set():
                    req.finish_reason = "cancelled"
                    self._retire(req, "cancel")
                    if not req.future.done():
                        req.future.set_result(
                            np.asarray(getattr(req, "prefix", []) + req.ids))
                    self._active[slot] = None
                    continue
                tokens[slot] = req.ids[self._pos[slot]] \
                    if self._pos[slot] < len(req.ids) else 0
            if self.active_count == 0:
                continue
            t_step = time.monotonic()
            with self._metrics.step.time():
                self._cache, logits = self.lm.decode(
                    self._cache, jnp.asarray(tokens), jnp.asarray(self._pos))
                logits = np.asarray(logits)
            dt_step = time.monotonic() - t_step
            flight_recorder.observe_phase(
                "device_compute", dt_step, program="serving/decode_step")
            self._metrics.note_decode(dt_step, self.active_count)
            produced = 0
            for slot, req in enumerate(self._active):
                if req is None:
                    continue
                self._pos[slot] += 1
                if self._pos[slot] < len(req.ids):
                    continue                      # still prefilling
                nxt = _sample_token(logits[slot], req, self._np_rng)
                req.ids.append(nxt)
                self._metrics.note_token(req)
                req.emit(nxt)
                req.remaining -= 1
                produced += 1
                if (req.remaining <= 0
                        or self._pos[slot] + 1 >= self.lm.max_len):
                    if req.remaining > 0:  # cache-capacity cut, not budget
                        req.finish_reason = "length"
                    self._retire(req, "finish")
                    req.future.set_result(
                        np.asarray(getattr(req, "prefix", []) + req.ids))
                    self._active[slot] = None
            with self._state_lock:
                self._tokens_done += produced
        for req in self._active:
            if req is not None and not req.future.done():
                self._retire(req, "cancel")
                req.future.set_result(
                    np.asarray(getattr(req, "prefix", []) + req.ids))
        while True:
            try:
                req = self._pending.get_nowait()
            except queue.Empty:
                break
            if not req.future.done():
                self._metrics.note_retire(req, "cancel")
                req.future.set_exception(RuntimeError("engine stopped"))

    def stats(self) -> Dict[str, float]:
        """Live metrics in the shape `scheduler.autoscaler.ReplicaAutoscaler
        .observe` consumes: decode throughput since start, queue depth, and
        active batch occupancy.  The counter snapshot happens under
        ``_state_lock`` (the worker loop batches its updates under the
        same lock) and the SAME values are pushed to the Prometheus
        gauges, so the load report and /metrics can't disagree."""
        with self._state_lock:
            tokens_done = self._tokens_done
        dt = max(time.monotonic() - self._t_start, 1e-9)
        tps = tokens_done / dt
        depth = self._pending.qsize()
        active = self.active_count
        self._metrics.tps.set(tps)
        self._metrics.queue.set(depth)
        self._metrics.active.set(active)
        self._metrics.occupancy.set(active / self.max_batch)
        return {"tokens_per_s": tps, "queue_depth": depth,
                "active": active, "capacity": self.max_batch}

    def _can_multi(self, k: int) -> bool:
        """Multi-token dispatch applies when every active row has k
        positions of cache headroom (sampling — including top-k/nucleus
        filtering — runs on-device)."""
        for slot, req in enumerate(self._active):
            if req is None:
                continue
            if self._pos[slot] + k >= self.lm.max_len:
                return False
        return True

    def _step_multi(self, k: int) -> None:
        import jax

        jnp = self._jnp
        b = self.max_batch
        prompt_buf = np.zeros((b, k), np.int32)
        prompt_n = np.ones((b,), np.int32)
        temps = np.zeros((b,), np.float32)
        top_k = np.zeros((b,), np.int32)
        top_p = np.ones((b,), np.float32)
        for slot, req in enumerate(self._active):
            if req is None:
                continue
            pos = int(self._pos[slot])
            upcoming = req.ids[pos:pos + k]
            if not upcoming:           # mid-generation: feed last sample
                upcoming = [req.ids[-1]]
            prompt_buf[slot, :len(upcoming)] = upcoming
            prompt_n[slot] = len(upcoming)
            temps[slot] = req.temperature
            top_k[slot] = req.top_k
            top_p[slot] = req.top_p
        self._rng_key, sub = jax.random.split(self._rng_key)
        t_dispatch = time.monotonic()
        # exact-filter dispatch (VERDICT r4 item 7): on a big vocab any
        # filtered row routes the dispatch through the full-vocab
        # bisection sampler — it is EXACT for every top_k/top_p (no
        # 128-candidate truncation) and measured FASTER than the capped
        # path at GPT-2 geometry (331 vs 373 ms/dispatch, bs128 k16,
        # vocab 50257 on v5e: the bisection's ~60 compare+reduce passes
        # cost less than one 50k-wide lax.top_k per token).  Unfiltered
        # batches keep the plain path.  The flag is static per jit — at
        # most two compiled variants.
        from .kv_cache_lm import FILTER_CAP

        exact = bool(self.lm.vocab > FILTER_CAP and np.any(
            (temps > 0) & ((top_k > 0) | (top_p < 1.0))))
        self._cache, emitted = self.lm.decode_multi(
            self._cache, jnp.asarray(prompt_buf), jnp.asarray(prompt_n),
            jnp.asarray(self._pos), jnp.asarray(temps),
            jnp.asarray(top_k), jnp.asarray(top_p), sub, k,
            exact_filters=exact)
        emitted = np.asarray(emitted)
        dt_dispatch = time.monotonic() - t_dispatch
        self._metrics.step.observe(dt_dispatch)
        flight_recorder.observe_phase(
            "device_compute", dt_dispatch, program="serving/decode_step")
        self._metrics.note_decode(dt_dispatch, self.active_count)
        produced = 0
        for slot, req in enumerate(self._active):
            if req is None:
                continue
            # R = prompt-ish tokens that were still unfed at dispatch time;
            # emitted[slot, j] (output after feeding inner token j) is NEW
            # from j = R-1 on — and not at all when the chunk was entirely
            # prefill (R > k: emitted[k-1] predicts a KNOWN prompt token)
            r = len(req.ids) - int(self._pos[slot])
            self._pos[slot] += k
            start = r - 1 if r <= k else k
            # one host conversion per slot — the loop below touches only
            # Python ints, never the (already np.asarray'd) batch array
            row = emitted[slot].tolist()
            for j in range(start, k):
                if req.remaining <= 0:
                    break
                req.ids.append(row[j])
                self._metrics.note_token(req)
                req.emit(row[j])
                req.remaining -= 1
                produced += 1
            if req.cancelled.is_set():
                req.finish_reason = "cancelled"
            if (req.remaining <= 0 or req.cancelled.is_set()
                    or self._pos[slot] + 1 >= self.lm.max_len):
                if req.remaining > 0 and not req.cancelled.is_set():
                    req.finish_reason = "length"
                self._retire(req, "cancel" if req.cancelled.is_set()
                             else "finish")
                if not req.future.done():
                    req.future.set_result(
                        np.asarray(getattr(req, "prefix", []) + req.ids))
                self._active[slot] = None
        with self._state_lock:
            self._tokens_done += produced
