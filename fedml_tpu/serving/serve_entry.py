"""`fedml serve` — the config-driven serving entrypoint containers run.

Capability parity: the reference brings endpoints up inside containers
with health checks, records per-request metrics, and autoscales/replaces
replicas (`model_scheduler/device_model_deployment.py:89-928`,
`device_model_db.py`, `comm_utils/job_monitor.py`).  This module is the
TPU-era, container-free core the Dockerfile/compose/k8s assets call:

* a GATEWAY HTTP server (stdlib) fronting `ReplicaProcessManager`:
  /predict (round-robin to replica processes, per-request metrics into
  EndpointDB), /ready, /stats, /scale, /rollback;
* an autoscale loop DRIVEN FROM THE METRICS STORE: every tick reads the
  recent window (qps/latency) from EndpointDB and feeds
  `ReplicaAutoscaler.observe`, whose apply_fn is `manager.scale_to`;
* versioned-endpoint rollback: POST /rollback repoints the model card to
  its previous version (`ModelCardRegistry.rollback`) and rolling-
  restarts the replicas onto it.

Entry: ``fedml serve --card NAME [--port ...]`` (cli.py) or
``python -m fedml_tpu.serving.serve_entry``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Dict, Optional

from ..utils.http_json import DeepBacklogHTTPServer, BadRequest, JsonHandler
from ..scheduler.autoscaler import AutoscalePolicy, ReplicaAutoscaler
from ..scheduler.model_cards import EndpointDB, ModelCardRegistry
from ..scheduler.replica_manager import ReplicaProcessManager


class ServeGateway:
    def __init__(self, card_name: str,
                 registry_root: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 replicas: int = 1,
                 db_path: Optional[str] = None,
                 policy: Optional[AutoscalePolicy] = None,
                 autoscale_interval_s: float = 10.0) -> None:
        self.card_name = card_name
        self.registry = ModelCardRegistry(root=registry_root)
        self.db = EndpointDB(path=db_path)
        self.manager = ReplicaProcessManager(card_name,
                                             registry_root=registry_root)
        self.policy = policy or AutoscalePolicy(
            min_replicas=int(replicas))
        self.autoscaler = ReplicaAutoscaler(
            self.policy, apply_fn=self.manager.scale_to)
        self.autoscaler.replicas = int(replicas)
        self.autoscale_interval_s = float(autoscale_interval_s)
        self._stop = threading.Event()
        gw = self

        class Handler(JsonHandler):
            _reply = JsonHandler.reply

            def do_GET(self) -> None:  # noqa: N802
                if self.path == "/ready":
                    return self._reply(200, {
                        "ready": gw.manager.live_count() > 0})
                if self.path == "/stats":
                    return self._reply(200, gw.stats())
                return self._reply(404, {"error": "not found"})

            def do_POST(self) -> None:  # noqa: N802
                try:
                    body = self.json_body()
                except BadRequest:
                    return self._reply(400, {"error": "bad json"})
                if self.path == "/predict":
                    # record BEFORE replying: the metric must be visible
                    # to a /stats request issued right after the response
                    t0 = time.time()
                    try:
                        out = gw.manager.predict(body)
                        err = None
                    except RuntimeError as e:
                        err = str(e)
                    try:
                        gw.db.record(gw.card_name,
                                     (time.time() - t0) * 1000.0,
                                     err is None)
                    except Exception:  # noqa: BLE001 — sqlite lock under
                        # concurrent load; losing one metric sample must
                        # not drop the client's HTTP response
                        logging.exception("metrics record failed")
                    if err is not None:
                        return self._reply(503, {"error": err})
                    return self._reply(200, out)
                if self.path == "/scale":
                    try:
                        n_req = int(body["replicas"])
                        if n_req < 0:
                            raise ValueError
                    except (KeyError, ValueError, TypeError):
                        return self._reply(
                            400, {"error": "replicas: non-negative int"})
                    try:
                        n_now = gw.manager.scale_to(n_req)
                    except Exception as e:  # noqa: BLE001 — boot failure
                        logging.exception("scale failed")
                        gw.autoscaler.replicas = gw.manager.live_count()
                        return self._reply(500, {"error": str(e)})
                    # report/track the ACTUAL count, not the request
                    gw.autoscaler.replicas = n_now
                    return self._reply(200, {"replicas": n_now})
                if self.path == "/rollback":
                    try:
                        card = gw.rollback()
                        return self._reply(200, {
                            "version": card["version"]})
                    except (KeyError, RuntimeError) as e:
                        return self._reply(409, {"error": str(e)})
                    except Exception as e:  # noqa: BLE001 — e.g. EROFS
                        logging.exception("rollback failed")
                        return self._reply(500, {"error": str(e)})
                return self._reply(404, {"error": "not found"})

        # bind the HTTP port BEFORE booting replica processes: a bind
        # failure must not leak orphaned replica_worker children
        self._srv = DeepBacklogHTTPServer((host, port), Handler)
        self._srv.daemon_threads = True
        self.host, self.port = self._srv.server_address
        try:
            self.manager.scale_to(int(replicas))
            self.manager.start_monitor()
        except BaseException:
            self.manager.shutdown()
            self._srv.server_close()
            raise
        self._http_thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name="serve-gateway")
        self._scale_thread = threading.Thread(
            target=self._autoscale_loop, daemon=True,
            name="serve-autoscale")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServeGateway":
        self._http_thread.start()
        self._scale_thread.start()
        return self

    # -- metrics-driven autoscaling ----------------------------------------
    def autoscale_tick(self) -> int:
        """One observation from the REQUEST-METRICS STORE into the
        autoscaler (exposed for tests and external schedulers)."""
        w = self.db.window(self.card_name,
                           window_s=max(self.autoscale_interval_s * 3,
                                        30.0))
        return self.autoscaler.observe(w["qps"], w["avg_latency_s"])

    def _autoscale_loop(self) -> None:
        while not self._stop.wait(self.autoscale_interval_s):
            try:
                self.autoscale_tick()
            except Exception:  # noqa: BLE001 — keep the loop alive
                import logging

                logging.exception("autoscale tick failed")

    # -- versioned rollback -------------------------------------------------
    def rollback(self) -> Dict[str, Any]:
        """Repoint the card to its previous version and rolling-restart
        the replicas onto it.  If the restart fails (rolled-back version
        won't load), the registry is repointed BACK so the index never
        disagrees with what the surviving replicas actually serve."""
        before = self.registry.get(self.card_name)["version"]
        card = self.registry.rollback(self.card_name)
        try:
            self.manager.rolling_restart()
        except Exception:
            # repoint BACK, then best-effort restart so slots that already
            # swapped to the rolled-back version return to the current one
            # (otherwise they'd serve mixed versions silently)
            self.registry.repoint(self.card_name, before)
            try:
                self.manager.rolling_restart()
            except Exception:  # noqa: BLE001 — monitor keeps healing
                logging.exception("post-failure restore restart failed")
            raise
        return card

    def stats(self) -> Dict[str, Any]:
        card = self.registry.get(self.card_name)
        return {
            "card": self.card_name,
            "version": card["version"],
            "replicas": self.manager.stats(),
            "endpoint": self.db.stats(self.card_name),
            "window": self.db.window(self.card_name),
        }

    def stop(self) -> None:
        self._stop.set()
        self._srv.shutdown()
        self._srv.server_close()
        self.manager.shutdown()


def main(argv: Optional[list] = None) -> None:
    import argparse

    p = argparse.ArgumentParser(description="fedml_tpu serving gateway")
    p.add_argument("--card", required=True)
    p.add_argument("--registry-root", default=None)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=2345)
    p.add_argument("--replicas", type=int, default=1)
    p.add_argument("--db", default=None, help="endpoint metrics sqlite")
    p.add_argument("--max-replicas", type=int, default=8)
    p.add_argument("--target-latency-s", type=float, default=1.0)
    cli = p.parse_args(argv)
    gw = ServeGateway(
        cli.card, registry_root=cli.registry_root, host=cli.host,
        port=cli.port, replicas=cli.replicas, db_path=cli.db,
        policy=AutoscalePolicy(min_replicas=cli.replicas,
                               max_replicas=cli.max_replicas,
                               target_latency_s=cli.target_latency_s),
    ).start()
    print(json.dumps({"serving": gw.url, "card": cli.card}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        gw.stop()


if __name__ == "__main__":
    main()
