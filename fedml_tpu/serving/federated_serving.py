"""Federated-serving plane.

Capability parity: reference `serving/server/fedml_server_manager.py` (311
LoC) + `serving/client/`: a Client/Server manager pair mirroring cross-silo
that distributes the (aggregated) model to serving nodes, brings an
inference endpoint up on each, health-checks the fleet, and tears it down —
the FL-to-serving handoff plane.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, Optional

from ..core.distributed.communication.message import Message
from ..core.distributed.fedml_comm_manager import FedMLCommManager


class ServingMessage:
    MSG_TYPE_C2S_NODE_READY = "SERVE_C2S_NODE_READY"
    MSG_TYPE_S2C_DEPLOY_MODEL = "SERVE_S2C_DEPLOY_MODEL"
    MSG_TYPE_C2S_ENDPOINT_UP = "SERVE_C2S_ENDPOINT_UP"
    MSG_TYPE_S2C_HEALTH_CHECK = "SERVE_S2C_HEALTH_CHECK"
    MSG_TYPE_C2S_HEALTH_REPORT = "SERVE_C2S_HEALTH_REPORT"
    MSG_TYPE_S2C_UNDEPLOY = "SERVE_S2C_UNDEPLOY"

    ARG_MODEL_PARAMS = "model_params"
    ARG_MODEL_NAME = "model_name"
    ARG_ENDPOINT_URL = "endpoint_url"
    ARG_HEALTHY = "healthy"
    ARG_STATS = "stats"


class ServingServerManager(FedMLCommManager):
    """Distributes a model to serving nodes and tracks endpoint health."""

    def __init__(self, args: Any, model_name: str, model_params: Any,
                 comm=None, rank: int = 0, client_num: int = 0,
                 backend: str = "INPROC") -> None:
        super().__init__(args, comm, rank, client_num + 1, backend)
        self.model_name = model_name
        self.model_params = model_params
        self.client_num = client_num
        self.ready_nodes: set = set()
        self.endpoints: Dict[int, str] = {}
        self.failed: set = set()
        self.health: Dict[int, Dict[str, Any]] = {}
        self.all_up = threading.Event()
        self.all_healthy = threading.Event()

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            ServingMessage.MSG_TYPE_C2S_NODE_READY, self._on_node_ready)
        self.register_message_receive_handler(
            ServingMessage.MSG_TYPE_C2S_ENDPOINT_UP, self._on_endpoint_up)
        self.register_message_receive_handler(
            ServingMessage.MSG_TYPE_C2S_HEALTH_REPORT, self._on_health)

    def _on_node_ready(self, msg: Message) -> None:
        self.ready_nodes.add(msg.get_sender_id())
        if len(self.ready_nodes) == self.client_num:
            for r in sorted(self.ready_nodes):
                dep = Message(ServingMessage.MSG_TYPE_S2C_DEPLOY_MODEL,
                              self.get_sender_id(), r)
                dep.add_params(ServingMessage.ARG_MODEL_NAME, self.model_name)
                dep.add_params(ServingMessage.ARG_MODEL_PARAMS,
                               self.model_params)
                self.send_message(dep)

    def _on_endpoint_up(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        url = str(msg.get(ServingMessage.ARG_ENDPOINT_URL) or "")
        if url:
            self.endpoints[sender] = url
        else:
            self.failed.add(sender)  # node reported a failed deploy
        if len(self.endpoints) + len(self.failed) == self.client_num:
            self.all_up.set()
            for r in sorted(self.endpoints) + sorted(self.failed):
                self.send_message(Message(
                    ServingMessage.MSG_TYPE_S2C_HEALTH_CHECK,
                    self.get_sender_id(), r))

    def _on_health(self, msg: Message) -> None:
        sender = msg.get_sender_id()
        self.health[sender] = {
            "healthy": bool(msg.get(ServingMessage.ARG_HEALTHY)),
            "stats": msg.get(ServingMessage.ARG_STATS, {}),
        }
        if len(self.health) == self.client_num:
            self.all_healthy.set()
            self._finish_if_done()

    def _finish_if_done(self) -> None:
        if bool(getattr(self.args, "serving_oneshot", True)):
            for r in range(1, self.client_num + 1):
                self.send_message(Message(
                    ServingMessage.MSG_TYPE_S2C_UNDEPLOY,
                    self.get_sender_id(), r))
            self.finish()


class ServingClientManager(FedMLCommManager):
    """A serving node: receives the model, brings the HTTP endpoint up,
    answers health checks with gateway stats."""

    def __init__(self, args: Any, comm=None, rank: int = 0, size: int = 0,
                 backend: str = "INPROC",
                 predictor_factory: Optional[Callable[[Any], Any]] = None
                 ) -> None:
        super().__init__(args, comm, rank, size, backend)
        self.predictor_factory = predictor_factory
        self.endpoint = None

    def register_message_receive_handlers(self) -> None:
        self.register_message_receive_handler(
            ServingMessage.MSG_TYPE_S2C_DEPLOY_MODEL, self._on_deploy)
        self.register_message_receive_handler(
            ServingMessage.MSG_TYPE_S2C_HEALTH_CHECK, self._on_health_check)
        self.register_message_receive_handler(
            ServingMessage.MSG_TYPE_S2C_UNDEPLOY, self._on_undeploy)

    def run(self) -> None:
        self.register_message_receive_handlers()
        self.send_message(Message(ServingMessage.MSG_TYPE_C2S_NODE_READY,
                                  self.get_sender_id(), 0))
        self.com_manager.handle_receive_message()

    def _on_deploy(self, msg: Message) -> None:
        from ..scheduler.model_cards import Endpoint, EndpointDB
        from .fedml_inference_runner import serve_ephemeral
        from .fedml_predictor import LinearHeadPredictor

        name = str(msg.get(ServingMessage.ARG_MODEL_NAME))
        params = msg.get(ServingMessage.ARG_MODEL_PARAMS)
        try:
            if self.predictor_factory is not None:
                predictor = self.predictor_factory(params)
            else:
                predictor = LinearHeadPredictor(params)
            runner = serve_ephemeral(predictor, host="127.0.0.1")
            self.endpoint = Endpoint(name=f"{name}@{self.rank}",
                                     host="127.0.0.1", port=runner.port,
                                     runner=runner, db=EndpointDB())
            url = self.endpoint.url
        except Exception:  # noqa: BLE001 — a failed node must still
            # report in, or the server waits for its ENDPOINT_UP forever
            logging.exception("serving node %d: deploy failed", self.rank)
            self.endpoint = None
            url = ""
        up = Message(ServingMessage.MSG_TYPE_C2S_ENDPOINT_UP,
                     self.get_sender_id(), 0)
        up.add_params(ServingMessage.ARG_ENDPOINT_URL, url)
        self.send_message(up)

    def _on_health_check(self, msg: Message) -> None:
        healthy = self.endpoint is not None and self.endpoint.ready()
        rep = Message(ServingMessage.MSG_TYPE_C2S_HEALTH_REPORT,
                      self.get_sender_id(), 0)
        rep.add_params(ServingMessage.ARG_HEALTHY, healthy)
        rep.add_params(ServingMessage.ARG_STATS,
                       self.endpoint.stats() if self.endpoint else {})
        self.send_message(rep)

    def _on_undeploy(self, msg: Message) -> None:
        if self.endpoint is not None:
            self.endpoint.stop()
        logging.info("serving node %d: undeployed", self.rank)
        self.finish()


def deploy_federated(args: Any, model_name: str, model_params: Any,
                     n_nodes: int = 2,
                     predictor_factory: Optional[Callable] = None
                     ) -> Dict[str, Any]:
    """One-shot federated deploy over INPROC: server + n serving nodes;
    returns endpoints + health (the smoke path the reference exercises in
    its serving examples)."""
    server = ServingServerManager(args, model_name, model_params, rank=0,
                                  client_num=n_nodes, backend="INPROC")
    clients = [ServingClientManager(args, rank=r, size=n_nodes + 1,
                                    backend="INPROC",
                                    predictor_factory=predictor_factory)
               for r in range(1, n_nodes + 1)]
    threads = [c.run_async() for c in clients]
    # watchdog: a node whose thread died before reporting in would otherwise
    # leave the server blocked on its receive loop forever
    timeout = float(getattr(args, "serving_deploy_timeout", 120.0))
    server_thread = server.run_async()
    server_thread.join(timeout=timeout)
    timed_out = server_thread.is_alive()
    if timed_out:
        logging.error("deploy_federated: timed out after %.0fs; "
                      "tearing down", timeout)
        server.finish()
        server_thread.join(timeout=5)
        for c in clients:  # stop leaked receive loops + HTTP endpoints
            if c.endpoint is not None:
                c.endpoint.stop()
            c.finish()
    for t in threads:
        t.join(timeout=5 if timed_out else 30)
    return {"endpoints": dict(server.endpoints),
            "failed": sorted(server.failed),
            "timed_out": timed_out,
            "health": dict(server.health)}
