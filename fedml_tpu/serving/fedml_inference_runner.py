"""FedMLInferenceRunner — HTTP inference endpoint.

Capability parity: reference `serving/fedml_inference_runner.py:8-60` —
FastAPI app with POST /predict (streaming supported via generator responses)
and GET /ready.  This build prefers FastAPI when installed and falls back to
a dependency-free stdlib ThreadingHTTPServer with identical routes, so the
serving plane works in the zero-dependency image.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Optional

from .fedml_predictor import FedMLPredictor


class FedMLInferenceRunner:
    def __init__(self, predictor: FedMLPredictor, host: str = "0.0.0.0",
                 port: int = 2345) -> None:
        self.predictor = predictor
        self.host = host
        self.port = port
        self._server = None
        self._serve_thread: Optional[threading.Thread] = None

    # -- fastapi path --------------------------------------------------------
    def _try_fastapi(self) -> bool:
        try:
            import uvicorn
            from fastapi import FastAPI, Request
            from fastapi.responses import StreamingResponse
        except ImportError:
            return False
        app = FastAPI()
        predictor = self.predictor

        @app.post("/predict")
        async def predict(request: Request):
            body = await request.json()
            result = predictor.predict(body)
            if hasattr(result, "__iter__") and not isinstance(
                    result, (dict, list, str, bytes)):
                return StreamingResponse(result)
            return result

        @app.get("/ready")
        async def ready():
            return {"ready": predictor.ready()}

        uvicorn.run(app, host=self.host, port=self.port)
        return True

    # -- stdlib fallback -----------------------------------------------------
    def _serve_stdlib(self, block: bool) -> None:
        from http.server import BaseHTTPRequestHandler

        from ..utils.http_json import DeepBacklogHTTPServer

        predictor = self.predictor

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                logging.debug("serving: " + fmt, *args)

            def do_GET(self):
                if self.path == "/ready":
                    self._json(200, {"ready": predictor.ready()})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/predict":
                    self._json(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    result = predictor.predict(body)
                except Exception as e:  # noqa: BLE001
                    self._json(500, {"error": str(e)})
                    return
                if hasattr(result, "__iter__") and not isinstance(
                        result, (dict, list, str, bytes)):
                    # streaming: chunked transfer of generator output
                    self.send_response(200)
                    self.send_header("Transfer-Encoding", "chunked")
                    self.send_header("Content-Type", "text/plain")
                    self.end_headers()
                    for chunk in result:
                        data = (chunk if isinstance(chunk, bytes)
                                else str(chunk).encode())
                        self.wfile.write(
                            f"{len(data):X}\r\n".encode() + data + b"\r\n")
                    self.wfile.write(b"0\r\n\r\n")
                    return
                self._json(200, result)

            def _json(self, code: int, obj: Any) -> None:
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = DeepBacklogHTTPServer((self.host, self.port), Handler)
        # port 0 → OS-assigned; resolve so callers see the bound port
        self.port = self._server.server_address[1]
        logging.info("inference endpoint on %s:%d", self.host, self.port)
        if block:
            self._server.serve_forever()
        else:
            self._serve_thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name=f"inference-endpoint-{self.port}")
            self._serve_thread.start()

    def run(self, block: bool = True, prefer_fastapi: bool = True) -> None:
        if prefer_fastapi and block and self._try_fastapi():
            return
        self._serve_stdlib(block)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            if self._serve_thread is not None:
                # reap the serve thread so stop() really means stopped —
                # callers rebind the port right after
                self._serve_thread.join(timeout=5)
                self._serve_thread = None
            # shutdown() only stops the accept loop; the listening socket
            # stays bound until server_close() releases it
            self._server.server_close()


def serve_ephemeral(predictor: FedMLPredictor, host: str = "127.0.0.1",
                    port: int = 0) -> "FedMLInferenceRunner":
    """Bring an endpoint up on `port` (0 → the OS assigns a free one at bind
    time, so concurrent callers can't race) in a background thread; returns
    the runner with `.port` resolved."""
    runner = FedMLInferenceRunner(predictor, host=host, port=port)
    runner.run(block=False, prefer_fastapi=False)
    return runner
