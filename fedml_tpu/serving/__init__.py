from .fedml_inference_runner import FedMLInferenceRunner
from .fedml_predictor import FedMLPredictor

__all__ = ["FedMLPredictor", "FedMLInferenceRunner"]
