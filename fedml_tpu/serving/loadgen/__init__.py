"""Serving observatory — open-loop load plane for the LLM engines.

Closed-loop drivers (N workers, each waiting for its response before
sending the next request) can never see queueing collapse: when the
engine slows down the offered load slows down with it, so latency looks
flat right up to the cliff.  This package drives the engines OPEN-loop —
arrivals follow a process (Poisson / bursty Markov-modulated / trace
replay) that does not care how the engine is doing — and makes every
request observable end to end:

* `arrivals` — arrival processes + prompt/output-length distributions
  sampled from committed histograms (the `size_hist` wire encoding);
* `driver` — `OpenLoopDriver`: submits the schedule into a live engine,
  samples queue-depth/occupancy gauges, self-measures its own overhead;
* `report` — per-request summaries (p50/p99 TTFT and TBT, tokens/s,
  shed rate) and the offered-load sweep → degradation curve with
  saturation-knee detection;
* `anatomy` — the `round_anatomy()` idiom applied per request: joins
  ledger lifecycle events + spans into a queue→prefill→decode timeline.

CLI surface: ``fedml load run|report|curve`` (see `cli.cli`).
"""

from .arrivals import (LengthSampler, MarkovModulatedProcess,
                       PoissonProcess, TraceProcess, parse_arrivals)
from .driver import LoadResult, OpenLoopDriver
from .report import (degradation_curve, find_knee, render_curve,
                     render_report, summarize_requests)
from .anatomy import (coverage, render_exemplars, render_request_timeline,
                      request_anatomy)
from .harness import (DEFAULT_GEOMETRY, build_engine, build_model,
                      run_soak, summarize, warm_engine, write_artifacts)

__all__ = [
    "PoissonProcess", "MarkovModulatedProcess", "TraceProcess",
    "parse_arrivals", "LengthSampler",
    "OpenLoopDriver", "LoadResult",
    "summarize_requests", "render_report", "degradation_curve",
    "find_knee", "render_curve",
    "request_anatomy", "render_request_timeline", "render_exemplars",
    "coverage",
    "DEFAULT_GEOMETRY", "build_model", "build_engine", "warm_engine",
    "run_soak", "summarize", "write_artifacts",
]
