"""Load-soak summaries and the offered-load degradation curve.

`summarize_requests` turns per-request rows (the driver's output, or a
reloaded ``requests.jsonl``) into the serving headline numbers: p50/p99
TTFT with its queue/prefill decomposition, p50/p99 TBT (finished
requests only), goodput, tokens/s and shed rate.

`degradation_curve` sweeps offered load and `find_knee` locates the
saturation point: the highest offered QPS the engine still serves at
goodput (≥ ``goodput_floor`` of offered) within the TTFT SLO.  Past the
knee a healthy engine DEGRADES GRACEFULLY — shed rate rises while
admitted-request p99 stays bounded; a collapsing one shows p99 growing
without bound.  `render_curve` prints exactly that contrast.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


def _pct(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    return float(np.percentile(np.asarray(values, dtype=np.float64), q))


def summarize_requests(rows: Sequence[Dict[str, Any]],
                       duration_s: float,
                       wall_s: Optional[float] = None,
                       overhead_s: Optional[float] = None
                       ) -> Dict[str, Any]:
    """Headline numbers for one soak at a fixed offered load."""
    rows = list(rows)
    finished = [r for r in rows if r.get("outcome") == "finish"]
    cancelled = [r for r in rows if r.get("outcome") == "cancel"]
    shed = [r for r in rows if r.get("outcome") == "shed"]
    admitted = [r for r in rows if r.get("outcome") != "shed"]
    ttfts = [r["ttft_s"] for r in admitted if r.get("ttft_s") is not None]
    qwaits = [r["queue_wait_s"] for r in admitted
              if r.get("queue_wait_s") is not None]
    prefills = [r["prefill_s"] for r in admitted
                if r.get("prefill_s") is not None]
    # TBT comes only from FINISHED requests — a cancelled stream's gaps
    # must not skew the percentiles (mirrors fedml_llm_tbt_seconds)
    tbts = [r["tbt_s"] for r in finished if r.get("tbt_s") is not None]
    tokens = int(sum(int(r.get("tokens") or 0) for r in rows))
    dur = max(float(duration_s), 1e-9)
    span = max(float(wall_s if wall_s is not None else duration_s), 1e-9)
    out: Dict[str, Any] = {
        "offered": len(rows),
        "offered_qps": len(rows) / dur,
        "finished": len(finished),
        "cancelled": len(cancelled),
        "shed": len(shed),
        "shed_rate": len(shed) / max(len(rows), 1),
        "goodput_qps": len(finished) / dur,
        "tokens": tokens,
        "tokens_per_s": tokens / span,
        "ttft_p50": _pct(ttfts, 50), "ttft_p99": _pct(ttfts, 99),
        "queue_wait_p50": _pct(qwaits, 50),
        "queue_wait_p99": _pct(qwaits, 99),
        "prefill_p50": _pct(prefills, 50),
        "prefill_p99": _pct(prefills, 99),
        "tbt_p50": _pct(tbts, 50), "tbt_p99": _pct(tbts, 99),
        "duration_s": float(duration_s),
        "wall_s": float(wall_s) if wall_s is not None else None,
    }
    if overhead_s is not None and wall_s is not None:
        out["overhead_s"] = float(overhead_s)
        out["overhead_frac"] = float(overhead_s) / span
    return out


def _fmt_ms(v: Optional[float]) -> str:
    return "    --" if v is None else f"{v * 1e3:6.1f}"


def render_report(summary: Dict[str, Any]) -> str:
    """Human rendering of one soak summary (`fedml load report`)."""
    s = summary
    lines = [
        f"offered  {s['offered']} requests @ {s['offered_qps']:.2f} qps "
        f"over {s['duration_s']:.1f}s",
        f"outcome  finished {s['finished']}  cancelled {s['cancelled']}  "
        f"shed {s['shed']}  (shed rate {s['shed_rate'] * 100:.1f}%)",
        f"goodput  {s['goodput_qps']:.2f} qps   "
        f"tokens {s['tokens']} ({s['tokens_per_s']:.1f} tok/s)",
        "latency (ms)        p50     p99",
        f"  ttft           {_fmt_ms(s['ttft_p50'])}  "
        f"{_fmt_ms(s['ttft_p99'])}",
        f"    queue wait   {_fmt_ms(s['queue_wait_p50'])}  "
        f"{_fmt_ms(s['queue_wait_p99'])}",
        f"    prefill      {_fmt_ms(s['prefill_p50'])}  "
        f"{_fmt_ms(s['prefill_p99'])}",
        f"  tbt            {_fmt_ms(s['tbt_p50'])}  "
        f"{_fmt_ms(s['tbt_p99'])}",
    ]
    if s.get("overhead_frac") is not None:
        lines.append(
            f"observability overhead {s['overhead_s']:.3f}s "
            f"({s['overhead_frac'] * 100:.2f}% of wall)")
    return "\n".join(lines)


def degradation_curve(run_at: Callable[[float], Dict[str, Any]],
                      qps_points: Sequence[float]) -> List[Dict[str, Any]]:
    """Sweep offered load: ``run_at(qps)`` → one soak summary per point
    (ascending offered QPS so warm-compile cost lands on the first)."""
    return [dict(run_at(float(q)), sweep_qps=float(q))
            for q in sorted(qps_points)]


def find_knee(points: Sequence[Dict[str, Any]],
              slo_ttft_p99_s: float,
              goodput_floor: float = 0.9) -> Optional[Dict[str, Any]]:
    """The saturation knee: the HIGHEST offered point still serving at
    goodput ≥ floor×offered with admitted p99 TTFT inside the SLO.
    None when even the lowest point breaches (engine undersized)."""
    knee = None
    for p in sorted(points, key=lambda p: p["offered_qps"]):
        ttft = p.get("ttft_p99")
        good = p["goodput_qps"] >= goodput_floor * p["offered_qps"]
        in_slo = ttft is not None and ttft <= slo_ttft_p99_s
        if good and in_slo:
            knee = p
    return knee


def render_curve(points: Sequence[Dict[str, Any]],
                 slo_ttft_p99_s: float,
                 goodput_floor: float = 0.9) -> str:
    """The degradation table (`fedml load curve`): one row per offered
    point, the knee marked, and a verdict on post-knee behaviour —
    graceful (bounded admitted p99, shed rate absorbing the excess) or
    collapsing (p99 past SLO with nothing shed)."""
    knee = find_knee(points, slo_ttft_p99_s, goodput_floor)
    lines = [
        "offered_qps  goodput_qps  ttft_p50(ms)  ttft_p99(ms)  "
        "tbt_p99(ms)  shed%    tok/s",
    ]
    for p in sorted(points, key=lambda p: p["offered_qps"]):
        mark = "  <- knee" if knee is not None and p is knee else ""
        lines.append(
            f"{p['offered_qps']:11.2f}  {p['goodput_qps']:11.2f}  "
            f"{_fmt_ms(p['ttft_p50']):>12}  {_fmt_ms(p['ttft_p99']):>12}  "
            f"{_fmt_ms(p['tbt_p99']):>11}  {p['shed_rate'] * 100:5.1f}  "
            f"{p['tokens_per_s']:7.1f}{mark}")
    if knee is None:
        lines.append(
            f"no knee: every point breaches the SLO "
            f"(ttft p99 <= {slo_ttft_p99_s * 1e3:.0f} ms, "
            f"goodput >= {goodput_floor * 100:.0f}% of offered)")
        return "\n".join(lines)
    lines.append(
        f"saturation knee: {knee['offered_qps']:.2f} qps offered "
        f"({knee['goodput_qps']:.2f} qps goodput, ttft p99 "
        f"{knee['ttft_p99'] * 1e3:.1f} ms)")
    past = [p for p in points
            if p["offered_qps"] > knee["offered_qps"]]
    if past:
        bounded = [p for p in past
                   if p.get("ttft_p99") is not None
                   and p["ttft_p99"] <= slo_ttft_p99_s]
        shedding = [p for p in past if p["shed_rate"] > 0.0]
        if len(bounded) == len(past) and shedding:
            lines.append(
                "past the knee: GRACEFUL — admitted p99 stays inside "
                "the SLO while shed rate absorbs the excess "
                f"(max shed {max(p['shed_rate'] for p in past) * 100:.1f}%)")
        elif shedding:
            lines.append(
                "past the knee: shedding engaged but admitted p99 "
                "breaches the SLO — shed earlier (tighten the "
                "admission queue/ttft budget)")
        else:
            lines.append(
                "past the knee: COLLAPSING — no shedding, p99 unbounded "
                "(run with --admission to bound it)")
    return "\n".join(lines)
