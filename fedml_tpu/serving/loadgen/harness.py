"""Soak harness behind ``fedml load run|curve`` (and the loadgen tests).

The CLI-facing glue: build a CPU-proxy engine from geometry flags, warm
the jit caches OUTSIDE the measured window (the first prefill of each
bucket and the first decode dispatch cost seconds of XLA compile — left
inside the soak they would dominate every latency percentile), run one
`OpenLoopDriver` soak, and write the artifact set that ``fedml load
report`` and ``fedml slo check --metrics`` consume offline::

    out/
      requests.jsonl   per-request lifecycle rows
      gauges.jsonl     queue-depth / occupancy / tok/s time series
      summary.json     the headline summary + run metadata
      metrics.prom     Prometheus scrape at soak end (offline SLO input)
      ledger.jsonl     serving lifecycle events   (when mlops is armed)
      spans.jsonl      serving.request spans      (when mlops is armed)

Warm-up uses a THROWAWAY engine over the same model object, then the
metrics registry is reset and the measured engine built fresh — the
model-level jits (prefill buckets, decode dispatch) are module-scoped in
`kv_cache_lm`, so the compile cache survives while the warm-up's
multi-second TTFTs never reach the measured histograms.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ...core.mlops import metrics as _metrics
from .driver import LoadResult, OpenLoopDriver
from .report import summarize_requests

#: tiny CPU-proxy geometry — same scale the serving tier-1 tests use, so
#: a quick soak compiles in seconds and queues under tens of offered QPS
DEFAULT_GEOMETRY: Dict[str, int] = {
    "vocab": 90, "dim": 32, "layers": 2, "heads": 4, "max_len": 96,
    "max_batch": 4, "tokens_per_dispatch": 4, "window": 24,
}


def build_model(kind: str = "kv", seed: int = 0,
                **geometry: int) -> Any:
    """The (engine-independent) model object: a `KVCacheLM` for the kv
    engine, a ``(bundle, variables)`` pair for the batched engine."""
    g = dict(DEFAULT_GEOMETRY, **geometry)
    import jax
    if kind == "kv":
        from ..kv_cache_lm import KVCacheLM

        return KVCacheLM.create(
            jax.random.PRNGKey(seed), vocab=g["vocab"], dim=g["dim"],
            layers=g["layers"], heads=g["heads"], max_len=g["max_len"])
    if kind == "batched":
        # the stock tiny transformer bundle (geometry dims fixed by the
        # model hub config; vocab still honoured)
        import fedml_tpu

        args = fedml_tpu.Config(model="transformer", dataset="shakespeare",
                                compute_dtype="float32")
        bundle = fedml_tpu.model.create(args, g["vocab"])
        variables = bundle.init_variables(jax.random.PRNGKey(seed),
                                          batch_size=2)
        return (bundle, variables)
    raise ValueError(f"unknown engine kind {kind!r} (want 'kv'|'batched')")


def build_engine(model: Any, kind: str = "kv", admission: Any = None,
                 **geometry: int) -> Any:
    g = dict(DEFAULT_GEOMETRY, **geometry)
    from ..llm_engine import BatchedLLMEngine, KVCacheLLMEngine

    if kind == "kv":
        return KVCacheLLMEngine(
            model, max_batch=g["max_batch"],
            tokens_per_dispatch=g["tokens_per_dispatch"],
            admission=admission)
    if kind == "batched":
        bundle, variables = model
        return BatchedLLMEngine(bundle, variables,
                                max_batch=g["max_batch"],
                                window=g["window"], admission=admission)
    raise ValueError(f"unknown engine kind {kind!r} (want 'kv'|'batched')")


def warm_engine(engine: Any, max_prompt: int,
                tokens_per_dispatch: int = 4) -> int:
    """Touch every jit the soak will hit: one prompt per prefill bucket
    up to ``max_prompt`` plus a decode long enough to cover the
    multi-token dispatch.  Returns the number of warm-up requests."""
    buckets = getattr(type(engine), "_PREFILL_BUCKETS", None) or (max_prompt,)
    lengths = [b for b in buckets if b <= max_prompt] or [buckets[0]]
    if lengths[-1] < max_prompt:
        lengths.append(lengths[-1])          # max_prompt rides that bucket
    futs = [engine.submit(list(range(1, n + 1)),
                          max_new=max(2 * tokens_per_dispatch, 4),
                          temperature=0.0)
            for n in lengths]
    for fut in futs:
        fut.result(300.0)
    return len(futs)


def run_soak(engine: Any, arrivals: Any, lengths: Any, duration_s: float,
             vocab: int = 90, cancel_fraction: float = 0.0,
             seed: int = 0, gauge_period_s: float = 0.25,
             drain_timeout_s: float = 300.0) -> LoadResult:
    """One measured soak (the engine should already be warm)."""
    driver = OpenLoopDriver(
        engine, arrivals, lengths, duration_s=duration_s, vocab=vocab,
        cancel_fraction=cancel_fraction, gauge_period_s=gauge_period_s,
        seed=seed)
    return driver.run(drain_timeout_s=drain_timeout_s)


def summarize(result: LoadResult) -> Dict[str, Any]:
    s = summarize_requests(result.rows, result.duration_s,
                           wall_s=result.wall_s,
                           overhead_s=result.overhead_s)
    s["meta"] = dict(result.meta)
    return s


def write_artifacts(out_dir: str, result: LoadResult,
                    summary: Optional[Dict[str, Any]] = None) -> List[str]:
    """requests.jsonl + gauges.jsonl + summary.json + metrics.prom; the
    mlops-side ledger.jsonl/spans.jsonl land in the same dir when the
    run was armed with ``log_file_dir=out_dir``."""
    os.makedirs(out_dir, exist_ok=True)
    written = []

    def _jsonl(name: str, rows: List[Dict[str, Any]]) -> None:
        path = os.path.join(out_dir, name)
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        written.append(path)

    _jsonl("requests.jsonl", result.rows)
    _jsonl("gauges.jsonl", result.gauges)
    path = os.path.join(out_dir, "summary.json")
    with open(path, "w") as f:
        json.dump(summary if summary is not None else summarize(result),
                  f, indent=2, sort_keys=True)
        f.write("\n")
    written.append(path)
    path = os.path.join(out_dir, "metrics.prom")
    with open(path, "w") as f:
        f.write(_metrics.render_prometheus())
    written.append(path)
    return written
