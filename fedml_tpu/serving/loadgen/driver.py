"""OpenLoopDriver — submit an arrival schedule into a live engine.

The driver is deliberately single-threaded: `submit()` on both engines
is a non-blocking queue put, so one thread can sustain thousands of
arrivals per second while the engine's own worker thread does the
serving.  Between arrivals it sleeps in short chunks and samples the
engine's locked `stats()` snapshot (which also refreshes the Prometheus
gauges), giving the report a queue-depth/occupancy time series without
a sampler thread.

Observability honesty: the driver self-measures its own bookkeeping
(schedule precompute, row collection, gauge sampling) and folds it into
the same <2% overhead budget the ledger and flight recorder already
answer to — a load generator whose own cost is invisible would corrupt
the very envelope it measures.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ...core.mlops import ledger
from ...core.mlops import metrics as _metrics
from ..admission import ShedError


def _observability_overhead_s() -> float:
    """Combined self-measured bookkeeping seconds: ledger + flight
    recorder (their counters survive re-arms within a process)."""
    rec = _metrics.counter(
        "fedml_flight_recorder_overhead_seconds_total",
        "Recorder bookkeeping time, self-measured (CI budget: <2% of "
        "attributed wall)")
    return ledger.overhead_s() + float(getattr(rec, "value", 0.0))


class LoadResult:
    """Everything one soak produced: per-request rows, the gauge time
    series, and the wall/overhead accounting the report consumes."""

    def __init__(self, rows: List[Dict[str, Any]],
                 gauges: List[Dict[str, Any]], wall_s: float,
                 driver_overhead_s: float, observability_overhead_s: float,
                 offered: int, duration_s: float,
                 meta: Optional[Dict[str, Any]] = None) -> None:
        self.rows = rows
        self.gauges = gauges
        self.wall_s = wall_s
        self.driver_overhead_s = driver_overhead_s
        self.observability_overhead_s = observability_overhead_s
        self.offered = offered
        self.duration_s = duration_s
        self.meta = dict(meta or {})

    @property
    def overhead_s(self) -> float:
        return self.driver_overhead_s + self.observability_overhead_s

    @property
    def overhead_frac(self) -> float:
        return self.overhead_s / max(self.wall_s, 1e-9)

    def offered_qps(self) -> float:
        return self.offered / max(self.duration_s, 1e-9)


def _row_from_request(req: Any, t0: float) -> Dict[str, Any]:
    """Flatten a retired `_Request`'s lifecycle timestamps into the
    requests.jsonl row shape (all offsets relative to soak start)."""
    tbt = None
    if (req.outcome == "finish" and req.n_generated >= 2
            and req.t_first_token is not None
            and req.t_last_token is not None):
        tbt = (req.t_last_token - req.t_first_token) \
            / (req.n_generated - 1)
    ttft = None
    if req.t_first_token is not None:
        ttft = req.t_first_token - req.t_submit
    service = None
    if req.t_finish is not None:
        service = req.t_finish - req.t_submit
    return {
        "rid": req.rid,
        "outcome": req.outcome,
        "finish_reason": req.finish_reason,
        "prompt_tokens": len(req.ids) - req.n_generated,
        "tokens": req.n_generated,
        "t_submit": round(req.t_submit - t0, 6),
        "queue_wait_s": round(req.queue_wait_s(), 6),
        "prefill_s": round(req.prefill_s(), 6),
        "ttft_s": None if ttft is None else round(ttft, 6),
        "tbt_s": None if tbt is None else round(tbt, 6),
        "service_s": None if service is None else round(service, 6),
    }


class OpenLoopDriver:
    """Drive one engine with one arrival process for one soak.

    * ``engine`` — `BatchedLLMEngine` / `KVCacheLLMEngine` (anything
      with ``submit``/``stats``);
    * ``process`` — an arrivals process (`arrivals.parse_arrivals`);
    * ``lengths`` — a `LengthSampler`;
    * ``cancel_fraction`` — inject mid-stream client disconnects: that
      fraction of requests cancels itself after ``cancel_after_tokens``
      generated tokens (exercising the `cancel` lifecycle path under
      load, not just in unit tests).
    """

    def __init__(self, engine: Any, process: Any, lengths: Any,
                 duration_s: float, vocab: int = 90,
                 temperature: float = 0.0, cancel_fraction: float = 0.0,
                 cancel_after_tokens: int = 2,
                 gauge_period_s: float = 0.25, seed: int = 0) -> None:
        self.engine = engine
        self.process = process
        self.lengths = lengths
        self.duration_s = float(duration_s)
        self.vocab = int(vocab)
        self.temperature = float(temperature)
        self.cancel_fraction = float(cancel_fraction)
        self.cancel_after_tokens = max(int(cancel_after_tokens), 1)
        self.gauge_period_s = float(gauge_period_s)
        self.seed = int(seed)

    def run(self, drain_timeout_s: float = 120.0) -> LoadResult:
        rng = np.random.default_rng(self.seed)
        t_prep = time.monotonic()
        offsets = np.asarray(self.process.schedule(self.duration_s))
        plan = []
        for i in range(offsets.size):
            lens = self.lengths.sample()
            plan.append((
                float(offsets[i]),
                rng.integers(1, max(self.vocab, 2),
                             size=max(lens["prompt_tokens"], 1)).tolist(),
                max(lens["output_tokens"], 1),
                bool(self.cancel_fraction > 0.0
                     and rng.random() < self.cancel_fraction),
            ))
        driver_overhead = time.monotonic() - t_prep

        futures: List[Any] = []
        gauges: List[Dict[str, Any]] = []
        obs0 = _observability_overhead_s()
        t0 = time.monotonic()
        next_gauge = t0

        def _sample_gauges(now: float) -> float:
            s = self.engine.stats()
            gauges.append({"t": round(now - t0, 3),
                           "queue_depth": s["queue_depth"],
                           "active": s["active"],
                           "tokens_per_s": round(s["tokens_per_s"], 3)})
            return now + self.gauge_period_s

        for offset, prompt_ids, max_new, inject_cancel in plan:
            # open loop: sleep to the SCHEDULED arrival, never to "when
            # the engine is ready" — chunked so gauge samples keep coming
            while True:
                now = time.monotonic()
                if now >= next_gauge:
                    t_book = time.monotonic()
                    next_gauge = _sample_gauges(now)
                    driver_overhead += time.monotonic() - t_book
                wait = (t0 + offset) - time.monotonic()
                if wait <= 0:
                    break
                time.sleep(min(wait, max(self.gauge_period_s, 0.01)))
            on_token = None
            if inject_cancel:
                on_token = _CancelAfter(self.cancel_after_tokens)
            fut = self.engine.submit(prompt_ids, max_new=max_new,
                                     temperature=self.temperature,
                                     on_token=on_token)
            if on_token is not None:
                on_token.bind(getattr(fut, "request", None))
            futures.append(fut)

        # drain: every in-flight request must resolve before the clock
        # stops (shed futures are already resolved with ShedError)
        deadline = time.monotonic() + drain_timeout_s
        for fut in futures:
            try:
                fut.result(max(deadline - time.monotonic(), 0.01))
            except ShedError:
                pass              # shed at submit: the row records it
            except Exception:  # noqa: BLE001 — a wedged request can't stop the report
                req = getattr(fut, "request", None)
                if req is not None:
                    req.cancel()
        wall_s = time.monotonic() - t0

        t_book = time.monotonic()
        rows = [_row_from_request(fut.request, t0) for fut in futures
                if getattr(fut, "request", None) is not None]
        driver_overhead += time.monotonic() - t_book
        return LoadResult(
            rows=rows, gauges=gauges, wall_s=wall_s,
            driver_overhead_s=driver_overhead,
            observability_overhead_s=_observability_overhead_s() - obs0,
            offered=len(plan), duration_s=self.duration_s,
            meta={"process": self.process.describe(),
                  "lengths": self.lengths.describe(),
                  "engine": type(self.engine).__name__,
                  "cancel_fraction": self.cancel_fraction})


class _CancelAfter:
    """Per-token callback that cancels its request after N tokens — the
    loadgen's stand-in for a client that disconnects mid-decode."""

    def __init__(self, after: int) -> None:
        self.after = int(after)
        self.seen = 0
        self.req: Any = None

    def bind(self, req: Any) -> None:
        self.req = req

    def __call__(self, _tok: int) -> None:
        self.seen += 1
        if self.req is not None and self.seen >= self.after:
            self.req.cancel()
