"""Arrival processes and length distributions for the open-loop driver.

Every process yields ABSOLUTE arrival offsets (seconds from soak start),
precomputed before the soak begins so schedule generation never competes
with submission for the driver thread.  Three processes cover the
envelope the serving papers measure (the Gemma-on-TPU serving envelope,
arxiv 2605.25645, sweeps exactly these):

* `PoissonProcess`   — memoryless steady state at a target QPS;
* `MarkovModulatedProcess` — bursty MMPP-2: a hidden 2-state chain
  alternates a calm rate and a burst rate, exposing queue behaviour
  that a time-averaged Poisson at the same mean QPS hides;
* `TraceProcess`     — replay of recorded inter-arrivals from a JSONL
  trace or a previous run's ledger (`submit` events), optionally
  time-scaled, so production traffic shapes are reproducible offline.

`LengthSampler` draws prompt/output lengths from committed histograms in
the `size_hist` wire encoding (`data.population`), so benchmark length
mixes are versioned artifacts, not hardcoded constants.

`parse_arrivals` is the CLI-boundary parser (the `parse_wire_compression`
idiom): ``poisson:8`` | ``mmpp:2:20:0.1`` | ``trace:path[:scale]``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class PoissonProcess:
    """Memoryless arrivals at ``rate_qps``: exponential inter-arrivals."""

    def __init__(self, rate_qps: float, seed: int = 0) -> None:
        if rate_qps <= 0:
            raise ValueError("rate_qps must be > 0")
        self.rate_qps = float(rate_qps)
        self.seed = int(seed)

    def schedule(self, duration_s: float) -> np.ndarray:
        """Arrival offsets in [0, duration_s), sorted ascending."""
        rng = np.random.default_rng(self.seed)
        # draw enough gaps to overshoot the horizon with margin
        n = max(int(self.rate_qps * duration_s * 2) + 16, 16)
        t = np.cumsum(rng.exponential(1.0 / self.rate_qps, size=n))
        while t[-1] < duration_s:
            t = np.concatenate(
                [t, t[-1] + np.cumsum(
                    rng.exponential(1.0 / self.rate_qps, size=n))])
        return t[t < duration_s]

    def describe(self) -> Dict[str, Any]:
        return {"process": "poisson", "rate_qps": self.rate_qps}


class MarkovModulatedProcess:
    """MMPP-2 bursty arrivals: a hidden 2-state Markov chain switches
    between ``calm_qps`` and ``burst_qps``; ``switch_p`` is the per-event
    probability of flipping state.  Mean rate sits between the two, but
    the burst state drives queue excursions a flat Poisson never shows.
    """

    def __init__(self, calm_qps: float, burst_qps: float,
                 switch_p: float = 0.1, seed: int = 0) -> None:
        if calm_qps <= 0 or burst_qps <= 0:
            raise ValueError("rates must be > 0")
        if not 0.0 < switch_p <= 1.0:
            raise ValueError("switch_p must be in (0, 1]")
        self.calm_qps = float(calm_qps)
        self.burst_qps = float(burst_qps)
        self.switch_p = float(switch_p)
        self.seed = int(seed)

    def schedule(self, duration_s: float) -> np.ndarray:
        rng = np.random.default_rng(self.seed)
        out: List[float] = []
        t = 0.0
        bursting = False
        while t < duration_s:
            rate = self.burst_qps if bursting else self.calm_qps
            t += float(rng.exponential(1.0 / rate))
            if t < duration_s:
                out.append(t)
            if rng.random() < self.switch_p:
                bursting = not bursting
        return np.asarray(out)

    def describe(self) -> Dict[str, Any]:
        return {"process": "mmpp", "calm_qps": self.calm_qps,
                "burst_qps": self.burst_qps, "switch_p": self.switch_p}


class TraceProcess:
    """Replay recorded arrival offsets.  ``scale`` > 1 speeds the trace
    up (offsets divided by scale → higher offered load), the standard
    trace-acceleration knob."""

    def __init__(self, offsets_s: Sequence[float],
                 scale: float = 1.0) -> None:
        if scale <= 0:
            raise ValueError("scale must be > 0")
        arr = np.sort(np.asarray(list(offsets_s), dtype=np.float64))
        if arr.size == 0:
            raise ValueError("trace has no arrivals")
        self._offsets = (arr - arr[0]) / float(scale)
        self.scale = float(scale)

    @classmethod
    def from_jsonl(cls, path: str, scale: float = 1.0,
                   key: str = "ts") -> "TraceProcess":
        """Trace file: one JSON object per line carrying an absolute or
        relative timestamp under ``key`` (bare numbers also accepted)."""
        offsets: List[float] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, (int, float)):
                    offsets.append(float(rec))
                elif isinstance(rec, dict) and key in rec:
                    offsets.append(float(rec[key]))
        return cls(offsets, scale=scale)

    @classmethod
    def from_ledger(cls, path: str, scale: float = 1.0) -> "TraceProcess":
        """Replay the ``submit`` events of a previous run's ledger — the
        observatory can re-drive yesterday's traffic shape."""
        from ...core.mlops.ledger import load_ledger

        offsets = [float(r.get("ts_mono", 0.0)) for r in load_ledger(path)
                   if r.get("actor") == "serving"
                   and r.get("event") == "submit"]
        return cls(offsets, scale=scale)

    def schedule(self, duration_s: float) -> np.ndarray:
        return self._offsets[self._offsets < duration_s]

    def describe(self) -> Dict[str, Any]:
        return {"process": "trace", "arrivals": int(self._offsets.size),
                "scale": self.scale}


def parse_arrivals(spec: str, seed: int = 0):
    """CLI-boundary parser: ``poisson:QPS`` | ``mmpp:CALM:BURST[:P]`` |
    ``trace:PATH[:SCALE]`` → a process.  Raises ValueError on a
    malformed spec so bad flags die at startup, not mid-soak."""
    parts = [p for p in str(spec).strip().split(":") if p != ""]
    if not parts:
        raise ValueError("empty arrivals spec")
    kind = parts[0].lower()
    try:
        if kind == "poisson" and len(parts) == 2:
            return PoissonProcess(float(parts[1]), seed=seed)
        if kind == "mmpp" and len(parts) in (3, 4):
            p = float(parts[3]) if len(parts) == 4 else 0.1
            return MarkovModulatedProcess(float(parts[1]), float(parts[2]),
                                          switch_p=p, seed=seed)
        if kind == "trace" and len(parts) in (2, 3):
            scale = float(parts[2]) if len(parts) == 3 else 1.0
            path = parts[1]
            if os.path.isdir(path) or path.endswith("ledger.jsonl"):
                return TraceProcess.from_ledger(path, scale=scale)
            return TraceProcess.from_jsonl(path, scale=scale)
    except ValueError as e:
        if "arrivals spec" in str(e):
            raise
        raise ValueError(f"bad arrivals spec {spec!r}: {e}") from None
    raise ValueError(
        f"bad arrivals spec {spec!r} (want 'poisson:QPS', "
        f"'mmpp:CALM:BURST[:SWITCH_P]' or 'trace:PATH[:SCALE]')")


class LengthSampler:
    """Prompt/output lengths drawn from committed histograms.

    The histogram file carries the `size_hist` wire encoding from
    `data.population` (``[[value, count], ...]``) under ``prompt`` and
    ``output`` keys — a versioned artifact, so a benchmark's length mix
    is reviewable in the diff that changes it."""

    def __init__(self, prompt_hist: Any, output_hist: Any,
                 seed: int = 0) -> None:
        from ...data.population import expand_size_hist

        self._prompts = expand_size_hist(prompt_hist)
        self._outputs = expand_size_hist(output_hist)
        if self._prompts.size == 0 or self._outputs.size == 0:
            raise ValueError("length histogram is empty")
        self._rng = np.random.default_rng(seed)

    @classmethod
    def from_file(cls, path: str, seed: int = 0) -> "LengthSampler":
        with open(path) as f:
            payload = json.load(f)
        return cls(payload["prompt"], payload["output"], seed=seed)

    @classmethod
    def fixed(cls, prompt: int, output: int,
              seed: int = 0) -> "LengthSampler":
        return cls([[int(prompt), 1]], [[int(output), 1]], seed=seed)

    def sample(self) -> Dict[str, int]:
        return {
            "prompt_tokens": int(self._rng.choice(self._prompts)),
            "output_tokens": int(self._rng.choice(self._outputs)),
        }

    def describe(self) -> Dict[str, Any]:
        return {
            "prompt_mean": float(self._prompts.mean()),
            "output_mean": float(self._outputs.mean()),
            "prompt_max": int(self._prompts.max()),
            "output_max": int(self._outputs.max()),
        }
