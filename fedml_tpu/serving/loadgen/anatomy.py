"""Per-request anatomy — `round_anatomy()` applied to the serving plane.

The training plane's correlator answers "what happened to client 3 in
round 7?"; this one answers "what happened to request 1042?".  It joins
the serving lifecycle events a soak leaves in the run ledger (``submit →
admit|shed → prefill → first_token → finish|cancel``, all keyed by
``rid``) with the tracing plane's ``serving.request`` spans into one
timeline per request, rendered by ``fedml load report --anatomy``::

    request 1042 (kv)  outcome=finish
      +0.000s submit       prompt=32 max_new=24
      +0.013s admit        slot=1  queue_wait 13.1 ms
      +0.019s prefill      6.2 ms over 32 tokens
      +0.031s first_token  ttft 31.2 ms = queue 13.1 + prefill 6.2
                           + first_decode 11.9
      +0.412s finish       24 tokens, service 412.0 ms

`coverage` is the CI gate: the fraction of submitted requests whose
lifecycle reached a terminal event — an instrumentation regression
(a retire path that forgets its event) shows up as coverage < 1.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

#: terminal lifecycle events — every submit must reach exactly one
TERMINAL_EVENTS = ("finish", "cancel", "shed")


def request_anatomy(ledger_records: Sequence[Dict[str, Any]],
                    span_records: Optional[Sequence[Dict[str, Any]]] = None
                    ) -> Dict[str, Any]:
    """Join serving ledger events (+ optional spans) per request.

    Returns ``{"requests": {rid: {...}}, "submitted": N,
    "terminal": N, "coverage": frac, "outcomes": {...}}``.
    """
    requests: Dict[int, Dict[str, Any]] = {}
    for rec in ledger_records:
        if rec.get("actor") != "serving":
            continue
        attrs = rec.get("attrs") or {}
        rid = attrs.get("rid")
        if rid is None:
            continue        # aggregate events (decode_batch) have no rid
        rid = int(rid)
        r = requests.setdefault(rid, {
            "rid": rid, "events": [], "engine": attrs.get("engine"),
            "outcome": None, "span": None})
        r["events"].append({
            "event": rec.get("event"),
            "ts_mono": float(rec.get("ts_mono", 0.0)),
            "attrs": attrs,
        })
        if rec.get("event") in TERMINAL_EVENTS:
            r["outcome"] = rec.get("event")
    for r in requests.values():
        r["events"].sort(key=lambda e: e["ts_mono"])
    for span in span_records or []:
        rid = (span.get("attrs") or {}).get("rid")
        if rid is not None and int(rid) in requests:
            requests[int(rid)]["span"] = {
                "dur_s": span.get("dur_s"),
                "status": span.get("status"),
                "trace_id": span.get("trace_id"),
            }
    submitted = sum(
        1 for r in requests.values()
        if any(e["event"] == "submit" for e in r["events"]))
    terminal = sum(1 for r in requests.values()
                   if r["outcome"] is not None)
    outcomes: Dict[str, int] = {}
    for r in requests.values():
        key = r["outcome"] or "open"
        outcomes[key] = outcomes.get(key, 0) + 1
    return {
        "requests": requests,
        "submitted": submitted,
        "terminal": terminal,
        "coverage": terminal / submitted if submitted else 0.0,
        "outcomes": outcomes,
    }


def coverage(anatomy: Dict[str, Any]) -> float:
    """Lifecycle coverage: submitted requests that reached a terminal
    event (the smoke gate asserts >= 0.95)."""
    return float(anatomy.get("coverage", 0.0))


def _fmt_event(e: Dict[str, Any], t0: float) -> str:
    a = e["attrs"]
    name = e["event"]
    detail = ""
    if name == "submit":
        detail = (f"prompt={a.get('prompt_tokens')} "
                  f"max_new={a.get('max_new')}")
    elif name == "admit":
        detail = (f"slot={a.get('slot')}  queue_wait "
                  f"{float(a.get('queue_wait_s', 0.0)) * 1e3:.1f} ms")
    elif name == "shed":
        detail = (f"reason={a.get('reason')}  "
                  f"queue_depth={a.get('queue_depth')}")
    elif name == "prefill":
        detail = (f"{float(a.get('secs', 0.0)) * 1e3:.1f} ms over "
                  f"{a.get('tokens')} tokens")
    elif name == "first_token":
        detail = (f"ttft {float(a.get('ttft_s', 0.0)) * 1e3:.1f} ms = "
                  f"queue {float(a.get('queue_wait_s', 0.0)) * 1e3:.1f} "
                  f"+ prefill {float(a.get('prefill_s', 0.0)) * 1e3:.1f} "
                  f"+ first_decode "
                  f"{float(a.get('first_decode_s', 0.0)) * 1e3:.1f}")
    elif name in ("finish", "cancel"):
        detail = (f"{a.get('tokens')} tokens, service "
                  f"{float(a.get('service_s', 0.0)) * 1e3:.1f} ms "
                  f"({a.get('finish_reason')})")
    return f"  +{e['ts_mono'] - t0:7.3f}s {name:<12} {detail}".rstrip()


def render_request_timeline(anatomy: Dict[str, Any], rid: int) -> str:
    """One request's queue→prefill→decode timeline."""
    r = (anatomy.get("requests") or {}).get(int(rid))
    if r is None or not r["events"]:
        return f"(no lifecycle events for request {rid})"
    t0 = r["events"][0]["ts_mono"]
    head = (f"request {r['rid']} ({r.get('engine')})  "
            f"outcome={r['outcome'] or 'open'}")
    if r.get("span") is not None and r["span"].get("dur_s") is not None:
        head += f"  span {float(r['span']['dur_s']) * 1e3:.1f} ms"
    return "\n".join([head] + [_fmt_event(e, t0) for e in r["events"]])


def render_exemplars(anatomy: Dict[str, Any]) -> str:
    """The acceptance rendering: one COMPLETED request (the slowest
    TTFT, where the decomposition is most interesting) and one SHED
    request, plus the outcome census and coverage line."""
    reqs = list((anatomy.get("requests") or {}).values())

    def _ttft(r: Dict[str, Any]) -> float:
        for e in r["events"]:
            if e["event"] == "first_token":
                return float(e["attrs"].get("ttft_s", 0.0))
        return -1.0

    finished = [r for r in reqs if r["outcome"] == "finish"]
    shed = [r for r in reqs if r["outcome"] == "shed"]
    cancelled = [r for r in reqs if r["outcome"] == "cancel"]
    parts: List[str] = [
        f"lifecycle coverage {anatomy['coverage'] * 100:.1f}% "
        f"({anatomy['terminal']}/{anatomy['submitted']} submitted "
        f"reached a terminal event)",
        "outcomes " + "  ".join(
            f"{k}={v}" for k, v in sorted(anatomy["outcomes"].items())),
    ]
    if finished:
        worst = max(finished, key=_ttft)
        parts += ["", "slowest completed request:",
                  render_request_timeline(anatomy, worst["rid"])]
    if cancelled:
        parts += ["", "a cancelled (client-disconnect) request:",
                  render_request_timeline(anatomy, cancelled[0]["rid"])]
    if shed:
        parts += ["", "a shed request:",
                  render_request_timeline(anatomy, shed[0]["rid"])]
    return "\n".join(parts)
