"""OpenAI-compatible chat API over a FedMLPredictor.

Capability parity: reference `serving/templates/hf_template/main_openai.py`
(254 LoC): `/v1/chat/completions` (streaming SSE + non-streaming) and
`/v1/models` in the OpenAI wire format, so OpenAI SDK clients can point at a
deployed model unchanged. The generation backend is any `FedMLPredictor`
whose `predict` accepts `{"prompt": str, "max_tokens": int, ...}` and
returns either a string or a token generator (the LLM trainer's models
plug in here).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional

from .admission import ShedError
from .fedml_predictor import FedMLPredictor


def _messages_to_prompt(messages: List[Dict[str, str]]) -> str:
    """Flatten a chat transcript to the template the LLM trainer uses."""
    parts = []
    for m in messages:
        parts.append(f"{m.get('role', 'user')}: {m.get('content', '')}")
    parts.append("assistant:")
    return "\n".join(parts)


def _completion_body(model: str, text: str, finish: str = "stop"
                     ) -> Dict[str, Any]:
    return {
        "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "message": {"role": "assistant", "content": text},
            "finish_reason": finish,
        }],
        "usage": {"prompt_tokens": 0, "completion_tokens": len(text.split()),
                  "total_tokens": len(text.split())},
    }


def _chunk_body(model: str, delta: str, cid: str,
                finish: Optional[str] = None) -> Dict[str, Any]:
    return {
        "id": cid,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "delta": {} if finish else {"content": delta},
            "finish_reason": finish,
        }],
    }


class OpenAIServer:
    """stdlib HTTP server exposing /v1/chat/completions + /v1/models."""

    def __init__(self, predictor: FedMLPredictor, model_name: str = "fedml",
                 host: str = "127.0.0.1", port: int = 8000) -> None:
        self.predictor = predictor
        self.model_name = model_name
        self.host = host
        self.port = port
        self._server = None
        self._serve_thread: Optional[threading.Thread] = None

    def run(self, block: bool = True) -> None:
        from http.server import BaseHTTPRequestHandler

        predictor = self.predictor
        model_name = self.model_name

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                logging.debug("openai-api: " + fmt, *args)

            def do_GET(self):
                if self.path == "/v1/models":
                    self._json(200, {"object": "list", "data": [{
                        "id": model_name, "object": "model",
                        "created": int(time.time()), "owned_by": "fedml_tpu",
                    }]})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/v1/chat/completions":
                    self._json(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    prompt = _messages_to_prompt(body.get("messages", []))
                    req = {"prompt": prompt,
                           "max_tokens": int(body.get("max_tokens", 64)),
                           "temperature": float(
                               body.get("temperature", 1.0)),
                           "top_p": float(body.get("top_p", 1.0) or 1.0),
                           "top_k": int(body.get("top_k", 0) or 0),
                           "stream": bool(body.get("stream"))}
                    # predict_full carries finish_reason ("length" when the
                    # engine truncated the token budget) — prefer it
                    full = getattr(predictor, "predict_full", None)
                    meta = full(req) if callable(full) else None
                    result = (meta.get("stream", meta.get("text"))
                              if meta is not None else predictor.predict(req))
                except ShedError as e:
                    # admission shed → 429 in the OpenAI error shape, so
                    # SDK clients back off instead of retrying hot
                    self._json(429, {"error": {
                        "message": str(e), "type": "overloaded",
                        "code": e.reason}})
                    return
                except Exception as e:  # noqa: BLE001
                    self._json(500, {"error": {"message": str(e)}})
                    return
                if body.get("stream"):
                    finish_fn = meta.get("finish") if meta else None
                    self._stream(result, finish_fn)
                else:
                    try:
                        if not isinstance(result, str):
                            # lazy generators raise here, not in predict()
                            result = "".join(str(c) for c in result)
                    except ShedError as e:
                        self._json(429, {"error": {
                            "message": str(e), "type": "overloaded",
                            "code": e.reason}})
                        return
                    except Exception as e:  # noqa: BLE001
                        self._json(500, {"error": {"message": str(e)}})
                        return
                    finish = (meta or {}).get("finish_reason", "stop")
                    self._json(200, _completion_body(model_name, result,
                                                     finish))

            def _stream(self, result: Any, finish_fn=None) -> None:
                cid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.end_headers()
                chunks: Iterable[str] = ([result] if isinstance(result, str)
                                         else result)
                finish = "stop"
                try:
                    for chunk in chunks:
                        data = json.dumps(_chunk_body(model_name, str(chunk),
                                                      cid))
                        self.wfile.write(f"data: {data}\n\n".encode())
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    # client disconnected mid-decode: closing the token
                    # generator cancels the engine request (slot frees,
                    # lifecycle retires as `cancel`); nothing more can be
                    # written to this socket
                    close = getattr(chunks, "close", None)
                    if callable(close):
                        close()
                    return
                except Exception as e:  # noqa: BLE001
                    # headers are already out: surface the error as a final
                    # chunk so SDK clients still see a terminated stream
                    logging.exception("openai-api: generator failed")
                    err = json.dumps(_chunk_body(model_name,
                                                 f"[error: {e}]", cid))
                    self.wfile.write(f"data: {err}\n\n".encode())
                    finish = "error"
                else:
                    if finish_fn is not None:
                        finish = finish_fn() or "stop"
                done = json.dumps(_chunk_body(model_name, "", cid,
                                              finish=finish))
                self.wfile.write(f"data: {done}\n\n".encode())
                self.wfile.write(b"data: [DONE]\n\n")

            def _json(self, code: int, obj: Any) -> None:
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        from ..utils.http_json import DeepBacklogHTTPServer

        self._server = DeepBacklogHTTPServer((self.host, self.port),
                                             Handler)
        # port 0 → OS-assigned; resolve so callers see the bound port
        self.port = self._server.server_address[1]
        logging.info("openai-compatible endpoint on %s:%d (model=%s)",
                     self.host, self.port, self.model_name)
        if block:
            self._server.serve_forever()
        else:
            self._serve_thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name=f"openai-api-{self.port}")
            self._serve_thread.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            if self._serve_thread is not None:
                # reap the serve thread so stop() really means stopped
                self._serve_thread.join(timeout=5)
                self._serve_thread = None
            # shutdown() only stops the accept loop; the listening socket
            # stays bound until server_close() releases it
            self._server.server_close()
