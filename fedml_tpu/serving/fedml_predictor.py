"""FedMLPredictor — user-facing inference contract.

Capability parity: reference `serving/fedml_predictor.py:4-22` (ABC with
``predict``) used by the deploy plane's gateway.
"""

from __future__ import annotations

import abc
from typing import Any


class FedMLPredictor(abc.ABC):
    def __init__(self) -> None:
        pass

    @abc.abstractmethod
    def predict(self, request: Any) -> Any:
        """request: decoded JSON dict; returns a JSON-serializable response
        or a generator of chunks (streaming)."""

    def ready(self) -> bool:
        return True
