"""FedMLPredictor — user-facing inference contract.

Capability parity: reference `serving/fedml_predictor.py:4-22` (ABC with
``predict``) used by the deploy plane's gateway.
"""

from __future__ import annotations

import abc
from typing import Any


class FedMLPredictor(abc.ABC):
    def __init__(self) -> None:
        pass

    @abc.abstractmethod
    def predict(self, request: Any) -> Any:
        """request: decoded JSON dict; returns a JSON-serializable response
        or a generator of chunks (streaming)."""

    def ready(self) -> bool:
        return True


class LinearHeadPredictor(FedMLPredictor):
    """Linear head on flat input over a flat weight dict (`w2`/`b2` — the
    native edge layout). Shared by the model-card default predictor and the
    federated-serving plane."""

    def __init__(self, params: Any) -> None:
        import numpy as np

        self.params = {k: np.asarray(v) for k, v in dict(params).items()}

    def predict(self, request: Any) -> Any:
        import numpy as np

        x = np.asarray(request["inputs"], np.float32)
        x = x.reshape(x.shape[0], -1)
        logits = x @ self.params["w2"] + self.params.get("b2", 0.0)
        return {"predictions": np.argmax(logits, -1).tolist()}
