"""SLO-aware serving admission — shed at submit time, never collapse.

The training plane learned this in PR 5: screen updates BEFORE they
enter the funnel (`FedMLAggregator.add_local_trained_result` validates,
quarantines with a recorded reason, and re-solicits) instead of letting
a poisoned update corrupt the round.  The serving plane has the same
failure shape under overload: a closed admission policy ("accept
everything") turns an offered-load spike into unbounded queue growth —
every admitted request still completes, but TTFT grows without bound
and the p99 the SLO engine watches collapses for *all* traffic.

`ServingAdmissionController` is the serving-plane port of that idiom:
every `submit()` is screened against (a) a hard queue-depth bound and
(b) an estimated queue wait — pending depth over the measured completion
rate — against a TTFT budget.  A request that fails the screen is SHED:
its future resolves with `ShedError`, a `shed` lifecycle event lands in
the run ledger with the reason, `fedml_llm_shed_total{engine,reason}`
counts it, and the OpenAI surface maps it to HTTP 429 — so past
saturation the engine keeps bounded p99 for admitted requests while the
shed rate (not the latency) absorbs the excess.  Screening is O(1) per
submit and allocation-free on the admit path.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional, Tuple

from ..core.mlops.lock_profiler import named_lock


class ShedError(RuntimeError):
    """A request refused admission by the serving admission policy.

    Carries ``reason`` ("queue_full" / "ttft_budget") so surfaces can
    report *why* (the OpenAI API maps this to HTTP 429)."""

    def __init__(self, reason: str, detail: str = "") -> None:
        super().__init__(detail or reason)
        self.reason = reason


class ServingAdmissionController:
    """Screen serving submits against queue depth and a TTFT budget.

    * ``max_queue_depth`` — hard bound on requests waiting for a slot;
    * ``ttft_budget_s`` — shed when the estimated queue wait (pending
      depth / completion rate over ``window_s``) exceeds the budget.
      Cold start (no completions observed yet) admits: the estimate
      needs real signal before it is allowed to refuse traffic.

    The engine calls ``note_finish()`` on every retirement (finish OR
    cancel — both free a slot) to feed the completion-rate estimate.
    """

    def __init__(self, max_queue_depth: Optional[int] = None,
                 ttft_budget_s: Optional[float] = None,
                 window_s: float = 10.0) -> None:
        if max_queue_depth is None and ttft_budget_s is None:
            raise ValueError("admission controller needs max_queue_depth "
                             "and/or ttft_budget_s")
        self.max_queue_depth = None if max_queue_depth is None \
            else int(max_queue_depth)
        self.ttft_budget_s = None if ttft_budget_s is None \
            else float(ttft_budget_s)
        self.window_s = float(window_s)
        self._lock = named_lock("AdmissionController._lock")
        self._finish_ts: "collections.deque[float]" = collections.deque(
            maxlen=1024)
        self._shed = 0
        self._admitted = 0

    # -- signal --------------------------------------------------------------
    def note_finish(self) -> None:
        """One request retired (finished or cancelled) — a slot freed."""
        with self._lock:
            self._finish_ts.append(time.monotonic())

    def completion_rate(self) -> float:
        """Requests retired per second over the sliding window (0.0 until
        the first retirement ages into the window)."""
        now = time.monotonic()
        with self._lock:
            recent = [t for t in self._finish_ts
                      if now - t <= self.window_s]
            if len(recent) < 2:
                return 0.0
            span = max(now - recent[0], 1e-6)
            return len(recent) / span

    # -- the screen ----------------------------------------------------------
    def admit(self, queue_depth: int) -> Tuple[bool, Optional[str]]:
        """→ (admitted, shed_reason).  O(1), never raises."""
        if self.max_queue_depth is not None \
                and queue_depth >= self.max_queue_depth:
            with self._lock:
                self._shed += 1
            return False, "queue_full"
        if self.ttft_budget_s is not None:
            rate = self.completion_rate()
            if rate > 0.0 and queue_depth / rate > self.ttft_budget_s:
                with self._lock:
                    self._shed += 1
                return False, "ttft_budget"
        with self._lock:
            self._admitted += 1
        return True, None

    def stats(self) -> dict:
        with self._lock:
            shed, admitted = self._shed, self._admitted
        return {"shed": shed, "admitted": admitted,
                "completion_rate": self.completion_rate()}


def parse_admission(spec: Optional[str]
                    ) -> Optional[ServingAdmissionController]:
    """CLI-boundary parser (the `parse_wire_compression` idiom):
    ``"queue:64"`` | ``"ttft:0.5"`` | ``"queue:64,ttft:0.5"`` | ``"none"``
    → a controller (or None).  Raises ValueError on a malformed spec so
    bad flags die at startup, not mid-soak."""
    if spec is None or spec.strip().lower() in ("", "none", "off"):
        return None
    max_q: Optional[int] = None
    budget: Optional[float] = None
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            kind, _, val = part.partition(":")
            kind = kind.strip().lower()
            if kind == "queue":
                max_q = int(val)
                if max_q <= 0:
                    raise ValueError
            elif kind == "ttft":
                budget = float(val)
                if budget <= 0:
                    raise ValueError
            else:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"bad admission spec {part!r} (want 'queue:N' and/or "
                f"'ttft:SECONDS', e.g. 'queue:64,ttft:0.5')") from None
    return ServingAdmissionController(max_queue_depth=max_q,
                                      ttft_budget_s=budget)
