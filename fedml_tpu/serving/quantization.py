"""Int8 weight quantization for serving.

Single-token decode is weight-bandwidth-bound: every step streams the full
parameter set from HBM for one row of activations.  Storing matmul weights
as per-output-channel int8 halves the at-rest footprint vs bf16 (4x vs
f32) and bounds quantization error to the per-channel scale.  The dequant
(`int8 → f32 · scale`) runs inside the jitted step; realizing the full
bandwidth win additionally requires XLA to fuse the dequant into the
matmul operand read — when a profile shows it materializing the converted
matrix instead, the next step is an in-kernel dequant matmul per the
pallas quantization pattern (/opt/skills/guides/pallas_guide.md).

API: ``quantize_lm_params`` converts the functional-LM pytree
(`parallel.seq_parallel.init_lm_params` layout) into a quantized variant;
``QuantizedKVCacheLM`` is a drop-in `KVCacheLM` whose prefill/decode
dequantize on the fly.  Norm scales/biases and embeddings stay in f32
(embeddings are gathers, not matmuls, and norm params are tiny).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .kv_cache_lm import KVCacheLM

_MATMUL_KEYS = ("wq", "wk", "wv", "wo", "w1", "w2")


def quantize_matrix_int8(w: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """[in, out] → {"q": int8 [in, out], "s": f32 [out]} per-output-channel
    symmetric quantization."""
    s = jnp.max(jnp.abs(w), axis=0) / 127.0
    s = jnp.maximum(s, 1e-12)
    q = jnp.clip(jnp.round(w / s[None, :]), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s.astype(jnp.float32)}


def dequantize_matrix(qs: Dict[str, jnp.ndarray],
                      dtype=jnp.float32) -> jnp.ndarray:
    return qs["q"].astype(dtype) * qs["s"].astype(dtype)[None, :]


def quantize_lm_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize every transformer matmul weight; leave embeddings, position
    table, and layernorm params full-precision."""
    out = dict(params)
    out["blocks"] = []
    for blk in params["blocks"]:
        qblk = dict(blk)
        for k in _MATMUL_KEYS:
            qblk[k] = quantize_matrix_int8(blk[k])
        out["blocks"].append(qblk)
    return out


def _dequant_blocks(params: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(params)
    out["blocks"] = [
        {k: (dequantize_matrix(v) if k in _MATMUL_KEYS else v)
         for k, v in blk.items()}
        for blk in params["blocks"]]
    return out


class QuantizedKVCacheLM(KVCacheLM):
    """KVCacheLM over int8-quantized weights: same prefill/decode API, the
    dequant happens inside the jitted steps (fused into the matmuls by
    XLA), so HBM weight traffic is ~half of the bf16 baseline."""

    @classmethod
    def from_lm(cls, lm: KVCacheLM) -> "QuantizedKVCacheLM":
        return cls(quantize_lm_params(lm.params), lm.heads, lm.max_len)

    def prefill(self, tokens, length, max_len: int = -1):
        ml = self.max_len if max_len == -1 else max_len
        return _q_prefill(self.params, tokens, length, self.heads, ml)

    def decode(self, cache, token, pos):
        return _q_decode(self.params, cache, token, pos, self.heads)

    def decode_multi(self, cache, prompt_buf, prompt_n, pos0, temps,
                     top_k, top_p, rng, k: int,
                     exact_filters: bool = False):
        return _q_decode_multi(self.params, cache, prompt_buf, prompt_n,
                               pos0, temps, top_k, top_p, rng, self.heads,
                               k, exact_filters)

    def full_logits(self, tokens):
        return KVCacheLM(_dequant_blocks(self.params), self.heads,
                         self.max_len).full_logits(tokens)


@partial(jax.jit, static_argnames=("heads", "max_len"))
def _q_prefill(params, tokens, length, heads, max_len=0):
    from . import kv_cache_lm as _k

    return _k.prefill.__wrapped__(_dequant_blocks(params), tokens, length,
                                  heads, max_len)


@partial(jax.jit, static_argnames=("heads",), donate_argnums=(1,))
def _q_decode(params, cache, token, pos, heads):
    from . import kv_cache_lm as _k

    return _k.decode_step.__wrapped__(_dequant_blocks(params), cache, token,
                                      pos, heads)


@partial(jax.jit, static_argnames=("heads", "k", "exact_filters"),
         donate_argnums=(1,))
def _q_decode_multi(params, cache, prompt_buf, prompt_n, pos0, temps,
                    top_k, top_p, rng, heads, k, exact_filters=False):
    from . import kv_cache_lm as _k

    return _k.decode_multi.__wrapped__(_dequant_blocks(params), cache,
                                       prompt_buf, prompt_n, pos0, temps,
                                       top_k, top_p, rng, heads, k,
                                       exact_filters)
