"""Per-row KV-cache incremental decoding for the functional transformer LM.

The scalellm-equivalent engine (`llm_engine.py`) originally re-ran the full
window every token — O(T²) per sequence.  This module gives it the standard
TPU serving treatment (prefill/decode split, the vLLM/scalellm
architecture):

* ``prefill`` — one full forward over the prompt, returning the per-layer
  K/V cache rows and the next-token logits;
* ``decode_step`` — one token per row per call against the cache, with a
  PER-ROW position vector, so continuously-batched rows at different
  generation depths share one fixed-shape jitted step (flax's built-in
  decode cache keys on a single scalar index and cannot do this);
* ``KVCacheLM`` — stateless convenience wrapper holding params/config.

Model = `parallel.seq_parallel` functional LM (same params pytree, same
math; parity-tested token-for-token against the non-cached forward).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.seq_parallel import _ln, init_lm_params, lm_forward


def init_cache(params: Dict[str, Any], batch: int, max_len: int,
               heads: int) -> List[Dict[str, jnp.ndarray]]:
    dim = params["embed"].shape[1]
    dh = dim // heads
    dt = params["embed"].dtype        # bf16 params -> bf16 cache (an fp32
    # zero cache would silently promote every where-update to fp32,
    # doubling decode HBM traffic)
    return [{"k": jnp.zeros((batch, max_len, heads, dh), dt),
             "v": jnp.zeros((batch, max_len, heads, dh), dt)}
            for _ in params["blocks"]]


def _with_bias(z, blk, bkey):
    """Optional-bias add (imported HF checkpoints carry biases; native
    init is bias-free — same convention as `seq_parallel.lm_forward`)."""
    return z + blk[bkey] if bkey in blk else z


def _head(h, params):
    if "w_out" in params:                   # optional untied output head
        return h @ params["w_out"]
    return h @ params["embed"].T            # tied output embedding


def _qkv(y, blk, b, heads, dh):
    """Single-position q/k/v projections [B, H, Dh] (shared by both decode
    cores — keep the transformer math in ONE place)."""
    q = _with_bias(y @ blk["wq"], blk, "bq").reshape(b, heads, dh)
    k = _with_bias(y @ blk["wk"], blk, "bk").reshape(b, heads, dh)
    v = _with_bias(y @ blk["wv"], blk, "bv").reshape(b, heads, dh)
    return q, k, v


def _post_attention(h, o, blk, b, dim):
    """Output projection + residual + MLP half of a block (shared by both
    decode cores)."""
    h = h + _with_bias(o.reshape(b, dim) @ blk["wo"], blk, "bo")
    y = _ln(h, blk["ln2"])
    return h + _with_bias(
        jax.nn.gelu(_with_bias(y @ blk["w1"], blk, "b1")) @ blk["w2"],
        blk, "b2")


@partial(jax.jit, static_argnames=("heads", "max_len"))
def prefill(params: Dict[str, Any], tokens: jnp.ndarray,
            length: jnp.ndarray, heads: int, max_len: int = 0
            ) -> Tuple[List[Dict[str, jnp.ndarray]], jnp.ndarray]:
    """Full pass over padded prompts [B, T] (valid length per row) →
    (cache sized for ``max_len`` positions, logits at the last valid
    position).  ``max_len`` > T zero-pads the cache rows so decode_step can
    keep writing past the prompt width (JAX would otherwise drop the
    out-of-bounds scatter silently); 0 keeps the prompt width (only safe
    when the caller re-scatters into a full-size cache itself)."""
    b, t = tokens.shape
    if max_len and max_len < t:
        raise ValueError(f"prefill: max_len={max_len} < prompt width {t}")
    dim = params["embed"].shape[1]
    dh = dim // heads
    h = params["embed"][tokens] + params["pos"][:t][None]
    cache = []
    pos_ids = jnp.arange(t)
    for blk in params["blocks"]:
        y = _ln(h, blk["ln1"])

        def heads_of(w, bkey):
            z = y @ w
            if bkey in blk:      # optional biases (imported checkpoints)
                z = z + blk[bkey]
            return z.reshape(b, t, heads, dh)

        q = heads_of(blk["wq"], "bq").transpose(0, 2, 1, 3)
        k = heads_of(blk["wk"], "bk")
        v = heads_of(blk["wv"], "bv")
        if max_len and max_len > t:
            pad = ((0, 0), (0, max_len - t), (0, 0), (0, 0))
            cache.append({"k": jnp.pad(k, pad), "v": jnp.pad(v, pad)})
        else:
            cache.append({"k": k, "v": v})
        kt = k.transpose(0, 2, 1, 3)
        vt = v.transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kt) / np.sqrt(dh)
        causal = pos_ids[:, None] >= pos_ids[None, :]
        s = jnp.where(causal[None, None], s, -1e30)
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vt)
        h = h + _with_bias(
            o.transpose(0, 2, 1, 3).reshape(b, t, dim) @ blk["wo"],
            blk, "bo")
        y = _ln(h, blk["ln2"])
        h = h + _with_bias(
            jax.nn.gelu(_with_bias(y @ blk["w1"], blk, "b1")) @ blk["w2"],
            blk, "b2")
    h = _ln(h, params["ln_f"])
    logits = _head(h, params)                            # [B, T, V]
    last = jnp.take_along_axis(
        logits, (length - 1)[:, None, None], axis=1)[:, 0]
    return cache, last


def _decode_core(params: Dict[str, Any],
                 cache: List[Dict[str, jnp.ndarray]],
                 token: jnp.ndarray, pos: jnp.ndarray, heads: int
                 ) -> Tuple[List[Dict[str, jnp.ndarray]], jnp.ndarray]:
    """One token per row (traced body shared by the single- and multi-token
    dispatch entry points).

    The cache update is a broadcast-compare SELECT, not a scatter: a
    per-row ``.at[rows, pos].set`` lowers to an XLA scatter that measured
    2.9x slower than the select on v5e (21.9 vs 7.5 ms/step at B=32
    T=1024; a per-row dynamic_update_slice chain was just as slow —
    benchmarks/BENCH_NOTES.md round 4)."""
    b = token.shape[0]
    dim = params["embed"].shape[1]
    dh = dim // heads
    t_cache = cache[0]["k"].shape[1]
    h = params["embed"][token] + params["pos"][pos]       # [B, D]
    new_cache = []
    iota = jnp.arange(t_cache)
    hit = (iota[None, :] == pos[:, None])                 # [B, T]
    for blk, layer in zip(params["blocks"], cache):
        y = _ln(h, blk["ln1"])
        q, k_new, v_new = _qkv(y, blk, b, heads, dh)
        k_cache = jnp.where(hit[:, :, None, None], k_new[:, None],
                            layer["k"])
        v_cache = jnp.where(hit[:, :, None, None], v_new[:, None],
                            layer["v"])
        new_cache.append({"k": k_cache, "v": v_cache})
        s = jnp.einsum("bhd,bthd->bht", q, k_cache) / np.sqrt(dh)
        valid = (iota[None] <= pos[:, None])              # [B, T]
        s = jnp.where(valid[:, None, :], s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bht,bthd->bhd", w, v_cache)
        h = _post_attention(h, o, blk, b, dim)
    h = _ln(h, params["ln_f"])
    return new_cache, _head(h, params)                    # [B, V]


def _decode_core_chunked(params: Dict[str, Any],
                         cache: List[Dict[str, jnp.ndarray]],
                         kc: jnp.ndarray, vc: jnp.ndarray,
                         token: jnp.ndarray, pos0: jnp.ndarray,
                         j: jnp.ndarray, heads: int
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One token per row against a READ-ONLY full cache plus a small
    per-chunk K/V buffer (``kc``/``vc`` [L, B, K, H, Dh], written at inner
    step ``j``) — the flash-decoding split that lets `decode_multi` avoid
    rewriting the [B, T] cache every token.  Row i's absolute position is
    ``pos0[i] + j``; full-cache entries are valid strictly below ``pos0``
    (everything newer lives in the chunk buffer).  Returns the updated
    chunk buffers and the logits."""
    b = token.shape[0]
    dim = params["embed"].shape[1]
    dh = dim // heads
    t_cache = cache[0]["k"].shape[1]
    kcap = kc.shape[2]
    pos = pos0 + j
    h = params["embed"][token] + params["pos"][pos]       # [B, D]
    iota_t = jnp.arange(t_cache)
    iota_k = jnp.arange(kcap)
    valid_full = (iota_t[None] < pos0[:, None])           # [B, T]
    valid_chunk = (iota_k <= j)                           # [K]
    for li, (blk, layer) in enumerate(zip(params["blocks"], cache)):
        y = _ln(h, blk["ln1"])
        q, k_new, v_new = _qkv(y, blk, b, heads, dh)
        # uniform-position write: every row writes chunk slot j (cheap
        # contiguous dynamic_update_slice, no per-row scatter)
        kc = jax.lax.dynamic_update_slice(
            kc, k_new[None, :, None].astype(kc.dtype), (li, 0, j, 0, 0))
        vc = jax.lax.dynamic_update_slice(
            vc, v_new[None, :, None].astype(vc.dtype), (li, 0, j, 0, 0))
        s_full = jnp.einsum("bhd,bthd->bht", q, layer["k"]) / np.sqrt(dh)
        s_full = jnp.where(valid_full[:, None, :], s_full, -1e30)
        s_chunk = jnp.einsum("bhd,bkhd->bhk", q, kc[li]) / np.sqrt(dh)
        s_chunk = jnp.where(valid_chunk[None, None, :], s_chunk, -1e30)
        s = jnp.concatenate([s_full, s_chunk], axis=-1)   # [B, H, T+K]
        w = jax.nn.softmax(s, axis=-1)
        o = (jnp.einsum("bht,bthd->bhd", w[..., :t_cache], layer["v"])
             + jnp.einsum("bhk,bkhd->bhd", w[..., t_cache:], vc[li]))
        h = _post_attention(h, o, blk, b, dim)
    h = _ln(h, params["ln_f"])
    return kc, vc, _head(h, params)                       # [B, V]


@partial(jax.jit, static_argnames=("heads",), donate_argnums=(1,))
def decode_step(params: Dict[str, Any],
                cache: List[Dict[str, jnp.ndarray]],
                token: jnp.ndarray, pos: jnp.ndarray, heads: int
                ) -> Tuple[List[Dict[str, jnp.ndarray]], jnp.ndarray]:
    """One token per row: ``token`` [B] at per-row position ``pos`` [B].
    Writes this position's K/V into the cache and returns next logits."""
    return _decode_core(params, cache, token, pos, heads)


#: sampler candidate cap: top-k / nucleus filtering runs over the top
#: FILTER_CAP logits via `lax.top_k` instead of two full-vocab sorts (a
#: 50k-wide bitonic sort per token was a measurable share of the decode
#: step).  Vocabs <= the cap (all tests) are handled EXACTLY; for larger
#: vocabs, top_k is clamped to the cap and nucleus probabilities are
#: exact (full-vocab logsumexp) but the nucleus can keep at most the cap's
#: candidates — the same truncation every capped TPU sampler makes.
#: Rows with NO active filter (top_k=0, top_p>=1) bypass the cap entirely
#: and sample the full vocab.
FILTER_CAP = 128


def _filter_sample(logits: jnp.ndarray, temps: jnp.ndarray,
                   top_k: jnp.ndarray, top_p: jnp.ndarray,
                   key: jax.Array) -> jnp.ndarray:
    """Per-row greedy / temperature sampling with on-device top-k and
    nucleus filtering ([B, V] logits; top_k 0 = off, top_p 1 = off)."""
    b, v = logits.shape
    cap = min(FILTER_CAP, v)
    greedy = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(temps, 1e-6)[:, None]
    scaled = logits.astype(jnp.float32) / temp
    vals, idxs = jax.lax.top_k(scaled, cap)          # [B, cap] desc
    # exact per-candidate log-probs: normalize against the FULL vocab
    logz = jax.nn.logsumexp(scaled, axis=-1, keepdims=True)
    probs = jnp.exp(vals - logz)
    slot = jnp.arange(cap)[None]                     # [1, cap]
    # top-k: keep the first top_k slots (0 = off; clamped to the cap)
    k_active = top_k > 0
    kk = jnp.where(k_active, jnp.minimum(top_k, cap), cap)[:, None]
    keep = slot < kk
    # nucleus AFTER top-k, over the top-k-renormalized distribution (the
    # sequential-warper order of the host sampler / HF): a slot stays iff
    # the renormalized mass BEFORE it is < top_p; slot 0 always stays, so
    # top_p<=0 degenerates to keep-top-token exactly like _sample_token.
    # With top-k off, the below-cap tail mass still counts in the
    # denominator, so kept nucleus prefixes are exact (never too small).
    probs_k = probs * keep
    tail = jnp.where(k_active, 0.0,
                     jnp.maximum(1.0 - jnp.sum(probs, axis=-1), 0.0))
    z_k = jnp.sum(probs_k, axis=-1) + tail
    csum_before = (jnp.cumsum(probs_k, axis=-1) - probs_k) \
        / jnp.maximum(z_k, 1e-20)[:, None]
    p_active = (top_p < 1.0)[:, None]
    keep &= jnp.where(p_active,
                      (csum_before < jnp.minimum(top_p, 1.0)[:, None])
                      | (slot == 0),
                      True)
    masked = jnp.where(keep, vals, -jnp.inf)
    # ONE gumbel draw serves both paths (categorical == gumbel-argmax):
    # rows with BOTH filters off sample the FULL vocab (the cap only
    # applies when a filter is active — plain temperature sampling must
    # match the host sampler's distribution, tail included), filtered
    # rows argmax over the kept candidates using the SAME noise gathered
    # at their vocab positions
    gumbel = jax.random.gumbel(key, scaled.shape, scaled.dtype)
    plain = jnp.argmax(scaled + gumbel, axis=-1)
    g_at = jnp.take_along_axis(gumbel, idxs, axis=-1)        # [B, cap]
    choice = jnp.argmax(masked + g_at, axis=-1)              # [B] in slots
    filtered = jnp.take_along_axis(idxs, choice[:, None], axis=-1)[:, 0]
    filters_off = (~k_active) & (top_p >= 1.0)
    sampled = jnp.where(filters_off, plain, filtered)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


#: bisection depth for the exact sampler: threshold resolution is
#: (max-min scaled logit)/2^ITERS per row — 1e-6-ish for sane
#: temperatures, i.e. below float32 spacing of the log-probs involved
EXACT_FILTER_ITERS = 30


def _exact_filter_sample(logits: jnp.ndarray, temps: jnp.ndarray,
                         top_k: jnp.ndarray, top_p: jnp.ndarray,
                         key: jax.Array) -> jnp.ndarray:
    """EXACT full-vocab top-k / nucleus filtering (VERDICT r4 item 7).

    Instead of sorting the vocab (a 50k-wide bitonic sort per token) or
    truncating candidates at FILTER_CAP, find the per-row keep THRESHOLDS
    by bisection — each iteration is one [B, V] compare+reduce, so the
    cost is ~2*EXACT_FILTER_ITERS cheap passes and no sort at all:

    - top-k keeps ``logp >= t_k`` where t_k is the largest threshold with
      ``count(logp >= t_k) >= k`` (== the k-th largest value, exactly);
    - nucleus (after top-k renormalization, HF sequential-warper order)
      keeps ``logp >= t_p`` where t_p is the largest threshold whose kept
      mass reaches ``top_p`` — the minimal sorted prefix crossing top_p,
      i.e. the token that crosses the boundary is kept, like the capped
      path's ``csum_before < p`` rule.

    Deviation from a sorted implementation: EXACT float ties at either
    boundary are all kept (a sort would keep only the first by sort
    order) — measure-zero for real logits.  Rows with filters off sample
    the full vocab with the SAME gumbel draw as `_filter_sample`, so the
    two samplers are distribution-identical wherever both are exact.
    Tested against a numpy sorted-nucleus oracle at vocab 50257
    (tests/test_llm.py::test_exact_topp_*)."""
    keep, scaled, greedy = _exact_filter_keep(logits, temps, top_k, top_p)
    gumbel = jax.random.gumbel(key, scaled.shape, scaled.dtype)
    choice = jnp.argmax(jnp.where(keep, scaled + gumbel, -jnp.inf),
                        axis=-1)
    return jnp.where(temps > 0, choice, greedy).astype(jnp.int32)


def _exact_filter_keep(logits: jnp.ndarray, temps: jnp.ndarray,
                       top_k: jnp.ndarray, top_p: jnp.ndarray):
    """Bisected per-row keep mask for `_exact_filter_sample` (split out so
    tests can diff the SET against a numpy sorted-nucleus oracle)."""
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(temps, 1e-6)[:, None]
    scaled = logits.astype(jnp.float32) / temp
    logz = jax.nn.logsumexp(scaled, axis=-1, keepdims=True)
    logp = scaled - logz                                   # [B, V]
    hi0 = jnp.max(logp, axis=-1) + 1e-3
    lo0 = jnp.min(logp, axis=-1) - 1e-3

    k_active = top_k > 0
    kk = jnp.where(k_active, top_k, v).astype(jnp.float32)

    # invariant: count{>=lo} >= k >= count{>=hi} (hi above the max keeps
    # nothing; lo below the min keeps everything)
    def kbody(_, lh):
        lo, hi = lh
        mid = 0.5 * (lo + hi)
        cnt = jnp.sum(logp >= mid[:, None], axis=-1).astype(jnp.float32)
        ge = cnt >= kk
        return jnp.where(ge, mid, lo), jnp.where(ge, hi, mid)

    t_k, _ = jax.lax.fori_loop(0, EXACT_FILTER_ITERS, kbody, (lo0, hi0))
    keep = jnp.where(k_active[:, None], logp >= t_k[:, None], True)

    probs_k = jnp.where(keep, jnp.exp(logp), 0.0)          # [B, V]
    target = jnp.clip(top_p, 0.0, 1.0) * jnp.sum(probs_k, axis=-1)

    def pbody(_, lh):
        lo, hi = lh
        mid = 0.5 * (lo + hi)
        mass = jnp.sum(jnp.where(logp >= mid[:, None], probs_k, 0.0),
                       axis=-1)
        ge = mass >= target
        return jnp.where(ge, mid, lo), jnp.where(ge, hi, mid)

    t_p, _ = jax.lax.fori_loop(0, EXACT_FILTER_ITERS, pbody, (lo0, hi0))
    p_active = top_p < 1.0
    keep &= jnp.where(p_active[:, None], logp >= t_p[:, None], True)
    # the argmax token can never be filtered out (top_p <= 0 degenerates
    # to keep-top-token, matching _filter_sample's slot-0 rule)
    keep |= jax.nn.one_hot(greedy, v, dtype=bool)
    return keep, scaled, greedy


@partial(jax.jit, static_argnames=("heads", "k", "exact_filters"),
         donate_argnums=(1,))
def decode_multi(params: Dict[str, Any],
                 cache: List[Dict[str, jnp.ndarray]],
                 prompt_buf: jnp.ndarray, prompt_n: jnp.ndarray,
                 pos0: jnp.ndarray, temps: jnp.ndarray,
                 top_k: jnp.ndarray, top_p: jnp.ndarray, rng: jax.Array,
                 heads: int, k: int, exact_filters: bool = False):
    """k tokens per row in ONE dispatch, sampling on-device — the
    autoregressive loop never returns to the host mid-chunk (a ~k×
    dispatch-latency win on remote/tunneled accelerators, and no per-token
    host sync on local ones).

    ``prompt_buf`` [B, k]: tokens to teacher-force (chunked prefill);
    row i consumes ``prompt_n[i]`` of them, then switches to its own
    samples.  ``temps`` [B]: 0 → greedy, else temperature sampling with
    per-row on-device top-k / nucleus filtering (`_filter_sample`).
    Returns (cache, emitted [B, k]) where emitted[i, j] is the model output
    after feeding inner token j — new tokens from j = prompt_n[i]-1 on.

    The inner scan never rewrites the [B, T] cache: new K/V land in a
    [L, B, k] chunk buffer (`_decode_core_chunked`) and are written back
    ONCE after the scan — without this the per-token full-cache rewrite
    made the step ~3x slower than its HBM read floor (BENCH_NOTES r4)."""
    b = prompt_buf.shape[0]
    nl = len(params["blocks"])
    dim = params["embed"].shape[1]
    dh = dim // heads
    dt = cache[0]["k"].dtype
    kc0 = jnp.zeros((nl, b, k, heads, dh), dt)
    vc0 = jnp.zeros((nl, b, k, heads, dh), dt)

    # scan carries the "next token to feed" per row + the chunk buffers
    def step(carry, j):
        kc, vc, tok, rng = carry
        kc, vc, logits = _decode_core_chunked(params, cache, kc, vc, tok,
                                              pos0, j, heads)
        rng, sub = jax.random.split(rng)
        # static switch: exact_filters=True routes through the full-vocab
        # bisection sampler (needed only when vocab > FILTER_CAP and a
        # request's nucleus/top-k could exceed the cap; the engine picks
        # per dispatch, so unfiltered batches never pay for it)
        sampler = _exact_filter_sample if exact_filters else _filter_sample
        out_tok = sampler(logits, temps, top_k, top_p, sub)
        # next inner step feeds the prompt while any remains, else out_tok
        nxt = jnp.where(j + 1 < prompt_n,
                        prompt_buf[jnp.arange(b),
                                   jnp.minimum(j + 1, k - 1)],
                        out_tok)
        return (kc, vc, nxt, rng), out_tok

    carry0 = (kc0, vc0, prompt_buf[:, 0], rng)
    (kc, vc, _, _), emitted = jax.lax.scan(step, carry0, jnp.arange(k))

    # write the chunk back into the persistent cache: full-cache position
    # iota maps to chunk slot iota - pos0[i] for iota in [pos0, pos0+k)
    t_cache = cache[0]["k"].shape[1]
    iota = jnp.arange(t_cache)
    hit = ((iota[None] >= pos0[:, None])
           & (iota[None] < pos0[:, None] + k))            # [B, T]
    slot = jnp.clip(iota[None] - pos0[:, None], 0, k - 1)  # [B, T]
    out_cache = []
    for li, layer in enumerate(cache):
        kf = jnp.take_along_axis(kc[li], slot[:, :, None, None], axis=1)
        vf = jnp.take_along_axis(vc[li], slot[:, :, None, None], axis=1)
        out_cache.append({
            "k": jnp.where(hit[:, :, None, None], kf, layer["k"]),
            "v": jnp.where(hit[:, :, None, None], vf, layer["v"]),
        })
    return out_cache, emitted.T                            # [B, k]


class KVCacheLM:
    """Decode-oriented LM handle for the batched engine: owns params and
    config, exposes prefill/decode with per-row positions."""

    def __init__(self, params: Dict[str, Any], heads: int,
                 max_len: int) -> None:
        self.params = params
        self.heads = int(heads)
        self.max_len = int(max_len)
        self.vocab = int(params["embed"].shape[0])

    @classmethod
    def create(cls, rng: jax.Array, vocab: int, dim: int = 64,
               layers: int = 2, heads: int = 4,
               max_len: int = 256) -> "KVCacheLM":
        return cls(init_lm_params(rng, vocab, dim=dim, layers=layers,
                                  heads=heads, max_len=max_len),
                   heads, max_len)

    def init_cache(self, batch: int):
        return init_cache(self.params, batch, self.max_len, self.heads)

    def prefill(self, tokens, length, max_len: int = -1):
        """max_len -1 → this LM's configured max_len (safe default: cache
        rows are sized so decode can continue past the prompt)."""
        ml = self.max_len if max_len == -1 else max_len
        return prefill(self.params, tokens, length, self.heads, ml)

    def decode(self, cache, token, pos):
        return decode_step(self.params, cache, token, pos, self.heads)

    def decode_multi(self, cache, prompt_buf, prompt_n, pos0, temps,
                     top_k, top_p, rng, k: int,
                     exact_filters: bool = False):
        return decode_multi(self.params, cache, prompt_buf, prompt_n, pos0,
                            temps, top_k, top_p, rng, self.heads, k,
                            exact_filters)

    def full_logits(self, tokens):
        """Non-cached forward (parity reference / tests)."""
        from ..parallel.ring_attention import reference_attention

        return lm_forward(self.params, tokens, self.heads,
                          partial(reference_attention, causal=True))


def kv_lm_from_checkpoint(path: str, heads: int,
                          max_len: Optional[int] = None,
                          schema: str = "auto") -> "KVCacheLM":
    """Serve an imported checkpoint (npz/safetensors, native or GPT-2
    naming) through the KV-cache engine — the deploy half of the
    reference's fine-tune → checkpoint → serve path
    (`train/llm/train_utils.py:196-244` + `device_model_deployment.py`).
    Heads are validated against the checkpoint dims; ``max_len`` defaults
    to the checkpoint's position-table length."""
    from ..train.llm.weight_import import (
        import_lm_weights,
        validate_lm_shapes,
    )

    params, _report = import_lm_weights(path, schema=schema)
    validate_lm_shapes(params, heads=heads)
    if max_len is None:
        max_len = int(params["pos"].shape[0])
    return KVCacheLM(params, heads, int(max_len))
