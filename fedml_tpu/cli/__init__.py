from .cli import cli, main

__all__ = ["cli", "main"]
